"""Chaos soak (ISSUE 6 acceptance): continuous publish/acquire traffic
while faults fire — kills, injected raises/delays, dropped frames.

Invariants asserted, per the acceptance criteria:

- **No committed generation is ever lost**: every version the publisher
  committed stays readable until superseded, and every acquired state dict
  is internally consistent (one version's weights, never a mix).
- **Self-healing without operator intervention**: the dead volume is
  quarantined by the health supervisor and its keys re-replicated with NO
  ``ts.repair()`` call anywhere in this file.
- **Zero client-visible get errors after failover**: transient internal
  retries are fine (counted in metrics), but no acquire/get ever raises.

The deterministic subset runs in tier-1; the long randomized soak is
``slow``-marked.
"""

import asyncio
import time

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.strategy import LocalRankStrategy


@pytest.fixture
def fast_health(monkeypatch):
    monkeypatch.setenv("TORCHSTORE_TPU_HEALTH_INTERVAL_S", "0.25")
    monkeypatch.setenv("TORCHSTORE_TPU_HEALTH_MISS_THRESHOLD", "2")


def _state_dict(version: int, keys: int = 4, numel: int = 1024) -> dict:
    # Every tensor carries the version as its fill value: an acquired dict
    # mixing generations is detected by a single np.unique.
    return {
        f"w{i}": np.full(numel, float(version), np.float32)
        for i in range(keys)
    }


def _assert_consistent(sd: dict, version: int) -> None:
    for key, arr in sd.items():
        vals = np.unique(np.asarray(arr))
        assert vals.size == 1, f"{key} mixes generations: {vals}"
        assert vals[0] == float(version), (
            f"{key} holds generation {vals[0]}, acquired version {version}"
        )


async def _kill_volume(store_name: str, volume_id: str) -> None:
    from torchstore_tpu import api

    client = ts.client(store_name)
    vmap = await client.controller.get_volume_map.call_one()
    target = vmap[volume_id]["ref"]
    handle = api._stores[store_name]
    for mesh in [handle.volume_mesh, *(handle.repair_meshes or [])]:
        if mesh is None:
            continue
        for idx, ref in enumerate(mesh.refs):
            if (ref.host, ref.port, ref.name) == (
                target.host,
                target.port,
                target.name,
            ):
                proc = mesh._processes[idx]
                proc.kill()
                proc.join(5)
                return
    raise AssertionError(f"no process found for volume {volume_id!r}")


async def _run_chaos(
    store_name: str,
    versions: int,
    chaos,
    publish_interval: float = 0.0,
) -> dict:
    """Publish ``versions`` versions while an acquire loop drains them and
    ``chaos(version)`` fires scheduled faults; returns a report. Publish
    and acquire run CONCURRENTLY — the fault schedule interleaves with live
    traffic, not between safely-quiesced iterations."""
    publisher = ts.WeightPublisher("chaos", store_name=store_name, keep=3)
    subscriber = ts.WeightSubscriber("chaos", store_name=store_name)
    report = {
        "published": [],
        "acquired": [],
        "publish_errors": [],
        "acquire_errors": [],
    }
    done = asyncio.Event()

    async def publish_loop():
        try:
            for v in range(versions):
                await chaos(v)
                if v % 3 == 2:
                    # Every third version publishes LAYER-STREAMED (one
                    # fragment per key): the chaos schedule interleaves
                    # with watermarked partial versions, and the barrier
                    # acquire loop must still never see them unsealed.
                    cs = publisher.stream()
                    for key, arr in _state_dict(v).items():
                        await cs.put({key: arr})
                    version = await cs.seal()
                else:
                    version = await publisher.publish(_state_dict(v))
                report["published"].append(version)
                if publish_interval:
                    await asyncio.sleep(publish_interval)
        except BaseException as exc:  # noqa: BLE001 - reported by the test
            report["publish_errors"].append(repr(exc))
            raise
        finally:
            done.set()

    async def acquire_loop():
        try:
            while not (
                done.is_set() and subscriber.last_version == versions - 1
            ):
                try:
                    sd, version = await asyncio.wait_for(
                        subscriber.acquire(timeout=30.0), timeout=60.0
                    )
                except (TimeoutError, asyncio.TimeoutError):
                    if done.is_set():
                        return  # publisher finished; nothing more is coming
                    raise
                _assert_consistent(sd, version)
                report["acquired"].append(version)
        except asyncio.CancelledError:
            raise
        except BaseException as exc:  # noqa: BLE001 - reported by the test
            # Recorded so the zero-client-visible-errors assertion is
            # checked against what actually happened, not an always-empty
            # list (the raw raise alone would fail the gather, but a later
            # refactor that swallows it must not turn the assert vacuous).
            report["acquire_errors"].append(repr(exc))
            raise

    pub_task = asyncio.ensure_future(publish_loop())
    acq_task = asyncio.ensure_future(acquire_loop())
    try:
        await asyncio.wait_for(
            asyncio.gather(pub_task, acq_task), timeout=240.0
        )
    finally:
        for task in (pub_task, acq_task):
            if not task.done():
                task.cancel()
        await asyncio.gather(pub_task, acq_task, return_exceptions=True)
    return report


async def test_chaos_deterministic_kill_and_reconverge(fast_health):
    """Kill one of three volumes mid-traffic: publishes and acquires keep
    succeeding, the supervisor quarantines + auto-repairs, and the fleet
    reconverges to full replication — no ts.repair() anywhere."""
    await ts.initialize(
        num_storage_volumes=3,
        strategy=LocalRankStrategy(replication=2),
        store_name="chaos_kill",
    )
    victim = {}
    try:
        client = ts.client("chaos_kill")
        await client._ensure_setup()

        async def chaos(version: int):
            if version == 6:
                # Kill a volume that demonstrably holds channel data.
                located = await client.controller.locate_volumes.call_one(
                    ["chaos/v5/w0"]
                )
                victim["vid"] = sorted(located["chaos/v5/w0"])[0]
                await _kill_volume("chaos_kill", victim["vid"])

        report = await _run_chaos("chaos_kill", versions=18, chaos=chaos)
        assert report["publish_errors"] == []
        assert report["acquire_errors"] == []
        assert report["published"] == list(range(18))
        # The subscriber may skip versions (acquire-latest semantics) but
        # must end on the final one with zero errors.
        assert report["acquired"][-1] == 17
        # Self-healing: quarantined without intervention. Bounded wait —
        # the run can outpace the supervisor's miss window (streamed
        # publishes shortened the post-kill phase below 2 x 0.25 s).
        deadline = time.monotonic() + 30.0
        while True:
            vh = await ts.volume_health("chaos_kill")
            if vh[victim["vid"]]["state"] == "quarantined":
                break
            assert time.monotonic() < deadline, f"never quarantined: {vh}"
            await asyncio.sleep(0.1)
        # ...and the LAST version's keys reconverged to 2 healthy replicas.
        deadline = time.monotonic() + 30.0
        keys = [f"chaos/v17/w{i}" for i in range(4)]
        while True:
            located = await client.controller.locate_volumes.call_one(keys)
            placements = {k: set(located[k]) for k in keys}
            if all(
                victim["vid"] not in p and len(p) == 2
                for p in placements.values()
            ):
                break
            assert time.monotonic() < deadline, (
                f"fleet did not reconverge: {placements}"
            )
            await asyncio.sleep(0.3)
        # Committed data still correct after reconvergence.
        final = await ts.get_state_dict("chaos/v17", store_name="chaos_kill")
        _assert_consistent(final, 17)
    finally:
        await ts.shutdown("chaos_kill")


async def test_chaos_deterministic_fault_schedule(fast_health):
    """A scheduled mix of raise + delay faults on the volume data plane
    fires inside live publish/acquire traffic; the unified retry absorbs
    every one (publish and acquire both see zero errors)."""
    await ts.initialize(
        num_storage_volumes=2,
        strategy=LocalRankStrategy(replication=2),
        store_name="chaos_sched",
    )
    try:

        async def chaos(version: int):
            if version == 3:
                await ts.inject_fault(
                    "volume.put", "raise", count=1, scope="volumes",
                    store_name="chaos_sched",
                )
            elif version == 6:
                await ts.inject_fault(
                    "volume.get", "raise", count=2, scope="volumes",
                    store_name="chaos_sched",
                )
            elif version == 9:
                await ts.inject_fault(
                    "volume.handshake", "delay", count=2, delay_ms=150,
                    store_name="chaos_sched",
                )
            elif version == 7:
                # Watermark application delayed INSIDE the controller's
                # notify: committed streamed bytes stay invisible to
                # streaming readers for 150 ms (they keep long-polling);
                # version 8 is a streamed publish, so this fires mid-
                # stream under live acquire traffic.
                await ts.inject_fault(
                    "channel.watermark", "delay", count=2, delay_ms=150,
                    scope="controller", store_name="chaos_sched",
                )
            elif version == 10:
                # One-sided bracket held open mid-landing: entry stamps
                # stay visibly odd, concurrent one-sided readers fall back
                # to the RPC path — acquire must still see zero errors and
                # never a mixed-generation state dict.
                await ts.inject_fault(
                    "shm.landing_stamp", "delay", count=2, delay_ms=200,
                    store_name="chaos_sched",
                )

        report = await _run_chaos("chaos_sched", versions=12, chaos=chaos)
        assert report["publish_errors"] == []
        assert report["acquire_errors"] == []
        assert report["acquired"][-1] == 11
        final = await ts.get_state_dict("chaos/v11", store_name="chaos_sched")
        _assert_consistent(final, 11)
        await ts.clear_faults(store_name="chaos_sched")
    finally:
        await ts.shutdown("chaos_sched")


async def test_chaos_wedged_stream_publisher_never_mixes(fast_health):
    """A publisher WEDGED mid-stream (channel.publish_layer faultpoint)
    provably never yields a mixed-generation acquire: barrier subscribers
    keep getting the previous sealed version, a streaming subscriber
    serves only the wedged stream's committed prefix and then times out
    (never returns a dict), and a resumed publisher reclaims the partial
    before republishing the same version number."""
    await ts.initialize(
        num_storage_volumes=2,
        strategy=LocalRankStrategy(replication=2),
        store_name="chaos_wedge",
    )
    try:
        pub = ts.WeightPublisher("chaos", store_name="chaos_wedge", keep=3)
        sub = ts.WeightSubscriber("chaos", store_name="chaos_wedge")
        # Healthy streamed v0.
        cs = pub.stream()
        for key, arr in _state_dict(0).items():
            await cs.put({key: arr})
        assert await cs.seal() == 0
        sd, version = await sub.acquire(timeout=30)
        assert version == 0
        _assert_consistent(sd, 0)
        # v1: two layers land, then the publisher wedges on the third
        # (client-scope faultpoint — the publisher lives in this process).
        cs1 = pub.stream()
        sd1 = _state_dict(1)
        keys = sorted(sd1)
        await cs1.put({keys[0]: sd1[keys[0]]})
        await cs1.put({keys[1]: sd1[keys[1]]})
        await ts.inject_fault(
            "channel.publish_layer", "wedge", count=1, scope="client",
            store_name="chaos_wedge",
        )

        async def wedged_rest():
            for key in keys[2:]:
                await cs1.put({key: sd1[key]})
            await cs1.seal()

        wedged = asyncio.ensure_future(wedged_rest())
        await asyncio.sleep(0.3)
        assert not wedged.done()
        # Barrier subscriber joining now: v0, fully consistent — the
        # wedged partial v1 is invisible.
        sub2 = ts.WeightSubscriber("chaos", store_name="chaos_wedge")
        sd, version = await sub2.acquire(timeout=15)
        assert version == 0
        _assert_consistent(sd, 0)
        # Streaming subscriber: serves ONLY the committed prefix of v1
        # (each layer individually consistent at generation 1), then times
        # out — it never returns a state dict, mixed or otherwise.
        served = []
        sub3 = ts.WeightSubscriber("chaos", store_name="chaos_wedge")
        with pytest.raises((TimeoutError, asyncio.TimeoutError)):
            await sub3.acquire_streamed(
                on_layer=lambda fk, v: served.append((fk, float(v[0]))),
                timeout=3,
            )
        assert set(k for k, _ in served) <= set(keys[:2])
        assert all(val == 1.0 for _, val in served)
        # The wedged task never completes inside this test: cancel it
        # (the crash), clear faults, resume with a fresh publisher.
        wedged.cancel()
        await asyncio.gather(wedged, return_exceptions=True)
        await ts.clear_faults(store_name="chaos_wedge")
        pub2 = ts.WeightPublisher("chaos", store_name="chaos_wedge", keep=3)
        version = await pub2.publish(_state_dict(1))
        assert version == 1  # partial v1 reclaimed, number reused
        sd, version = await sub2.acquire(timeout=30)
        assert version == 1
        _assert_consistent(sd, 1)
    finally:
        await ts.clear_faults(store_name="chaos_wedge")
        await ts.shutdown("chaos_wedge")


async def test_chaos_wedged_delta_publisher_never_mixes_or_drifts(fast_health):
    """ISSUE-13 chaos fold-in: a DELTA publisher wedged mid-version
    (channel.publish_layer) leaves barrier readers on the previous sealed
    version; the resumed publisher (fresh process = no baselines)
    re-KEYFRAMES, and readers converge on bit-exact weights — zero
    mixed-generation or drifted reads, asserted through the stream
    record's watermarks (inconsistent_keys) and a byte-level compare
    against the publisher's baseline. A scheduled channel.delta_baseline
    raise also proves baseline loss surfaces loudly mid-traffic."""
    from torchstore_tpu import stream_sync

    await ts.initialize(
        num_storage_volumes=2,
        strategy=LocalRankStrategy(replication=2),
        store_name="chaos_delta",
    )
    try:
        pub = ts.WeightPublisher(
            "dchan", store_name="chaos_delta", keep=5,
            transfer_quant="int8_block", delta=True, keyframe_every=4,
        )
        sub = ts.WeightSubscriber("dchan", store_name="chaos_delta")
        w = {f"w{i}": np.random.randn(512).astype(np.float32) for i in range(4)}

        async def stream_publish():
            cs = pub.stream()
            for key in sorted(w):
                await cs.put({key: w[key]})
            return await cs.seal()

        def assert_exact(sd):
            for key in w:
                base = pub._codec.entries[key]["baseline"]
                got = sub._delta_decoder().state[key]["blocks"]
                np.testing.assert_array_equal(got, base)
                tol = np.abs(w[key]).max() / 127 + 1e-6
                np.testing.assert_allclose(sd[key], w[key], atol=tol)

        # Healthy streamed delta v0 (keyframes) + v1 (sparse deltas).
        assert await stream_publish() == 0
        sd, v = await sub.acquire(timeout=30)
        assert v == 0
        assert_exact(sd)
        for key in list(w)[:1]:
            w[key][:64] += 0.1
        assert await stream_publish() == 1
        sd, v = await sub.acquire(timeout=30)
        assert v == 1
        assert_exact(sd)

        # v2 wedges after two layers (client-scope: publisher is local).
        keys = sorted(w)
        w[keys[0]][:64] += 0.1
        cs2 = pub.stream()
        await cs2.put({keys[0]: w[keys[0]]})
        await cs2.put({keys[1]: w[keys[1]]})
        await ts.inject_fault(
            "channel.publish_layer", "wedge", count=1, scope="client",
            store_name="chaos_delta",
        )

        async def wedged_rest():
            for key in keys[2:]:
                await cs2.put({key: w[key]})
            await cs2.seal()

        wedged = asyncio.ensure_future(wedged_rest())
        await asyncio.sleep(0.3)
        assert not wedged.done()
        # Barrier join mid-wedge: previous sealed version, consistent
        # watermarks for everything it serves.
        sub2 = ts.WeightSubscriber("dchan", store_name="chaos_delta")
        sd2, v2 = await sub2.acquire(timeout=15)
        assert v2 == 1
        state1 = await ts.client("chaos_delta").stream_state("dchan/v1")
        served_sks = [f"dchan/v1/{k}" for k in keys]
        assert stream_sync.inconsistent_keys(
            state1, served_sks, state1["version"]
        ) == []
        # Crash the wedged publisher; a RESUMED publisher has no baselines
        # and must re-keyframe (never delta over a lost baseline).
        wedged.cancel()
        await asyncio.gather(wedged, return_exceptions=True)
        await ts.clear_faults(store_name="chaos_delta")
        pub2 = ts.WeightPublisher(
            "dchan", store_name="chaos_delta", keep=5,
            transfer_quant="int8_block", delta=True, keyframe_every=4,
        )
        version = await pub2.publish(w)
        assert version == 2  # partial v2 reclaimed, number reused
        info = ts.state_dict_utils.parse_quant_blob(
            await ts.client("chaos_delta").get(f"dchan/v2/{keys[0]}")
        )
        assert info["flags"] & ts.state_dict_utils._FLAG_KEYFRAME
        sd, v = await sub.acquire(timeout=30)
        assert v == 2
        for key in w:
            tol = np.abs(w[key]).max() / 127 + 1e-6
            np.testing.assert_allclose(sd[key], w[key], atol=tol)
            np.testing.assert_array_equal(
                sub._delta_decoder().state[key]["blocks"],
                pub2._codec.entries[key]["baseline"],
            )
        # Scheduled baseline-loss injection: the next delta publish fails
        # LOUDLY at the faultpoint instead of shipping anything stale.
        await ts.inject_fault(
            "channel.delta_baseline", "raise", count=1, scope="client",
            store_name="chaos_delta",
        )
        w[keys[0]][:64] += 0.1
        from torchstore_tpu.faults import FaultInjectedError

        with pytest.raises(FaultInjectedError):
            await pub2.publish(w)
        await ts.clear_faults(store_name="chaos_delta")
        version = await pub2.publish(w)
        sd, v = await sub.acquire(timeout=30)
        assert v == version
        for key in w:
            tol = np.abs(w[key]).max() / 127 + 1e-6
            np.testing.assert_allclose(sd[key], w[key], atol=tol)
    finally:
        await ts.clear_faults(store_name="chaos_delta")
        await ts.shutdown("chaos_delta")


async def test_chaos_tiered_cohorts_kill_mid_spill_and_fault_in(
    fast_health, monkeypatch, tmp_path
):
    """ISSUE 12 acceptance: 3 cohorts pinned to 3 different versions read
    concurrently while the publisher advances LATEST and the spill writer
    runs — zero mixed-generation reads, no pinned version GC'd or
    spilled-then-lost while leased. The chaos schedule kills a volume
    MID-SPILL (``volume.spill`` die) and injects ``volume.fault_in``
    raises mid-promotion: pinned cohorts reconverge through replica
    failover + auto-repair with NO ``ts.repair()`` anywhere."""
    monkeypatch.setenv("TORCHSTORE_TPU_TIER_ENABLED", "1")
    monkeypatch.setenv("TORCHSTORE_TPU_TIER_DIR", str(tmp_path / "tier"))
    # Tiny budget: the working set crosses the HIGH watermark after a few
    # versions, so every sweep below actually demotes.
    monkeypatch.setenv("TORCHSTORE_TPU_TIER_BUDGET_BYTES", str(48 * 1024))
    monkeypatch.setenv("TORCHSTORE_TPU_TIER_HIGH_PCT", "0.5")
    monkeypatch.setenv("TORCHSTORE_TPU_TIER_LOW_PCT", "0.25")
    # Deterministic: the test drives its own sweeps.
    monkeypatch.setenv("TORCHSTORE_TPU_TIER_SWEEP_INTERVAL_S", "0")
    await ts.initialize(
        num_storage_volumes=3,
        strategy=LocalRankStrategy(replication=2),
        store_name="chaos_tier",
    )
    pins = {"rollout-v0": 0, "eval-v1": 1, "canary-v2": 2}
    report = {"pinned_reads": 0, "pinned_errors": [], "sweep_rounds": 0}
    stop = asyncio.Event()
    victim = {}
    try:
        client = ts.client("chaos_tier")
        await client._ensure_setup()
        pub = ts.WeightPublisher("chaos", store_name="chaos_tier", keep=3)
        for v in range(3):
            await pub.publish(_state_dict(v))
        leases = {
            cohort: await client.lease_acquire(
                cohort, "chaos", v, ttl_s=300
            )
            for cohort, v in pins.items()
        }
        assert all(le["resident_keys"] > 0 for le in leases.values())

        async def cohort_loop(cohort: str, version: int):
            sub = ts.WeightSubscriber(
                "chaos", store_name="chaos_tier", cohort=cohort
            )
            try:
                while not stop.is_set():
                    sd, got = await sub.acquire(version=version)
                    assert got == version
                    _assert_consistent(sd, version)
                    report["pinned_reads"] += 1
                    await asyncio.sleep(0.05)
            except BaseException as exc:  # noqa: BLE001 - reported below
                report["pinned_errors"].append(f"{cohort}: {exc!r}")
                raise

        async def sweep_loop():
            while not stop.is_set():
                await ts.tier_sweep("chaos_tier")
                report["sweep_rounds"] += 1
                await asyncio.sleep(0.15)

        async def publish_loop():
            try:
                for v in range(3, 11):
                    if v == 5:
                        # Kill ONE data-holding volume mid-spill: the die
                        # fires inside the next sweep's spill pass, after
                        # the demotion decision, before the crash-safe
                        # disk write commits.
                        located = await client.controller.locate_volumes.call_one(
                            ["chaos/v3/w0"]
                        )
                        victim["vid"] = sorted(located["chaos/v3/w0"])[0]
                        await ts.inject_fault(
                            "volume.spill", "die", count=1,
                            scope=victim["vid"], store_name="chaos_tier",
                        )
                    if v == 8:
                        # Fault-in raises mid-promotion: pinned reads of
                        # spilled versions retry/fail over, never error.
                        # Armed per SURVIVING volume (the mid-spill victim
                        # is already dead and cannot answer the inject).
                        for vid in sorted(client._volume_refs):
                            if vid == victim.get("vid"):
                                continue
                            await ts.inject_fault(
                                "volume.fault_in", "raise", count=2,
                                scope=vid, store_name="chaos_tier",
                            )
                    await pub.publish(_state_dict(v))
                    await asyncio.sleep(0.1)
            finally:
                stop.set()

        tasks = [
            asyncio.ensure_future(cohort_loop(c, v))
            for c, v in pins.items()
        ]
        tasks.append(asyncio.ensure_future(sweep_loop()))
        pub_task = asyncio.ensure_future(publish_loop())
        await asyncio.wait_for(pub_task, timeout=120.0)
        await asyncio.wait_for(
            asyncio.gather(*tasks, return_exceptions=False), timeout=60.0
        )
        assert report["pinned_errors"] == []
        assert report["pinned_reads"] >= 3 * 3  # every cohort read repeatedly
        assert report["sweep_rounds"] > 0
        # No pinned version was GC'd while leased (keep=3 advanced the
        # cutoff far past all three), and every pinned read still serves.
        for cohort, v in pins.items():
            assert await client.keys(f"chaos/v{v}") != [], f"v{v} reaped"
            sd, _ = await ts.WeightSubscriber(
                "chaos", store_name="chaos_tier", cohort=cohort
            ).acquire(version=v)
            _assert_consistent(sd, v)
        # An UNLEASED mid-run version was reaped as usual (leases pin,
        # they don't disable GC).
        assert await client.keys("chaos/v4") == []
        catalog = await ts.version_catalog("chaos", store_name="chaos_tier")
        for cohort, v in pins.items():
            assert cohort in [
                le["cohort"] for le in catalog["chaos"][v]["leases"]
            ]
        # The mid-spill kill was detected and the fleet self-healed — the
        # dead volume is quarantined, no ts.repair() anywhere in this test.
        deadline = time.monotonic() + 30.0
        while True:
            vh = await ts.volume_health("chaos_tier")
            if vh[victim["vid"]]["state"] == "quarantined":
                break
            assert time.monotonic() < deadline, f"never quarantined: {vh}"
            await asyncio.sleep(0.1)
        for cohort, lease in leases.items():
            await client.lease_release(lease["lease_id"])
    finally:
        stop.set()
        await ts.clear_faults(store_name="chaos_tier")
        await ts.shutdown("chaos_tier")


@pytest.mark.slow
async def test_chaos_soak_randomized(fast_health):
    """Long randomized soak: probabilistic raise/delay faults armed across
    the fleet plus a mid-run volume kill, under sustained publish/acquire
    traffic. Same invariants as the deterministic subset, at scale."""
    await ts.initialize(
        num_storage_volumes=3,
        strategy=LocalRankStrategy(replication=2),
        store_name="chaos_soak",
    )
    try:
        client = ts.client("chaos_soak")
        await client._ensure_setup()
        await ts.inject_fault(
            "volume.get", "raise", prob=0.05, scope="volumes",
            store_name="chaos_soak",
        )
        await ts.inject_fault(
            "volume.put", "delay", prob=0.1, delay_ms=50,
            store_name="chaos_soak",
        )
        killed = {}

        async def chaos(version: int):
            if version == 20:
                vmap = await client.controller.get_volume_map.call_one()
                killed["vid"] = sorted(vmap)[-1]
                await _kill_volume("chaos_soak", killed["vid"])

        report = await _run_chaos(
            "chaos_soak", versions=60, chaos=chaos, publish_interval=0.05
        )
        assert report["publish_errors"] == []
        assert report["acquire_errors"] == []
        assert report["published"] == list(range(60))
        assert report["acquired"][-1] == 59
        vh = await ts.volume_health("chaos_soak")
        assert vh[killed["vid"]]["state"] == "quarantined"
        final = await ts.get_state_dict("chaos/v59", store_name="chaos_soak")
        _assert_consistent(final, 59)
    finally:
        await ts.clear_faults(store_name="chaos_soak")
        await ts.shutdown("chaos_soak")


# --------------------------------------------------------------------------
# control plane (ISSUE 16): volume dies mid-migration; reshard under traffic
# --------------------------------------------------------------------------


async def _seed_hot_key(store_name: str, rng_fill: float = 1.0) -> dict:
    """Committed baseline: one 32 KB key re-put hot plus four quiet 2 KB
    keys; returns ``{key: expected array}`` for loss checks."""
    expected = {}
    hot = np.full(8192, rng_fill, np.float32)  # 32 KB
    for _ in range(8):
        await ts.put("ctl/hot", hot, store_name=store_name)
    expected["ctl/hot"] = hot
    for i in range(4):
        arr = np.full(512, float(i), np.float32)  # 2 KB
        await ts.put(f"ctl/quiet{i}", arr, store_name=store_name)
        expected[f"ctl/quiet{i}"] = arr
    return expected


async def _assert_no_loss(store_name: str, expected: dict) -> None:
    for key, want in expected.items():
        got = await ts.get(key, store_name=store_name)
        np.testing.assert_array_equal(np.asarray(got), want)


async def test_chaos_volume_dies_mid_migration(fast_health, monkeypatch):
    """A volume dies while an engine-driven migration is copying onto it
    (``control.migrate`` delay faultpoint holds the copy open): the
    action fails LOUDLY — an ``error``/``abandoned`` decision outcome,
    never a silent half-move — no committed generation is lost, and
    concurrent reads stay consistent throughout. A plain injected raise
    at the same faultpoint is checked first (the cheap determinism)."""
    monkeypatch.setenv("TORCHSTORE_TPU_CONTROL_MIN_WINDOW_BYTES", "1024")
    monkeypatch.setenv("TORCHSTORE_TPU_CONTROL_HOT_KEY_MIN_BYTES", "4096")
    monkeypatch.setenv("TORCHSTORE_TPU_CONTROL_COOLDOWN_S", "0.2")
    await ts.initialize(
        num_storage_volumes=3,
        strategy=LocalRankStrategy(replication=2),
        store_name="chaos_ctl",
    )
    try:
        expected = await _seed_hot_key("chaos_ctl")
        plan = await ts.control_plan("chaos_ctl")
        moves = [
            a
            for a in plan["actions"]
            if a["kind"] in ("migrate_key", "split_hot_key")
        ]
        assert moves, f"policy saw no hot key: {plan}"
        assert moves[0]["subject"] == "ctl/hot"

        # Leg 1: the copy path raises at the faultpoint — the round
        # continues, the outcome says error, nothing is lost.
        await ts.inject_fault(
            "control.migrate", "raise", count=1, scope="controller",
            store_name="chaos_ctl",
        )
        rep = await ts.rebalance("chaos_ctl")
        outcomes = [a["outcome"] for a in rep["actions"]]
        assert any(o.startswith("error:") for o in outcomes), outcomes
        await _assert_no_loss("chaos_ctl", expected)

        # Leg 2: hold the NEXT migration open long enough to kill its
        # destination volume under it, with a live read loop running.
        await asyncio.sleep(0.3)  # let the failed subject's cooldown lapse
        for _ in range(4):  # refresh the rolling window
            await ts.put("ctl/hot", expected["ctl/hot"], store_name="chaos_ctl")
        plan = await ts.control_plan("chaos_ctl")
        moves = [
            a
            for a in plan["actions"]
            if a["kind"] in ("migrate_key", "split_hot_key")
        ]
        assert moves, f"policy went quiet after the failed round: {plan}"
        dst = moves[0]["dst_volume"]
        await ts.inject_fault(
            "control.migrate", "delay", count=1, delay_ms=1200,
            scope="controller", store_name="chaos_ctl",
        )
        reb_task = asyncio.ensure_future(ts.rebalance("chaos_ctl"))
        await asyncio.sleep(0.3)
        await _kill_volume("chaos_ctl", dst)
        read_errors = []
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            try:
                await _assert_no_loss("chaos_ctl", expected)
            except Exception as exc:  # noqa: BLE001 - asserted below
                read_errors.append(repr(exc))
                break
            await asyncio.sleep(0.1)
        rep = await asyncio.wait_for(reb_task, timeout=60.0)
        assert read_errors == []
        by_subject = {
            a["subject"]: a["outcome"]
            for a in rep["actions"]
            if a["kind"] in ("migrate_key", "split_hot_key")
        }
        # The move onto the dead volume must NOT report applied — it
        # failed loudly and the decision audit says so.
        assert "ctl/hot" in by_subject, rep["actions"]
        assert not by_subject["ctl/hot"].startswith("applied"), by_subject
        assert by_subject["ctl/hot"].split(":")[0] in ("error", "abandoned")
        # Zero committed-generation loss once the dust settles (the dead
        # volume only ever held a second replica or the aborted copy).
        await _assert_no_loss("chaos_ctl", expected)

        # The reconcile-entry faultpoint is live too: an injected raise
        # fails the manual trigger LOUDLY (no silent empty round).
        await ts.inject_fault(
            "control.reconcile", "raise", count=1, scope="controller",
            store_name="chaos_ctl",
        )
        with pytest.raises(Exception, match="control.reconcile"):
            await ts.rebalance("chaos_ctl")
    finally:
        await ts.clear_faults(store_name="chaos_ctl")
        await ts.shutdown("chaos_ctl")


async def test_chaos_reshard_under_live_traffic(fast_health):
    """Runtime elastic resharding (``ts.rebalance(shards=2)``) under a
    concurrent get loop: zero lost keys, zero failed client ops — stale-
    topology errors are absorbed by the metadata router's reload+retry."""
    await ts.initialize(
        num_storage_volumes=2,
        store_name="chaos_reshard",
    )
    try:
        expected = {}
        for i in range(24):
            arr = np.full(256, float(i), np.float32)
            await ts.put(f"rk/{i:02d}", arr, store_name="chaos_reshard")
            expected[f"rk/{i:02d}"] = arr
        stop = asyncio.Event()
        read_errors: list[str] = []
        reads = {"n": 0}

        async def read_loop():
            keys = sorted(expected)
            while not stop.is_set():
                key = keys[reads["n"] % len(keys)]
                try:
                    got = await ts.get(key, store_name="chaos_reshard")
                    np.testing.assert_array_equal(
                        np.asarray(got), expected[key]
                    )
                except Exception as exc:  # noqa: BLE001 - asserted below
                    read_errors.append(f"{key}: {exc!r}")
                    return
                reads["n"] += 1
                await asyncio.sleep(0)

        reader = asyncio.ensure_future(read_loop())
        try:
            summary = await asyncio.wait_for(
                ts.rebalance("chaos_reshard", shards=2), timeout=120.0
            )
            assert summary["shards"] == 2 and summary["was"] == 1
            assert summary["keys"] == len(expected), summary
            # Writes keep landing on the NEW plane too.
            extra = np.full(256, 99.0, np.float32)
            await ts.put("rk/post", extra, store_name="chaos_reshard")
            expected["rk/post"] = extra
            await asyncio.sleep(0.2)
        finally:
            stop.set()
            await asyncio.wait_for(reader, timeout=30.0)
        assert read_errors == []
        assert reads["n"] > 0  # the loop demonstrably overlapped the swap
        await _assert_no_loss("chaos_reshard", expected)
    finally:
        await ts.shutdown("chaos_reshard")


# ---------------------------------------------------------------------------
# ISSUE 18: elastic fleet + cold tier under fire
# ---------------------------------------------------------------------------


@pytest.fixture
def elastic_chaos_env(monkeypatch):
    """Second-scale autoscale thresholds (1 s ledger windows, 1 idle
    round, 1-key drain quanta) with auto-repair off so the fleet size is
    exactly what the scale engine decides."""
    monkeypatch.setenv("TORCHSTORE_TPU_AUTOSCALE_IDLE_ROUNDS", "1")
    monkeypatch.setenv("TORCHSTORE_TPU_AUTOSCALE_COOLDOWN_S", "0.2")
    monkeypatch.setenv("TORCHSTORE_TPU_AUTOSCALE_DRAIN_KEYS_PER_ROUND", "1")
    monkeypatch.setenv("TORCHSTORE_TPU_LEDGER_WINDOW_S", "1")
    monkeypatch.setenv("TORCHSTORE_TPU_AUTO_REPAIR", "0")


async def _drain_started(store_name: str, rounds: int = 30) -> str:
    """Run autoscale rounds until some volume is marked draining; returns
    its id (the drain stays mid-flight: 1-key quanta)."""
    client = ts.client(store_name)
    for _ in range(rounds):
        await asyncio.sleep(0.5)
        await ts.autoscale(store_name=store_name)
        vmap = await client.controller.get_volume_map.call_one()
        for vid, info in vmap.items():
            if info.get("health") == "draining":
                return vid
    raise AssertionError(f"no drain started after {rounds} rounds: {vmap}")


async def test_chaos_volume_killed_mid_drain(fast_health, elastic_chaos_env):
    """ISSUE 18 leg 1: the drain victim dies with entries still resident.
    The injected-raise determinism check runs first (an ``autoscale.drain``
    raise surfaces as an ``error:`` outcome, never a silent round); then
    the real kill — the health loop quarantines the dark volume, the
    drain is ABANDONED loudly (``drain_abandoned`` health event), later
    autoscale rounds neither wedge nor plan for the corpse, and zero
    committed generations are lost (the survivor holds every replica)."""
    await ts.initialize(
        num_storage_volumes=2,
        strategy=LocalRankStrategy(replication=2),
        store_name="chaos_drain",
    )
    try:
        expected = await _seed_hot_key("chaos_drain")
        victim = await _drain_started("chaos_drain")

        # Leg 1 (determinism): a raise at the faultpoint fails the action
        # loudly; the round reports it and continues.
        await ts.inject_fault(
            "autoscale.drain", "raise", count=1, scope="controller",
            store_name="chaos_drain",
        )
        rep = await ts.autoscale(store_name="chaos_drain")
        outcomes = [a["outcome"] for a in rep["actions"]]
        assert any(o.startswith("error:") for o in outcomes), outcomes
        await _assert_no_loss("chaos_drain", expected)

        # Leg 2: kill the half-drained victim for real.
        await _kill_volume("chaos_drain", victim)
        client = ts.client("chaos_drain")
        gone = False
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            rep = await ts.autoscale(store_name="chaos_drain")  # never wedges
            vmap = await client.controller.get_volume_map.call_one()
            state = vmap.get(victim, {}).get("health")
            if state in (None, "quarantined"):
                gone = True
                break
            await asyncio.sleep(0.3)
        assert gone, f"victim {victim} never quarantined: {vmap}"

        record = await ts.flight_record(store_name="chaos_drain")
        assert any(
            e.get("kind") == "health"
            and e.get("name") == f"drain_abandoned/{victim}"
            for e in record["events"]
        ), "drain abandonment was silent"
        # Post-abandon rounds plan nothing for the corpse.
        rep = await ts.autoscale(store_name="chaos_drain")
        assert all(a["subject"] != victim for a in rep["actions"]), rep
        await _assert_no_loss("chaos_drain", expected)
    finally:
        await ts.clear_faults(store_name="chaos_drain")
        await ts.shutdown("chaos_drain")


async def test_chaos_spawn_fault_fails_loudly(fast_health, monkeypatch):
    """A raise at ``autoscale.spawn`` aborts the spawn batch: the round
    still reports the deferred scale-out decision, ``spawned`` stays
    empty, nothing leaks — and the NEXT round (fault budget spent, fresh
    traffic) completes the scale-out it owed."""
    from torchstore_tpu import faults

    monkeypatch.setenv("TORCHSTORE_TPU_AUTOSCALE_OUT_WINDOW_BYTES", "4096")
    monkeypatch.setenv("TORCHSTORE_TPU_AUTOSCALE_COOLDOWN_S", "0.2")
    monkeypatch.setenv("TORCHSTORE_TPU_AUTOSCALE_MAX_VOLUMES", "2")
    monkeypatch.setenv("TORCHSTORE_TPU_LEDGER_WINDOW_S", "30")
    await ts.initialize(store_name="chaos_spawn")
    try:
        hot = np.arange(4096, dtype=np.float32)
        for i in range(4):
            await ts.put(f"s{i}", hot + i, store_name="chaos_spawn")
        faults.arm("autoscale.spawn", "raise", count=1)  # spawns run HERE
        try:
            rep = await ts.autoscale(store_name="chaos_spawn")
        finally:
            faults.disarm("autoscale.spawn")
        assert rep["spawned"] == [], rep
        assert any(
            a["kind"] == "scale_out" and a["outcome"].startswith("deferred")
            for a in rep["actions"]
        ), rep["actions"]
        await asyncio.sleep(0.4)  # cooldown; windows stay hot (30 s)
        rep = await ts.autoscale(store_name="chaos_spawn")
        assert rep["spawned"] == ["scale-0"], rep
        client = ts.client("chaos_spawn")
        vmap = await client.controller.get_volume_map.call_one()
        assert "scale-0" in vmap
        for i in range(4):
            got = await ts.get(f"s{i}", store_name="chaos_spawn")
            np.testing.assert_array_equal(np.asarray(got), hot + i)
    finally:
        await ts.shutdown("chaos_spawn")


async def test_chaos_kill_all_volumes_cold_restore(
    fast_health, monkeypatch, tmp_path
):
    """ISSUE 18 leg 2, the scale-to-zero acceptance: checkpoint the fleet
    into the blob tier, KILL every volume process (not a graceful stop),
    cold-start a brand-new fleet, ``ts.blob_restore()`` — every committed
    key comes back byte-identical. A ``blob.io`` raise injected into the
    restore path must surface in ``failed``, never as silent loss."""
    monkeypatch.setenv("TORCHSTORE_TPU_BLOB_ENABLED", "1")
    monkeypatch.setenv("TORCHSTORE_TPU_BLOB_DIR", str(tmp_path / "coldblob"))
    monkeypatch.setenv("TORCHSTORE_TPU_AUTO_REPAIR", "0")
    expected = {
        f"ck/{i}": np.arange(700, dtype=np.float32) * (i + 1)
        for i in range(5)
    }
    await ts.initialize(num_storage_volumes=2, store_name="chaos_cold")
    try:
        for key, arr in expected.items():
            await ts.put(key, arr, store_name="chaos_cold")
        rep = await ts.blob_checkpoint(store_name="chaos_cold")
        assert rep["keys"] == len(expected) and not rep["errors"], rep
        client = ts.client("chaos_cold")
        vmap = await client.controller.get_volume_map.call_one()
        for vid in sorted(vmap):
            await _kill_volume("chaos_cold", vid)
    finally:
        await ts.shutdown("chaos_cold")
        ts.reset_client()

    await ts.initialize(num_storage_volumes=2, store_name="chaos_cold2")
    try:
        from torchstore_tpu import faults

        # The restore's blob reads run in THIS process: an injected I/O
        # raise fails the restore LOUDLY (here on the very first blob op,
        # the manifest read) — never a quietly partial fleet.
        faults.arm("blob.io", "raise", count=1)
        try:
            with pytest.raises(faults.FaultInjectedError):
                await ts.blob_restore(store_name="chaos_cold2")
        finally:
            faults.disarm("blob.io")
        rep = await ts.blob_restore(store_name="chaos_cold2")
        assert rep["restored"] == len(expected), rep
        assert not rep["failed"], rep
        for key, arr in expected.items():
            got = await ts.get(key, store_name="chaos_cold2")
            np.testing.assert_array_equal(np.asarray(got), arr)
    finally:
        await ts.shutdown("chaos_cold2")
