"""Bulk transport tests: forced-bulk integration, promote-on-success cache
semantics, abort on failure, registration cache weakref eviction, large
transfers (reference tests/test_torchcomms_transport.py +
test_rdma_memory_cache.py)."""

import asyncio
import gc

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.transport.bulk import BulkClientCache, BulkTransportBuffer
from torchstore_tpu.transport.cache import ArrayRegistrationCache


class TestRegistrationCache:
    def test_hit_keyed_by_ptr_and_size(self):
        cache = ArrayRegistrationCache()
        a = np.ones(16, np.float32)
        r1 = cache.register(a)
        r2 = cache.register(a)
        assert r1 is r2 and len(cache) == 1

    def test_weakref_eviction(self):
        # Plain ndarrays aren't weakref-able; subclasses (and jax buffers)
        # are — eviction fires when the owner dies.
        class Weakable(np.ndarray):
            pass

        cache = ArrayRegistrationCache()
        a = np.ones(16, np.float32).view(Weakable)
        cache.register(a)
        assert len(cache) == 1
        del a
        gc.collect()
        assert len(cache) == 0

    def test_view_keeps_registration_alive(self):
        class Weakable(np.ndarray):
            pass

        cache = ArrayRegistrationCache()
        a = np.ones(16, np.float32).view(Weakable)
        view = a[:4]
        cache.register(a)
        del a
        gc.collect()
        assert len(cache) == 1  # view keeps the owner alive
        del view
        gc.collect()
        assert len(cache) == 0

    def test_fifo_bound_for_plain_arrays(self):
        cache = ArrayRegistrationCache(maxsize=4)
        keep = [np.ones(i + 1, np.float32) for i in range(8)]
        for a in keep:
            cache.register(a)
        assert len(cache) == 4

    def test_clear(self):
        cache = ArrayRegistrationCache()
        cache.register(np.ones(4))
        cache.clear()
        assert len(cache) == 0


@pytest.fixture
async def store():
    await ts.initialize(
        store_name="blk",
        strategy=ts.SingletonStrategy(default_transport_type="bulk"),
    )
    yield "blk"
    await ts.shutdown("blk")


async def test_forced_bulk_roundtrip(store):
    x = np.random.rand(64, 64).astype(np.float32)
    await ts.put("w", x, store_name=store)
    np.testing.assert_array_equal(await ts.get("w", store_name=store), x)


async def test_objects_and_tensors_mixed_batch(store):
    await ts.put_batch(
        {"t": np.arange(8.0), "o": {"cfg": True}, "t2": np.ones((3, 3))},
        store_name=store,
    )
    out = await ts.get_batch({"t": None, "o": None, "t2": None}, store_name=store)
    np.testing.assert_array_equal(out["t"], np.arange(8.0))
    assert out["o"] == {"cfg": True}


async def test_connection_promoted_and_reused(store):
    client = ts.client(store)
    await client.put("a", np.ones(4))
    cache = client._ctx.get_cache(BulkClientCache)
    assert len(cache.connections) == 1
    conn = next(iter(cache.connections.values()))
    await client.put("b", np.ones(4))
    await client.get("a")
    # Same connection object survived across requests.
    assert next(iter(cache.connections.values())) is conn


async def test_large_tensor_bulk(store):
    x = np.random.rand(2048, 1024).astype(np.float32)  # 8 MB, > chunk
    await ts.put("big", x, store_name=store)
    out = await ts.get("big", store_name=store)
    np.testing.assert_array_equal(out, x)


async def test_concurrent_bulk_ops(store):
    async def one(i):
        x = np.full((256,), float(i), np.float32)
        await ts.put(f"c/{i}", x, store_name=store)
        out = await ts.get(f"c/{i}", store_name=store)
        np.testing.assert_array_equal(out, x)

    await asyncio.gather(*(one(i) for i in range(8)))


async def test_failed_put_does_not_poison_cache(store):
    client = ts.client(store)
    await client.put("good", np.ones(4))  # promote a connection
    # A put that fails server-side (type confusion) after bytes were sent.
    with pytest.raises(ValueError, match="already stored"):
        await client.put("good", {"now": "object"})
    # The promoted connection still works for subsequent ops.
    np.testing.assert_array_equal(await client.get("good"), np.ones(4))
    await client.put("after", np.full(2, 5.0))
    np.testing.assert_array_equal(await client.get("after"), np.full(2, 5.0))


async def test_sharded_reshard_over_bulk(store):
    jax = pytest.importorskip("jax")
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    g = np.arange(64.0, dtype=np.float32).reshape(8, 8)
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("x", "y"))
    await ts.put(
        "s", jax.device_put(g, NamedSharding(mesh, P("x", "y"))), store_name=store
    )
    like = jax.device_put(
        np.zeros_like(g),
        NamedSharding(Mesh(np.array(jax.devices()).reshape(2, 4), ("a", "b")), P("b", "a")),
    )
    out = await ts.get("s", like=like, store_name=store)
    np.testing.assert_array_equal(np.asarray(out), g)


async def test_inplace_bulk_get(store):
    x = np.arange(12.0).reshape(3, 4)
    await ts.put("x", x, store_name=store)
    dest = np.zeros((3, 4))
    out = await ts.get("x", like=dest, store_name=store)
    assert out is dest
    np.testing.assert_array_equal(dest, x)


# --------------------------------------------------------------------------
# striping (VERDICT r1 item 6: large transfers across parallel connections)
# --------------------------------------------------------------------------


@pytest.fixture
async def bulk_store():
    await ts.initialize(
        store_name="stripe",
        strategy=ts.SingletonStrategy(default_transport_type="bulk"),
    )
    yield "stripe"
    await ts.shutdown("stripe")


async def test_striped_put_get_roundtrip(bulk_store):
    """>64MB payloads stripe across extra connections in BOTH directions;
    content must round-trip exactly (chunks reassembled by offset)."""
    x = (np.arange(24 * 1024 * 1024, dtype=np.float32)).reshape(4096, 6144)
    x[0, 0] = 7.5  # 96 MB
    await ts.put("big", x, store_name=bulk_store)
    cache = ts.client(bulk_store)._ctx.get_cache(BulkClientCache)
    assert any(cache.stripe_conns.values())  # striping actually engaged
    out = await ts.get("big", store_name=bulk_store)
    np.testing.assert_array_equal(out, x)
    # In-place destination: stripes recv() straight into the buffer.
    dest = np.zeros_like(x)
    out2 = await ts.get("big", like=dest, store_name=bulk_store)
    assert out2 is dest
    np.testing.assert_array_equal(dest, x)


async def test_striped_cross_host_emulation():
    """Emulated cross-host DCN: volumes bind 0.0.0.0 and advertise a
    non-loopback-resolved name; a striped transfer rides the bulk path."""
    import os

    os.environ["TORCHSTORE_TPU_BIND_HOST"] = "0.0.0.0"
    os.environ["TORCHSTORE_TPU_ADVERTISE_HOST"] = "127.0.0.1"
    try:
        await ts.initialize(
            store_name="dcnstripe",
            strategy=ts.SingletonStrategy(default_transport_type="bulk"),
        )
        try:
            x = np.random.rand(3072, 8192).astype(np.float32)  # 96 MB
            await ts.put("w", x, store_name="dcnstripe")
            out = await ts.get("w", store_name="dcnstripe")
            np.testing.assert_array_equal(out, x)
        finally:
            await ts.shutdown("dcnstripe")
    finally:
        del os.environ["TORCHSTORE_TPU_BIND_HOST"]
        del os.environ["TORCHSTORE_TPU_ADVERTISE_HOST"]


async def test_small_transfers_not_striped(bulk_store):
    x = np.random.rand(1024).astype(np.float32)
    await ts.put("small", x, store_name=bulk_store)
    np.testing.assert_array_equal(
        await ts.get("small", store_name=bulk_store), x
    )
    cache = ts.client(bulk_store)._ctx.get_cache(BulkClientCache)
    assert not any(cache.stripe_conns.values())
