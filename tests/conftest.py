"""Test configuration: force JAX onto a virtual 8-device CPU platform so
sharding tests run anywhere (the driver's multi-chip dry-run uses the same
mechanism). Must run before jax is imported anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# On this image a sitecustomize force-sets jax_platforms="axon,cpu" (real TPU
# tunnel), overriding the env var — override it back at config level.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass

# Children spawned by the actor runtime inherit these so any jax import in a
# storage-volume process also lands on CPU.
os.environ.setdefault("TORCHSTORE_TPU_TEST_MODE", "1")

import pytest


@pytest.fixture
def anyio_backend():
    # pytest-asyncio isn't in this image; async tests run via anyio's plugin
    # in auto mode (see pyproject.toml) on the stdlib asyncio backend.
    return "asyncio"
