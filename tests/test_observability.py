"""Observability subsystem: registry semantics, exporters, span tracing,
end-to-end metric emission through a real store, and the regression tests
for the carried ADVICE fixes that the new gauges made assertable."""

import json

import numpy as np
import pytest

from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import tracing


# --------------------------------------------------------------------------
# registry semantics
# --------------------------------------------------------------------------


class TestRegistry:
    def test_counter_inc_and_labels(self):
        r = obs_metrics.MetricsRegistry()
        c = r.counter("ops_total", "ops")
        c.inc()
        c.inc(4, op="put")
        c.inc(op="put")
        assert c.value() == 1
        assert c.value(op="put") == 5
        assert c.total() == 6

    def test_counter_rejects_decrease(self):
        c = obs_metrics.MetricsRegistry().counter("c")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge_set_inc_dec(self):
        g = obs_metrics.MetricsRegistry().gauge("g")
        g.set(10, volume="0")
        g.inc(5, volume="0")
        g.dec(3, volume="0")
        assert g.value(volume="0") == 12
        assert g.value(volume="1") == 0

    def test_histogram_buckets_cumulative(self):
        h = obs_metrics.MetricsRegistry().histogram(
            "h", buckets=(0.1, 1.0, 10.0)
        )
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        val = h.value()
        assert val["count"] == 5
        assert val["sum"] == pytest.approx(56.05)
        assert val["buckets"]["0.1"] == 1
        assert val["buckets"]["1.0"] == 3
        assert val["buckets"]["10.0"] == 4
        assert val["buckets"]["+Inf"] == 5

    def test_histogram_boundary_is_le(self):
        # Prometheus semantics: an observation equal to a bound lands IN
        # that bucket (le = less-than-or-equal).
        h = obs_metrics.MetricsRegistry().histogram("h", buckets=(1.0, 2.0))
        h.observe(1.0)
        assert h.value()["buckets"]["1.0"] == 1

    def test_get_or_create_idempotent_and_type_checked(self):
        r = obs_metrics.MetricsRegistry()
        assert r.counter("x") is r.counter("x")
        with pytest.raises(TypeError):
            r.gauge("x")

    def test_reset_zeroes_but_keeps_instruments(self):
        r = obs_metrics.MetricsRegistry()
        c = r.counter("c")
        c.inc(7)
        r.reset()
        assert c.value() == 0
        c.inc()  # the cached instrument object still feeds the registry
        assert r.snapshot()["c"]["series"][0]["value"] == 1


# --------------------------------------------------------------------------
# exporters
# --------------------------------------------------------------------------


class TestExporters:
    def _registry(self):
        r = obs_metrics.MetricsRegistry()
        r.counter("reqs_total", "requests").inc(3, op="put", transport="shm")
        r.gauge("resident_bytes").set(4096, volume="0")
        h = r.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        return r

    def test_snapshot_is_json_serializable_and_shaped(self):
        snap = self._registry().snapshot()
        json.dumps(snap)  # fully serializable
        assert snap["reqs_total"]["kind"] == "counter"
        series = snap["reqs_total"]["series"][0]
        assert series["labels"] == {"op": "put", "transport": "shm"}
        assert series["value"] == 3
        hist = snap["lat_seconds"]["series"][0]["value"]
        assert hist["count"] == 2 and hist["buckets"]["+Inf"] == 2

    def test_render_json_envelope(self):
        doc = json.loads(self._registry().render_json())
        assert {"ts", "pid", "metrics"} <= set(doc)
        assert doc["metrics"]["resident_bytes"]["series"][0]["value"] == 4096

    def test_render_prometheus_format(self):
        text = self._registry().render_prometheus()
        assert "# TYPE reqs_total counter" in text
        assert 'reqs_total{op="put",transport="shm"} 3' in text
        assert 'resident_bytes{volume="0"} 4096' in text
        # Histogram: cumulative le buckets + sum + count.
        assert 'lat_seconds_bucket{le="0.1"} 1' in text
        assert 'lat_seconds_bucket{le="1.0"} 2' in text
        assert 'lat_seconds_bucket{le="+Inf"} 2' in text
        assert "lat_seconds_count 2" in text

    def test_prometheus_escapes_label_values(self):
        r = obs_metrics.MetricsRegistry()
        r.counter("c").inc(key='we"ird\nkey')
        text = r.render_prometheus()
        assert r'we\"ird\nkey' in text

    def test_dump_metrics_writes_file(self, tmp_path):
        path = str(tmp_path / "m.json")
        obs_metrics.counter("ts_dump_probe_total").inc()
        written = obs_metrics.dump_metrics(path)
        assert written == path
        doc = json.loads(open(path).read())
        assert "ts_dump_probe_total" in doc["metrics"]

    def test_dump_metrics_prom_extension(self, tmp_path):
        path = str(tmp_path / "m.prom")
        obs_metrics.counter("ts_dump_probe_total").inc()
        assert obs_metrics.dump_metrics(path) == path
        assert "# TYPE ts_dump_probe_total counter" in open(path).read()


# --------------------------------------------------------------------------
# span tracing
# --------------------------------------------------------------------------


class TestSpans:
    def _swap_path(self, collector, path):
        old = collector.path
        collector.path = path
        return old

    def test_span_nesting_and_flush(self, tmp_path):
        collector = tracing.collector()
        path = str(tmp_path / "trace.json")
        old = self._swap_path(collector, path)
        try:
            with tracing.span("outer", key="k", nbytes=1000):
                with tracing.span("inner", coords=(0, 1)):
                    pass
            collector.flush()
        finally:
            collector.path = old
        content = open(path).read()
        data = json.loads(
            content if content.rstrip().endswith("]") else content + "\n]"
        )
        by_name = {e["name"]: e for e in data}
        assert {"outer", "inner"} <= set(by_name)
        outer, inner = by_name["outer"], by_name["inner"]
        # Complete events with derived throughput + stringified attrs.
        assert outer["ph"] == "X" and outer["args"]["bytes"] == 1000
        assert "GBps" in outer["args"]
        assert inner["args"]["coords"] == "(0, 1)"
        # Nesting: inner is contained within outer on the same thread.
        assert inner["tid"] == outer["tid"]
        assert inner["ts"] >= outer["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3

    def test_span_records_error_class(self, tmp_path):
        collector = tracing.collector()
        path = str(tmp_path / "trace.json")
        old = self._swap_path(collector, path)
        try:
            with pytest.raises(RuntimeError):
                with tracing.span("boom"):
                    raise RuntimeError("x")
            collector.flush()
        finally:
            collector.path = old
        content = open(path).read()
        data = json.loads(content + "\n]")
        # Files lead with a process_name metadata event now — find the span.
        (boom,) = [e for e in data if e["name"] == "boom"]
        assert boom["args"]["error"] == "RuntimeError"

    def test_span_disabled_is_noop(self):
        collector = tracing.collector()
        old = self._swap_path(collector, None)
        try:
            with tracing.span("nothing", key="k"):
                pass
            assert collector.events == []
        finally:
            collector.path = old


# --------------------------------------------------------------------------
# end-to-end: a put/get round trip feeds the registry and the trace
# --------------------------------------------------------------------------


@pytest.mark.anyio
async def test_round_trip_increments_expected_metrics(tmp_path):
    import torchstore_tpu as ts

    collector = tracing.collector()
    trace_path = str(tmp_path / "trace.json")
    old_path = collector.path
    collector.path = trace_path

    reg = obs_metrics.get_registry()
    ops = reg.counter("ts_client_ops_total")
    tbytes = reg.counter("ts_transport_bytes_total")
    ops0_put = ops.value(op="put")
    ops0_get = ops.value(op="get")
    put_bytes0 = tbytes.value(transport="shm", op="put")
    get_bytes0 = tbytes.value(transport="shm", op="get")
    try:
        await ts.initialize(
            store_name="obs_e2e",
            strategy=ts.SingletonStrategy(default_transport_type="shm"),
        )
        try:
            arr = np.arange(2048, dtype=np.float32)
            await ts.put("obs/k", arr, store_name="obs_e2e")
            out = await ts.get("obs/k", store_name="obs_e2e")
            np.testing.assert_array_equal(np.asarray(out), arr)
            del out  # release the zero-copy view before shutdown

            snap = ts.metrics_snapshot()
            # Logical client ops counted once per op.
            assert ops.value(op="put") == ops0_put + 1
            assert ops.value(op="get") == ops0_get + 1
            # Nonzero per-transport byte counters, both directions.
            assert (
                tbytes.value(transport="shm", op="put") - put_bytes0
                == arr.nbytes
            )
            assert (
                tbytes.value(transport="shm", op="get") - get_bytes0
                == arr.nbytes
            )
            # The snapshot is the same data, shaped for export.
            assert "ts_client_op_seconds" in snap
            put_hist = [
                s["value"]
                for s in snap["ts_client_op_seconds"]["series"]
                if s["labels"] == {"op": "put"}
            ]
            assert put_hist and put_hist[0]["count"] >= 1
        finally:
            await ts.shutdown("obs_e2e")
    finally:
        collector.flush()
        collector.path = old_path
    content = open(trace_path).read()
    data = json.loads(
        content if content.rstrip().endswith("]") else content + "\n]"
    )
    names = {e["name"] for e in data}
    # ≥1 span per layer: client op, transport transfer, per-volume fetch.
    assert "put_batch" in names
    assert "get_batch" in names
    assert "transport.put" in names and "transport.get" in names
    assert "fetch_volume" in names
    tput = next(e for e in data if e["name"] == "transport.put")
    assert tput["args"]["transport"] == "shm"
    assert tput["args"]["bytes"] == 2048 * 4


# --------------------------------------------------------------------------
# regression: carried ADVICE fixes
# --------------------------------------------------------------------------


class TestShmSpareHygiene:
    def test_sweep_purges_spare_by_size(self, monkeypatch):
        """ADVICE r4: a TTL-reaped reserved spare must also leave
        spare_by_size, or the per-size name lists grow without bound."""
        from torchstore_tpu.transport import shared_memory as shm

        if not shm.is_available():
            pytest.skip("/dev/shm unavailable")
        cache = shm.ShmServerCache()
        seg = shm.ShmSegment.create(128)
        try:
            cache.reserved[seg.name] = (seg, 0.0)  # reserved long ago
            cache.spare_by_size[128] = [seg.name]
            monkeypatch.setattr(
                shm.time, "monotonic", lambda: shm.RESERVED_TTL_S + 1.0
            )
            cache.sweep()
            assert seg.name not in cache.reserved
            assert cache.spare_by_size == {}
        finally:
            seg.unlink()

    def test_collect_released_evicts_stale_pre_attached(self, monkeypatch):
        """ADVICE carried: stale pre-attached spares must be evicted on the
        per-RPC entry point (collect_released), not only when another
        pre_attach call happens to arrive."""
        from torchstore_tpu.transport import shared_memory as shm

        if not shm.is_available():
            pytest.skip("/dev/shm unavailable")
        cache = shm.ShmClientCache()
        seg = shm.ShmSegment.create(64)
        try:
            cache.segments[seg.name] = seg
            cache._pre_attached[seg.name] = 0.0  # attached long ago
            monkeypatch.setattr(
                shm.time, "monotonic", lambda: shm.RESERVED_TTL_S + 1.0
            )
            cache.collect_released("v0")
            assert seg.name not in cache.segments
            assert cache._pre_attached == {}
        finally:
            seg.unlink()


@pytest.mark.anyio
async def test_reclaim_collects_generationless_durable_bytes():
    """ADVICE r4 carried fix: keys ABSENT from the volume's write_gens
    reply (durable bytes surviving a volume restart — no in-memory
    generation) must stay in the reclaim batch and be deleted, not dropped.
    Asserted through the real StorageVolume so the new resident-bytes gauge
    is the witness: it returns to baseline after the reclaim's delete."""
    from torchstore_tpu.controller import Controller
    from torchstore_tpu.storage_volume import InMemoryStore, StorageVolume
    from torchstore_tpu.transport.types import Request, TensorMeta

    vol = StorageVolume(storage=InMemoryStore())
    gauge = obs_metrics.get_registry().gauge("ts_volume_resident_bytes")
    baseline = gauge.value(volume=vol.volume_id)

    # Stale partial-landing bytes from BEFORE a volume restart: present in
    # storage, absent from _write_gens (the restart cleared them).
    arr = np.ones(256, np.float32)
    vol.store.store([Request.from_tensor("k", arr).meta_only()], {0: arr})
    vol._resident_bytes += arr.nbytes
    vol._publish_residency()
    assert gauge.value(volume=vol.volume_id) == baseline + arr.nbytes

    class VolumeRef:
        """Adapter exposing the real volume's endpoint coroutines the way
        the reclaim drainer calls them."""

        class _Ep:
            def __init__(self, fn):
                self.call_one = fn

        def __getattr__(self, name):
            # @endpoint methods are plain bound coroutines on the instance.
            return self._Ep(getattr(vol, name))

    c = Controller()
    c.volume_refs = {"v0": VolumeRef()}

    def meta():
        req = Request.from_tensor("k", arr)
        req.tensor_meta = TensorMeta(shape=(256,), dtype="float32")
        return req.meta_only()

    # First-ever put of k lands on v1 and FAILS on v0 -> v0 detached with
    # unknown generation (-1): exactly the partial-landing shape, but the
    # volume's write_gens reply is EMPTY (restart wiped it).
    await c.notify_put_batch(
        [meta()], "v1", detach_volume_ids=["v0"],
        write_gens={"v1": {"k": 200}},
    )
    assert c._pending_reclaims["v0"] == {"k": -1}
    for task in list(c._reclaim_tasks):
        await task
    # The generation-less durable bytes were reclaimed (not dropped) and
    # the resident-bytes gauge is back at baseline.
    assert "k" not in vol.store.kv
    assert c._pending_reclaims == {}
    assert gauge.value(volume=vol.volume_id) == baseline
