"""Store integration tests: put/get across actor processes, objects, exists,
delete idempotency, batches, error paths (reference tests/test_store.py)."""

import asyncio

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.runtime import Actor, endpoint, spawn_actors


@pytest.fixture(params=["auto", "rpc"])
async def store(request):
    # "auto" resolves to shm on a same-host volume once the SHM transport is
    # available; the "rpc" row keeps the fallback rung covered (reference
    # strategy x transport parameterization, tests/utils.py:33-69).
    strategy = ts.SingletonStrategy(
        default_transport_type=None if request.param == "auto" else request.param
    )
    await ts.initialize(store_name="t", strategy=strategy)
    yield "t"
    await ts.shutdown("t")


async def test_location_cache_survives_cross_client_changes(store):
    """Client A's cached key location must not serve stale results after
    client B deletes or re-publishes the key (stale fetches retry once
    against a fresh locate)."""
    from torchstore_tpu.client import LocalClient

    a = ts.client(store)
    b = LocalClient(a.controller, a._config)
    x = np.arange(16.0, dtype=np.float32)
    await a.put("k", x)
    np.testing.assert_array_equal(await a.get("k"), x)  # location now cached
    assert "k" in a._loc_cache
    # B re-publishes with a DIFFERENT shape; A must see the new value.
    y = np.arange(64.0, dtype=np.float32).reshape(8, 8)
    await b.put("k", y)
    out = await a.get("k")
    np.testing.assert_array_equal(out, y)
    # B deletes; A must raise, not serve stale bytes.
    await b.delete("k")
    with pytest.raises(KeyError):
        await a.get("k")


async def test_tensor_roundtrip(store):
    x = np.arange(24.0, dtype=np.float32).reshape(4, 6)
    await ts.put("x", x, store_name=store)
    out = await ts.get("x", store_name=store)
    np.testing.assert_array_equal(out, x)
    assert out.dtype == np.float32


async def test_object_roundtrip(store):
    await ts.put("obj", {"lr": 1e-3, "betas": (0.9, 0.95)}, store_name=store)
    assert await ts.get("obj", store_name=store) == {"lr": 1e-3, "betas": (0.9, 0.95)}


async def test_scalar_stored_as_object(store):
    await ts.put("s", 3.5, store_name=store)
    assert await ts.get("s", store_name=store) == 3.5


async def test_missing_key_raises(store):
    with pytest.raises(KeyError, match="not found"):
        await ts.get("nope", store_name=store)


async def test_exists(store):
    assert not await ts.exists("k", store_name=store)
    await ts.put("k", np.ones(3), store_name=store)
    assert await ts.exists("k", store_name=store)


async def test_overwrite_same_key(store):
    await ts.put("k", np.ones(4), store_name=store)
    await ts.put("k", np.full(4, 2.0), store_name=store)
    np.testing.assert_array_equal(
        await ts.get("k", store_name=store), np.full(4, 2.0)
    )


async def test_overwrite_type_confusion_rejected(store):
    await ts.put("k", np.ones(4), store_name=store)
    with pytest.raises(ValueError, match="already stored"):
        await ts.put("k", {"an": "object"}, store_name=store)


async def test_delete_and_idempotency(store):
    await ts.put("k", np.ones(2), store_name=store)
    await ts.delete("k", store_name=store)
    assert not await ts.exists("k", store_name=store)
    # Deleting again (and deleting missing keys) is a no-op.
    await ts.delete("k", store_name=store)
    await ts.delete_batch(["k", "never-existed"], store_name=store)


async def test_keys_prefix(store):
    for k in ["sd/v0/a", "sd/v0/b", "sd/v1/a", "zzz"]:
        await ts.put(k, np.ones(1), store_name=store)
    assert await ts.keys("sd/v0", store_name=store) == ["sd/v0/a", "sd/v0/b"]
    assert len(await ts.keys(store_name=store)) == 4


async def test_put_get_batch(store):
    items = {f"b/{i}": np.full((3,), float(i)) for i in range(5)}
    items["b/obj"] = ["any", "object"]
    await ts.put_batch(items, store_name=store)
    out = await ts.get_batch({k: None for k in items}, store_name=store)
    for i in range(5):
        np.testing.assert_array_equal(out[f"b/{i}"], np.full((3,), float(i)))
    assert out["b/obj"] == ["any", "object"]


async def test_get_batch_all_or_nothing(store):
    await ts.put("present", np.ones(2), store_name=store)
    with pytest.raises(KeyError):
        await ts.get_batch({"present": None, "absent": None}, store_name=store)


async def test_inplace_get_into_numpy(store):
    x = np.arange(12.0).reshape(3, 4)
    await ts.put("x", x, store_name=store)
    dest = np.zeros((3, 4))
    out = await ts.get("x", like=dest, store_name=store)
    assert out is dest
    np.testing.assert_array_equal(dest, x)


async def test_non_contiguous_put(store):
    base = np.arange(64.0).reshape(8, 8)
    noncontig = base[:, 1:5]
    assert not noncontig.flags["C_CONTIGUOUS"]
    await ts.put("nc", noncontig, store_name=store)
    np.testing.assert_array_equal(await ts.get("nc", store_name=store), noncontig)


async def test_bfloat16_roundtrip(store):
    import ml_dtypes

    x = np.arange(16, dtype=np.float32).astype(ml_dtypes.bfloat16)
    await ts.put("bf16", x, store_name=store)
    out = await ts.get("bf16", store_name=store)
    assert out.dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(out, x)


class WorkerActor(Actor):
    """README 4-actor example pattern: actors discover the store via the
    published handle and exchange tensors."""

    def __init__(self):
        import os

        self.rank = int(os.environ["RANK"])
        self.world = int(os.environ["WORLD_SIZE"])

    @endpoint
    async def store_tensor(self):
        await ts.put(f"worker/{self.rank}", np.full((4,), float(self.rank)), store_name="t")

    @endpoint
    async def fetch_neighbor(self):
        other = (self.rank + 1) % self.world
        out = await ts.get(f"worker/{other}", store_name="t")
        return float(out[0])


async def test_cross_actor_exchange(store):
    actors = await spawn_actors(3, WorkerActor, "workers")
    try:
        await actors.store_tensor.call()
        got = await actors.fetch_neighbor.call()
        assert got == [1.0, 2.0, 0.0]
    finally:
        await actors.stop()


async def test_get_with_shape_dtype_struct_target(store):
    jax = pytest.importorskip("jax")
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    g = np.arange(64.0, dtype=np.float32).reshape(8, 8)
    await ts.put("w", g, store_name=store)
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("x", "y"))
    spec = jax.ShapeDtypeStruct(
        g.shape, g.dtype, sharding=NamedSharding(mesh, P("x", "y"))
    )
    out = await ts.get("w", like=spec, store_name=store)
    assert out.sharding == spec.sharding
    np.testing.assert_array_equal(np.asarray(out), g)


async def test_volume_get_meta_endpoint(store):
    # Parity with the reference's get_meta used by allocation-driven
    # transports (/root/reference/torchstore/storage_volume.py:361-394).
    await ts.put("t", np.ones((3, 4), np.float32), store_name=store)
    await ts.put("o", {"x": 1}, store_name=store)
    client = ts.client(store)
    await client._ensure_setup()
    volume = next(iter(client._volume_refs.values()))
    from torchstore_tpu.transport.types import Request

    metas = await volume.actor.get_meta.call_one(
        [Request.meta_request("t"), Request.from_objects("o", None).meta_only()]
    )
    assert metas[0].shape == (3, 4) and metas[0].dtype == "float32"
    assert metas[1] == "obj"


async def test_concurrent_puts_and_gets(store):
    async def one(i):
        await ts.put(f"c/{i}", np.full((8,), float(i)), store_name=store)
        out = await ts.get(f"c/{i}", store_name=store)
        assert out[0] == float(i)

    await asyncio.gather(*(one(i) for i in range(16)))


class _KeysActor(Actor):
    def __init__(self):
        import os

        self.rank = int(os.environ["RANK"])

    @endpoint
    async def put_keys(self):
        await ts.put(f"ns/rank{self.rank}/a", np.ones(1), store_name="t")
        await ts.put(f"ns/rank{self.rank}/b", np.ones(1), store_name="t")

    @endpoint
    async def list_prefix(self, prefix):
        return await ts.keys(prefix, store_name="t")


async def test_keys_multi_process(store):
    # Prefix listing across writer processes (reference tests/test_keys.py).
    actors = await spawn_actors(2, _KeysActor, "keysactors")
    try:
        await actors.put_keys.call()
        listed = await actors[0].list_prefix.call_one("ns")
        assert listed == [
            "ns/rank0/a", "ns/rank0/b", "ns/rank1/a", "ns/rank1/b",
        ]
        assert await ts.keys("ns/rank1", store_name=store) == [
            "ns/rank1/a", "ns/rank1/b",
        ]
    finally:
        await actors.stop()


async def test_controller_stats(store):
    await ts.put("s1", np.ones((4, 4), np.float32), store_name=store)
    await ts.get("s1", store_name=store)
    # Warm same-host locates are served one-sided from the stamped
    # metadata segment (zero controller RPCs), so the locate counter only
    # moves on an explicit RPC locate — issue one to pin the assertion.
    await ts.client(store).controller.locate_volumes.call_one(["s1"])
    stats = await ts.client(store).controller.stats.call_one()
    assert stats["puts"] >= 1 and stats["put_bytes"] >= 64
    assert stats["locates"] >= 1 and stats["num_keys"] >= 1
    assert stats["num_volumes"] == 1
    assert "volumes" not in stats  # per-volume fan-out is opt-in


async def test_volume_stats_fanout(store):
    await ts.put("sv", np.ones((8, 8), np.float32), store_name=store)
    stats = await ts.client(store).controller.stats.call_one(
        include_volumes=True
    )
    (vstats,) = stats["volumes"].values()
    assert vstats["entries"] >= 1
    assert vstats["stored_bytes"] >= 256
    # SHM segment economics appear once the SHM transport served traffic.
    if "shm" in vstats:
        assert vstats["shm"]["live_segments"] >= 1
        assert vstats["shm"]["pool_bytes"] >= 0


async def test_delete_prefix(store):
    for v in ("v0", "v1"):
        for k in ("a", "b"):
            await ts.put(f"ckpt/{v}/{k}", np.ones(2), store_name=store)
    removed = await ts.delete_prefix("ckpt/v0", store_name=store)
    assert removed == 2
    assert await ts.keys("ckpt", store_name=store) == ["ckpt/v1/a", "ckpt/v1/b"]
    # Idempotent on an empty prefix.
    assert await ts.delete_prefix("ckpt/v0", store_name=store) == 0


async def test_get_batch_accepts_key_list(store):
    """Reference signature parity: get_batch takes a plain list of keys."""
    a, b = np.arange(8.0), np.arange(4.0)
    await ts.put_batch({"a": a, "b": b}, store_name=store)
    out = await ts.get_batch(["a", "b"], store_name=store)
    np.testing.assert_array_equal(out["a"], a)
    np.testing.assert_array_equal(out["b"], b)
