"""Cross-address DCN smoke test (VERDICT r5 #5): the whole stack must work
when nothing listens on 127.0.0.1 — controller/volume actors and the bulk
data plane bound to 127.0.0.2 (and a second store on 127.0.0.3), with the
client dialing across addresses. Any hardcoded 127.0.0.1 in the actor
server, bulk listener, or client dial path fails this test. Also asserts
the propagated trace id survives the cross-address hop (PR 2)."""

import json

import numpy as np
import pytest

from torchstore_tpu.observability import tracing


@pytest.mark.anyio
async def test_cross_address_fleet(tmp_path, monkeypatch):
    import torchstore_tpu as ts

    base = str(tmp_path / "trace.json")
    monkeypatch.setenv("TORCHSTORE_TPU_TRACE", base)
    collector = tracing.collector()
    old_path = collector.path
    collector.path = base

    # Fleet A (controller + volume + bulk listener) on 127.0.0.2, forced
    # onto the bulk transport so its dedicated data-plane sockets bind the
    # non-default address too.
    monkeypatch.setenv("TORCHSTORE_TPU_BIND_HOST", "127.0.0.2")
    try:
        await ts.initialize(
            store_name="xaddr_a",
            strategy=ts.SingletonStrategy(default_transport_type="bulk"),
        )
        # Fleet B on 127.0.0.3 (default transport ladder).
        monkeypatch.setenv("TORCHSTORE_TPU_BIND_HOST", "127.0.0.3")
        await ts.initialize(store_name="xaddr_b")
        try:
            # Nothing in either fleet advertises loopback-default addresses.
            for store, want in (("xaddr_a", "127.0.0.2"), ("xaddr_b", "127.0.0.3")):
                c = ts.client(store)
                assert c.controller.host == want, (store, c.controller.host)
                vmap = await c.controller.get_volume_map.call_one()
                for vid, info in vmap.items():
                    assert info["ref"].host == want, (store, vid, info["ref"].host)

            # Small put/get + a bulk transfer (multi-MB payload over the
            # dedicated bulk sockets) across the 127.0.0.2 hop.
            small = np.arange(256, dtype=np.float32)
            await ts.put("x/small", small, store_name="xaddr_a")
            np.testing.assert_array_equal(
                np.asarray(await ts.get("x/small", store_name="xaddr_a")), small
            )
            bulk = np.random.default_rng(0).standard_normal(
                (512, 1024)
            ).astype(np.float32)  # 2 MiB
            await ts.put("x/bulk", bulk, store_name="xaddr_a")
            got = await ts.get("x/bulk", store_name="xaddr_a")
            np.testing.assert_array_equal(np.asarray(got), bulk)
            del got

            # Cross-store relay: read from the .2 fleet, write to the .3
            # fleet — one client talking to both addresses in one process.
            relay = await ts.get("x/small", store_name="xaddr_a")
            await ts.put("x/relay", np.asarray(relay), store_name="xaddr_b")
            np.testing.assert_array_equal(
                np.asarray(await ts.get("x/relay", store_name="xaddr_b")), small
            )
            del relay
        finally:
            await ts.shutdown("xaddr_b")
            await ts.shutdown("xaddr_a")
        merged = ts.collect_trace(str(tmp_path / "merged.json"))
    finally:
        collector.flush()
        collector.path = old_path

    # The trace id minted client-side survived the cross-address RPC hop:
    # the bulk put's span and a remote process's rpc span share it.
    events = json.load(open(merged["path"]))
    spans = [e for e in events if e.get("ph") == "X"]
    put_spans = [
        e
        for e in spans
        if e["name"] == "put_batch" and "trace_id" in (e.get("args") or {})
    ]
    assert put_spans
    stitched = 0
    for put_span in put_spans:
        tid = put_span["args"]["trace_id"]
        pids = {
            e["pid"]
            for e in spans
            if (e.get("args") or {}).get("trace_id") == tid
        }
        stitched += len(pids) >= 2
    assert stitched >= 1, "no trace id crossed the 127.0.0.2/127.0.0.3 hop"
