"""Cross-address DCN smoke test (VERDICT r5 #5): the whole stack must work
when nothing listens on 127.0.0.1 — controller/volume actors and the bulk
data plane bound to 127.0.0.2 (and a second store on 127.0.0.3), with the
client dialing across addresses. Any hardcoded 127.0.0.1 in the actor
server, bulk listener, or client dial path fails this test. Also asserts
the propagated trace id survives the cross-address hop (PR 2).

Cross-HOST tier (PR 20): `TORCHSTORE_TPU_HOSTNAME` overlays emulate a
multi-host fleet in one process tree, so the metadata-mirror + push-session
planes are exercised exactly as a real DCN deployment would drive them —
warm remote acquires must issue ZERO metadata RPCs, and killing a mirror's
relay parent mid-stream must fall back loudly (never serve mixed
generations) until the re-parented subscription resumes."""

import asyncio
import json
import time

import numpy as np
import pytest

from torchstore_tpu.observability import tracing


@pytest.mark.anyio
async def test_cross_address_fleet(tmp_path, monkeypatch):
    import torchstore_tpu as ts

    base = str(tmp_path / "trace.json")
    monkeypatch.setenv("TORCHSTORE_TPU_TRACE", base)
    collector = tracing.collector()
    old_path = collector.path
    collector.path = base

    # Fleet A (controller + volume + bulk listener) on 127.0.0.2, forced
    # onto the bulk transport so its dedicated data-plane sockets bind the
    # non-default address too.
    monkeypatch.setenv("TORCHSTORE_TPU_BIND_HOST", "127.0.0.2")
    try:
        await ts.initialize(
            store_name="xaddr_a",
            strategy=ts.SingletonStrategy(default_transport_type="bulk"),
        )
        # Fleet B on 127.0.0.3 (default transport ladder).
        monkeypatch.setenv("TORCHSTORE_TPU_BIND_HOST", "127.0.0.3")
        await ts.initialize(store_name="xaddr_b")
        try:
            # Nothing in either fleet advertises loopback-default addresses.
            for store, want in (("xaddr_a", "127.0.0.2"), ("xaddr_b", "127.0.0.3")):
                c = ts.client(store)
                assert c.controller.host == want, (store, c.controller.host)
                vmap = await c.controller.get_volume_map.call_one()
                for vid, info in vmap.items():
                    assert info["ref"].host == want, (store, vid, info["ref"].host)

            # Small put/get + a bulk transfer (multi-MB payload over the
            # dedicated bulk sockets) across the 127.0.0.2 hop.
            small = np.arange(256, dtype=np.float32)
            await ts.put("x/small", small, store_name="xaddr_a")
            np.testing.assert_array_equal(
                np.asarray(await ts.get("x/small", store_name="xaddr_a")), small
            )
            bulk = np.random.default_rng(0).standard_normal(
                (512, 1024)
            ).astype(np.float32)  # 2 MiB
            await ts.put("x/bulk", bulk, store_name="xaddr_a")
            got = await ts.get("x/bulk", store_name="xaddr_a")
            np.testing.assert_array_equal(np.asarray(got), bulk)
            del got

            # Cross-store relay: read from the .2 fleet, write to the .3
            # fleet — one client talking to both addresses in one process.
            relay = await ts.get("x/small", store_name="xaddr_a")
            await ts.put("x/relay", np.asarray(relay), store_name="xaddr_b")
            np.testing.assert_array_equal(
                np.asarray(await ts.get("x/relay", store_name="xaddr_b")), small
            )
            del relay
        finally:
            await ts.shutdown("xaddr_b")
            await ts.shutdown("xaddr_a")
        merged = ts.collect_trace(str(tmp_path / "merged.json"))
    finally:
        collector.flush()
        collector.path = old_path

    # The trace id minted client-side survived the cross-address RPC hop:
    # the bulk put's span and a remote process's rpc span share it.
    events = json.load(open(merged["path"]))
    spans = [e for e in events if e.get("ph") == "X"]
    put_spans = [
        e
        for e in spans
        if e["name"] == "put_batch" and "trace_id" in (e.get("args") or {})
    ]
    assert put_spans
    stitched = 0
    for put_span in put_spans:
        tid = put_span["args"]["trace_id"]
        pids = {
            e["pid"]
            for e in spans
            if (e.get("args") or {}).get("trace_id") == tid
        }
        stitched += len(pids) >= 2
    assert stitched >= 1, "no trace id crossed the 127.0.0.2/127.0.0.3 hop"


@pytest.mark.anyio
async def test_cross_host_mirror_zero_rpc_warm(monkeypatch):
    """Warm remote acquire over the cross-host one-sided tier: with the
    client on a DIFFERENT (emulated) host than every stamped publisher,
    the mirror replica serves locates/epochs locally and the push session
    stages fresh layers — repeated warm gets issue ZERO metadata RPCs
    (``ts.traffic_matrix()["metadata"]`` is the measured assertion)."""
    import torchstore_tpu as ts
    from torchstore_tpu.transport import bulk as bulk_mod

    monkeypatch.setenv("TORCHSTORE_TPU_HOSTNAME", "mirror-vol-host")
    monkeypatch.setenv("TORCHSTORE_TPU_META_MIRROR_INTERVAL_MS", "10")
    await ts.initialize(
        store_name="xmirror",
        strategy=ts.SingletonStrategy(default_transport_type="bulk"),
    )
    try:
        payload = np.arange(4096, dtype=np.float32)
        await ts.put("m/warm", payload, store_name="xmirror")

        # Become a REMOTE host: reload the topology under a different
        # identity, so every stamped publisher is cross-host and the
        # router arms the mirror instead of same-host shm.
        monkeypatch.setenv("TORCHSTORE_TPU_HOSTNAME", "mirror-client-host")
        client = ts.client("xmirror")
        await client._load_volumes()
        router = client._controller
        assert router._mirror is not None, "mirror did not arm cross-host"
        assert await router._mirror.wait_ready(5.0)

        # Cold get: RPC locate + doorbell-plan registration are allowed
        # here (this is the one-time plan establishment).
        got = await ts.get("m/warm", store_name="xmirror")
        np.testing.assert_array_equal(np.asarray(got), payload)

        # Wait until the mirrored index resolves the key locally — from
        # here on the warm path has everything it needs with zero RPCs.
        deadline = time.monotonic() + 5.0
        while router.stamped_locate(["m/warm"]) is None:
            assert (
                time.monotonic() < deadline
            ), "mirror never replicated the index image"
            await asyncio.sleep(0.02)

        # A fresh put AFTER the plan is registered: the volume pushes the
        # new generation at watermark time into the client's staging
        # arena (push-on-publish), so the next read's first byte is a
        # local memcpy.
        payload2 = np.arange(4096, dtype=np.float32) * 2.0
        await ts.put("m/warm", payload2, store_name="xmirror")
        cache = client._ctx.get_cache(bulk_mod.BulkClientCache)
        deadline = time.monotonic() + 5.0
        while not cache.push_staging:
            assert (
                time.monotonic() < deadline
            ), "push session never staged the fresh layer"
            await asyncio.sleep(0.02)

        before = (await ts.traffic_matrix("xmirror"))["metadata"]
        push_serves0 = bulk_mod._PUSH_SERVES.total()
        for _ in range(3):
            got = await ts.get("m/warm", store_name="xmirror")
            np.testing.assert_array_equal(np.asarray(got), payload2)
        after = (await ts.traffic_matrix("xmirror"))["metadata"]
        diff = {
            op: after["rpcs"].get(op, 0) - before["rpcs"].get(op, 0)
            for op in set(after["rpcs"]) | set(before["rpcs"])
        }
        # traffic_matrix itself scrapes the fleet over one counted
        # "stats" RPC per call — nothing else may move.
        hot = {op: n for op, n in diff.items() if n and op != "stats"}
        assert not hot, f"warm remote gets issued metadata RPCs: {hot}"
        assert sum(after["stamped"].values()) > sum(
            before["stamped"].values()
        ), "warm reads were not served from the mirrored stamped plane"
        assert bulk_mod._PUSH_SERVES.total() > push_serves0, (
            "warm gets never served from the push-staged arena"
        )
    finally:
        await ts.shutdown("xmirror")


@pytest.mark.anyio
async def test_cross_host_mirror_chaos_reparent(monkeypatch):
    """Chaos leg: kill the mirror's relay PARENT mid-stream. The client's
    stamped reads must fall back LOUDLY to RPC (``mirror_lag``, never a
    silent stale serve), every read during the dark window must be a
    single committed generation (no tearing/blending), and the mirror
    must re-subscribe AROUND the dead parent and resume."""
    import torchstore_tpu as ts
    from torchstore_tpu.metadata import mirror as mirror_mod
    from torchstore_tpu.metadata import stamped as stamped_mod

    monkeypatch.setenv("TORCHSTORE_TPU_HOSTNAME", "chaos-vol-host")
    monkeypatch.setenv("TORCHSTORE_TPU_META_MIRROR_INTERVAL_MS", "10")
    monkeypatch.setenv("TORCHSTORE_TPU_META_MIRROR_HEARTBEAT_S", "0.05")
    monkeypatch.setenv("TORCHSTORE_TPU_META_MIRROR_LAG_S", "0.4")
    await ts.initialize(store_name="xchaos")
    try:
        client = ts.client("xchaos")
        coordinator = client._controller.coordinator
        topo = await coordinator.metadata_topology.call_one()
        feed = topo.get("meta_feed")
        assert feed, "controller did not start the metadata feed"

        # An intermediate relay hop: the FIRST subscriber takes the root
        # feed's only slot (ROOT_FANOUT=1)...
        monkeypatch.setenv("TORCHSTORE_TPU_HOSTNAME", "chaos-hop-host")
        hop = mirror_mod.MetadataMirror(
            coordinator, (feed["host"], feed["port"])
        )
        await hop.start()
        assert await hop.wait_ready(5.0)

        # ...so the CLIENT's mirror is fanned through the hop, exactly
        # the one-deep relay shape a real trainer-host tree produces.
        monkeypatch.setenv("TORCHSTORE_TPU_HOSTNAME", "chaos-client-host")
        await client._load_volumes()
        router = client._controller
        assert router._mirror is not None
        assert await router._mirror.wait_ready(5.0)
        assert router._mirror._parent_hostname == "chaos-hop-host"

        async def _put_fill(i: int) -> None:
            await ts.put(
                "c/key",
                np.full(1024, float(i), dtype=np.float32),
                store_name="xchaos",
            )

        def _assert_uniform(arr) -> None:
            arr = np.asarray(arr)
            assert arr.shape == (1024,)
            assert (arr == arr[0]).all(), (
                "mixed-generation read: blended fills "
                f"{sorted(set(arr.tolist()))[:4]}"
            )

        await _put_fill(0)
        deadline = time.monotonic() + 5.0
        while router.stamped_locate(["c/key"]) is None:
            assert time.monotonic() < deadline, "replica never caught up"
            await asyncio.sleep(0.02)

        # Loud-fallback ladder, deterministically: rewind the replica's
        # receive clock past the lag bound and read IN THE SAME TICK
        # (stamped reads are synchronous — no heartbeat can interleave).
        # The read must refuse the stale mirror and count mirror_lag.
        fb0 = stamped_mod.STAMPED_FALLBACKS.value(reason="mirror_lag")
        router._mirror._last_rx = time.monotonic() - 60.0
        assert router.stamped_locate(["c/key"]) is None
        assert (
            stamped_mod.STAMPED_FALLBACKS.value(reason="mirror_lag") > fb0
        ), "stale mirror served silently (no mirror_lag fallback)"
        # The RPC plane still answers correctly through the dark window.
        _assert_uniform(await ts.get("c/key", store_name="xchaos"))

        # Kill the relay parent MID-STREAM: writes keep landing while the
        # tree re-forms; the client's mirror must re-subscribe around the
        # dead hop (down-set) and land back on the root feed.
        resub0 = mirror_mod._RESUBSCRIBES.total()
        hop.close()
        gen = 1
        deadline = time.monotonic() + 15.0
        reparented = False
        while time.monotonic() < deadline:
            await _put_fill(gen)
            _assert_uniform(await ts.get("c/key", store_name="xchaos"))
            gen += 1
            if (
                router._mirror._parent_hostname != "chaos-hop-host"
                and router._mirror.fresh()
            ):
                reparented = True
                break
            await asyncio.sleep(0.05)
        assert reparented, "mirror never re-parented around the dead hop"
        assert mirror_mod._RESUBSCRIBES.total() > resub0

        # Resumed replica serves the LATEST committed generation warm.
        await _put_fill(gen)
        deadline = time.monotonic() + 5.0
        while True:
            hits = router.stamped_locate(["c/key"])
            if hits is not None:
                break
            assert time.monotonic() < deadline, "replica never resumed"
            await asyncio.sleep(0.02)
        _assert_uniform(await ts.get("c/key", store_name="xchaos"))
    finally:
        await ts.shutdown("xchaos")
