"""Blockwise int8/int4 + delta wire tier (ISSUE 13).

Covers the fused-blob codec (scales packed in the same segment as the
payload via the arena layout's scale slots), the delta encoder/decoder
(bit-identical publisher baseline vs reader accumulation, keyframe
cadence, chain walks), the unchanged-watermark protocol (streamed reads of
unchanged keys served from v-1 bytes with ZERO re-transfer, seal re-check
consistent), plan-cache integration (quantized publishes hit the cache —
no exclusion branch), loud-failure paths (NaN block naming, broken delta
chains, the channel.delta_baseline faultpoint), and the provisioning
manifest's scale-bearing blob sizes.
"""

import asyncio

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu import faults
from torchstore_tpu import state_dict_utils as sdu

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402


@pytest.fixture
async def store():
    await ts.initialize(store_name="qd")
    yield "qd"
    await ts.shutdown("qd")


def _metric(name: str) -> float:
    snap = ts.metrics_snapshot()
    m = snap.get(name) or {"series": []}
    return float(sum(s["value"] for s in m["series"]))


def _tol(arr, qmax=127.0):
    # One keyframe step per block bounds the tier's error (the skip rule's
    # threshold is half a step; shipped residuals re-center).
    return float(np.max(np.abs(arr))) / qmax + 1e-6


# --------------------------------------------------------------------------
# codec
# --------------------------------------------------------------------------


@pytest.mark.parametrize("fmt", ["int8_block", "int4_block"])
async def test_blockwise_roundtrip(store, fmt):
    sd = {
        "w": np.random.randn(300, 17).astype(np.float32),  # ragged tail block
        "b": np.random.randn(5).astype(np.float32) * 0.01,
        "step": 7,
    }
    await ts.put_state_dict("m", sd, transfer_quant=fmt, store_name="qd")
    out = await ts.get_state_dict("m", store_name="qd")
    qmax = sdu._QMAX[fmt]
    assert out["w"].dtype == np.float32 and out["w"].shape == (300, 17)
    np.testing.assert_allclose(out["w"], sd["w"], atol=_tol(sd["w"], qmax))
    np.testing.assert_allclose(out["b"], sd["b"], atol=_tol(sd["b"], qmax))
    assert out["step"] == 7


async def test_blockwise_inplace_and_jax_targets(store):
    sd = {"w": np.random.randn(64, 8).astype(np.float32)}
    await ts.put_state_dict(
        "mi", sd, transfer_quant="int8_block", store_name="qd"
    )
    user = {"w": np.zeros((64, 8), np.float32)}
    out = await ts.get_state_dict("mi", user_state_dict=user, store_name="qd")
    assert out["w"] is user["w"]  # decoded into the caller's memory
    np.testing.assert_allclose(user["w"], sd["w"], atol=_tol(sd["w"]))
    # jax spec target: decoded host-side, device_put with the target dtype.
    spec = jax.ShapeDtypeStruct(
        (64, 8),
        jnp.float32,
        sharding=jax.sharding.SingleDeviceSharding(jax.devices()[0]),
    )
    out = await ts.get_state_dict(
        "mi", user_state_dict={"w": spec}, store_name="qd"
    )
    assert out["w"].dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(out["w"]), sd["w"], atol=_tol(sd["w"])
    )


async def test_scales_ride_the_payload_segment(store):
    """The wire/store artifact is ONE uint8 blob per tensor whose layout
    (landing.quant_blob_layout) fuses the scale table after the payload —
    stored bytes are ~N + scales, never a separate scales object."""
    from torchstore_tpu.transport import landing

    n = 256 * 256
    sd = {"w": np.random.randn(256, 256).astype(np.float32)}
    await ts.put_state_dict(
        "ms", sd, transfer_quant="int8_block", store_name="qd"
    )
    stats = await ts.client("qd").controller.stats.call_one(
        include_volumes=True
    )
    (vstats,) = stats["volumes"].values()
    expect = landing.quant_wire_nbytes("int8_block", 256, n, 2)
    # Stored bytes ~= one fused blob (+ the marker object), far under 4N.
    assert vstats["stored_bytes"] < expect + 4096
    assert expect < n * 1.05  # scale slots cost ~1.6% at block 256


async def test_nonfinite_block_names_key_and_block(store):
    bad = np.random.randn(1024).astype(np.float32)
    bad[700] = np.nan  # block 2 at block size 256
    with pytest.raises(ValueError, match=r"'w'.*block 2.*non-finite") as ei:
        await ts.put_state_dict(
            "nf", {"w": bad}, transfer_quant="int8_block", store_name="qd"
        )
    assert "block 2" in str(ei.value)
    # Per-tensor int8 still raises (no block index: one block per tensor).
    with pytest.raises(ValueError, match="non-finite"):
        await ts.put_state_dict(
            "nf", {"w": bad}, transfer_quant="int8", store_name="qd"
        )


def test_cross_backend_dequantize_bit_equivalence():
    """Satellite: the blessed _dequantize produces BIT-identical bytes on
    numpy and jax-cpu (one f32 code x f32 scale path, no
    numpy-rounds-the-scale-but-jax-does-not seam)."""
    q = np.random.randint(-127, 128, 4096).astype(np.int8)
    for scale in (0.0123456789, 1.0, 3.7e-5):
        a = sdu._dequantize(q, scale, "float32")
        b = np.asarray(sdu._dequantize(jnp.asarray(q), scale, "float32"))
        assert a.tobytes() == b.tobytes()
    # The vector path (blockwise scales) through the same core:
    codes = np.random.randint(-127, 128, (16, 64)).astype(np.int8)
    scales = np.abs(np.random.randn(16, 1)).astype(np.float32) + 1e-3
    a = sdu._dequant_codes(codes, scales)
    b = np.asarray(sdu._dequant_codes(jnp.asarray(codes), scales))
    assert a.tobytes() == b.tobytes()


async def test_env_default_mode(store):
    """TORCHSTORE_TPU_TRANSFER_QUANT selects the wire tier without call-site
    changes (config-resolved per client)."""
    client = ts.client("qd")
    orig = client._config
    client._config = orig.merged(transfer_quant="int8_block")
    try:
        sd = {"w": np.random.randn(128).astype(np.float32)}
        await ts.put_state_dict("me", sd, store_name="qd")
        marker = await client.get("me/MAPPING")
        assert marker["quant"]["fmt"] == "int8_block"
        out = await ts.get_state_dict("me", store_name="qd")
        np.testing.assert_allclose(out["w"], sd["w"], atol=_tol(sd["w"]))
    finally:
        client._config = orig


# --------------------------------------------------------------------------
# plan cache (acceptance: no cache-exclusion branch remains)
# --------------------------------------------------------------------------


async def test_quantized_publishes_hit_plan_cache(store):
    sd = {
        "w": np.random.randn(1024).astype(np.float32),
        "b": np.random.randn(32).astype(np.float32),
    }
    user = {"w": np.zeros(1024, np.float32), "b": np.zeros(32, np.float32)}
    hits0 = _metric("ts_plan_cache_hits_total")
    for it in range(3):
        sd["w"][0] = float(it)
        await ts.put_state_dict(
            "pc", sd, transfer_quant="int8_block", store_name="qd"
        )
        await ts.get_state_dict("pc", user_state_dict=user, store_name="qd")
    hits = _metric("ts_plan_cache_hits_total") - hits0
    # Warm iterations hit on BOTH the put and the get plan.
    assert hits >= 4, hits
    np.testing.assert_allclose(user["w"], sd["w"], atol=_tol(sd["w"]))


# --------------------------------------------------------------------------
# delta tier: channel publishes
# --------------------------------------------------------------------------


async def test_delta_channel_accuracy_and_unchanged(store):
    pub = ts.WeightPublisher(
        "dc", store_name="qd", keep=5, transfer_quant="int8_block",
        delta=True, keyframe_every=4,
    )
    sub = ts.WeightSubscriber("dc", store_name="qd")
    w = {
        "hot": np.random.randn(600).astype(np.float32),
        "frozen": np.random.randn(600).astype(np.float32),
    }
    unchanged0 = _metric("ts_delta_unchanged_keys_total")
    kf0 = _metric("ts_delta_keyframes_total")
    for v in range(4):
        if v:
            w["hot"][:100] += 0.05
        ver = await pub.publish(w)
        sd, got = await sub.acquire(timeout=30)
        assert got == ver == v
        for k in w:
            np.testing.assert_allclose(sd[k], w[k], atol=_tol(w[k]))
        # Reader accumulation is BIT-identical to the publisher baseline.
        st = sub._delta_decoder().state[k]
        np.testing.assert_array_equal(
            st["blocks"], pub._codec.entries[k]["baseline"]
        )
    # The frozen key went unchanged (zero bytes shipped) after its first
    # delta round; keyframes fired once per key at v0.
    assert _metric("ts_delta_unchanged_keys_total") - unchanged0 >= 2
    assert _metric("ts_delta_keyframes_total") - kf0 >= 2
    # A fresh (joining) barrier reader chain-walks to the same bytes.
    sub2 = ts.WeightSubscriber("dc", store_name="qd")
    sd2, v2 = await sub2.acquire(timeout=30)
    assert v2 == ver
    for k in w:
        np.testing.assert_array_equal(np.asarray(sd2[k]), np.asarray(sd[k]))


async def test_delta_keyframe_cadence_bounds_chain(store):
    pub = ts.WeightPublisher(
        "kc", store_name="qd", keep=4, transfer_quant="int8_block",
        delta=True, keyframe_every=3,
    )
    sub = ts.WeightSubscriber("kc", store_name="qd")
    w = {"w": np.random.randn(512).astype(np.float32)}
    kf0 = _metric("ts_delta_keyframes_total")
    for v in range(7):
        w["w"][:64] += 0.01
        await pub.publish(w)
        await sub.acquire(timeout=30)
    # Keyframes at v0, v3, v6 — cadence 3.
    assert _metric("ts_delta_keyframes_total") - kf0 == 3


async def test_delta_requires_blockwise_and_retained_chain(store):
    with pytest.raises(ValueError, match="blockwise"):
        await ts.WeightPublisher(
            "dv", store_name="qd", transfer_quant="int8", delta=True
        ).publish({"w": np.ones(8, np.float32)})
    with pytest.raises(ValueError, match="keep >= keyframe"):
        await ts.WeightPublisher(
            "dv2", store_name="qd", keep=2, transfer_quant="int8_block",
            delta=True, keyframe_every=8,
        ).publish({"w": np.ones(8, np.float32)})


async def test_delta_broken_chain_fails_loudly(store):
    """A delta whose baseline version was evicted must raise — never
    silently serve a drifted accumulation."""
    pub = ts.WeightPublisher(
        "bc", store_name="qd", keep=5, transfer_quant="int8_block",
        delta=True, keyframe_every=4,
    )
    w = {"w": np.random.randn(512).astype(np.float32)}
    await pub.publish(w)          # v0 keyframe
    w["w"][:64] += 0.5
    await pub.publish(w)          # v1 delta on v0
    client = ts.client("qd")
    # Simulate retention violation: the keyframe's bytes vanish.
    await client.delete_prefix("bc/v0")
    fresh = ts.WeightSubscriber("bc", store_name="qd")
    with pytest.raises(RuntimeError, match="delta chain broken"):
        await fresh.acquire(version=1, timeout=30)


async def test_delta_baseline_faultpoint_raises_loudly(store):
    """channel.delta_baseline armed with raise: both the publisher's
    baseline reuse and the reader's accumulation fail LOUDLY (never a
    silent re-keyframe over stale bytes), and recovery works after
    clearing."""
    pub = ts.WeightPublisher(
        "fb", store_name="qd", keep=5, transfer_quant="int8_block",
        delta=True, keyframe_every=4,
    )
    sub = ts.WeightSubscriber("fb", store_name="qd")
    w = {"w": np.random.randn(512).astype(np.float32)}
    await pub.publish(w)
    await sub.acquire(timeout=30)
    faults.arm("channel.delta_baseline", "raise", count=1)
    try:
        w["w"][:64] += 0.1
        with pytest.raises(faults.FaultInjectedError):
            await pub.publish(w)
    finally:
        faults.disarm("channel.delta_baseline")
    # Cleared: the interrupted version number was consumed or not, either
    # way the next publish + acquire converge on correct bytes.
    ver = await pub.publish(w)
    sd, got = await sub.acquire(timeout=30)
    assert got == ver
    np.testing.assert_allclose(sd["w"], w["w"], atol=_tol(w["w"]))


# --------------------------------------------------------------------------
# unchanged-watermark protocol (streamed)
# --------------------------------------------------------------------------


async def test_streamed_unchanged_served_from_v1_bytes_zero_retransfer(store):
    """Acceptance: a streamed delta publish of unchanged keys watermarks
    them as aliases; a warm streaming subscriber serves them from its
    accumulated v-1 state with ZERO re-transfer, and the final seal
    re-check passes (no restarts, no MixedGenerationError)."""
    pub = ts.WeightPublisher(
        "su", store_name="qd", keep=5, transfer_quant="int8_block",
        delta=True, keyframe_every=4,
    )
    sub = ts.WeightSubscriber("su", store_name="qd")
    layers = {
        str(i): np.random.randn(256).astype(np.float32) for i in range(3)
    }
    order = [f"layers/{i}" for i in range(3)]

    async def publish(churn: bool):
        cs = pub.stream()
        for i in range(3):
            if churn and i == 0:
                layers["0"][:32] += 0.1
            await cs.put({"layers": {str(i): layers[str(i)]}})
        return await cs.seal()

    async def acquire():
        served = []
        task = asyncio.ensure_future(
            sub.acquire_streamed(
                key_order=order,
                on_layer=lambda fk, v: served.append(fk),
                timeout=30,
            )
        )
        sd, ver = await task
        assert served == order
        return sd, ver

    falls0 = _metric("ts_stream_fallbacks_total")
    served0 = _metric("ts_delta_unchanged_served_total")
    # v0 keyframes; v1 and v2: layers 1-2 frozen -> unchanged aliases.
    for v in range(3):
        pt = asyncio.ensure_future(publish(churn=v > 0))
        sd, ver = await acquire()
        await pt
        assert ver == v
        for i in range(3):
            np.testing.assert_allclose(
                sd["layers"][str(i)], layers[str(i)],
                atol=_tol(layers[str(i)]),
            )
    # Frozen layers at v1/v2 were served locally (4 = 2 layers x 2
    # versions), with zero stream restarts — the seal re-check treated the
    # unchanged watermarks as consistent.
    assert _metric("ts_delta_unchanged_served_total") - served0 >= 4
    assert _metric("ts_stream_fallbacks_total") - falls0 == 0
    # Controller-side: the stream record carries the aliases, watermarked
    # at the stream version (inconsistent_keys == []).
    from torchstore_tpu import stream_sync

    state = await ts.client("qd").stream_state("su/v2")
    aliased = [k for k in state["aliases"]]
    assert aliased, state
    assert (
        stream_sync.inconsistent_keys(state, aliased, state["version"]) == []
    )


async def test_unchanged_alias_to_missing_base_fails_publish(store):
    """The controller validates alias targets are committed: an alias to
    GC'd bytes fails the PUBLISHER loudly instead of handing readers an
    unservable key."""
    client = ts.client("qd")
    await client.stream_begin("ghost/v3")
    with pytest.raises(Exception, match="not committed"):
        await client.stream_mark_unchanged(
            "ghost/v3", 1, {"ghost/v3/w": ("ghost/v2/w", 2)}
        )


async def test_recreated_channel_resets_delta_decoder(store):
    """Review hardening: a deleted-then-recreated channel restarts version
    numbering under a fresh epoch — a subscriber's accumulated state from
    the OLD epoch must never satisfy the new epoch's delta bases (same
    version ints, different weights)."""
    pub = ts.WeightPublisher(
        "re", store_name="qd", keep=5, transfer_quant="int8_block",
        delta=True, keyframe_every=4,
    )
    sub = ts.WeightSubscriber("re", store_name="qd")
    old = {"w": np.random.randn(512).astype(np.float32)}
    await pub.publish(old)  # old-epoch v0 keyframe
    sd, v = await sub.acquire(timeout=30)
    assert v == 0
    await pub.close(delete=True)
    # Fresh epoch, numbering restarts; DIFFERENT weights. Publish v0 AND
    # v1 before the subscriber wakes, so it jumps straight to v1 — a
    # delta whose base (v0) matches the stale state's version int.
    pub2 = ts.WeightPublisher(
        "re", store_name="qd", keep=5, transfer_quant="int8_block",
        delta=True, keyframe_every=4,
    )
    new = {"w": np.random.randn(512).astype(np.float32)}
    assert await pub2.publish(new) == 0
    new["w"][:64] += 0.1
    assert await pub2.publish(new) == 1
    sd, v = await sub.acquire(timeout=30)
    assert v == 1
    np.testing.assert_allclose(sd["w"], new["w"], atol=_tol(new["w"]))
    np.testing.assert_array_equal(
        sub._delta_decoder().state["w"]["blocks"],
        pub2._codec.entries["w"]["baseline"],
    )


async def test_stream_record_reuse_drops_stale_quant_meta(store):
    """Review hardening: an unquantized stream over a key that previously
    streamed QUANTIZED must not inherit the old record's quant meta —
    readers would skip in-place landings and misdecode raw tensors."""
    client = ts.client("qd")
    x1 = np.random.randn(64).astype(np.float32)
    s = ts.state_dict_stream("rq", transfer_quant="int8_block", store_name="qd")
    await s.put({"w": x1})
    await s.seal()
    out = await ts.get_state_dict("rq", stream=True, store_name="qd")
    np.testing.assert_allclose(out["w"], x1, atol=_tol(x1))
    # Same key, now unquantized: the record must carry quant=None and the
    # streamed read must land IN PLACE into the user target.
    x2 = np.random.randn(64).astype(np.float32)
    s2 = ts.state_dict_stream("rq", store_name="qd")
    await s2.put({"w": x2})
    await s2.seal()
    assert (await client.stream_state("rq"))["quant"] is None
    user = {"w": np.zeros(64, np.float32)}
    out = await ts.get_state_dict(
        "rq", user_state_dict=user, stream=True, store_name="qd"
    )
    assert out["w"] is user["w"]
    np.testing.assert_array_equal(user["w"], x2)


# --------------------------------------------------------------------------
# provisioning manifest
# --------------------------------------------------------------------------


def test_manifest_sizes_quant_blobs():
    from torchstore_tpu.provision.manifest import StateDictManifest
    from torchstore_tpu.transport.landing import quant_wire_nbytes

    sd = {
        "w": np.zeros((1000, 32), np.float32),
        "idx": np.zeros(100, np.int64),  # non-floating: uncompressed
    }
    man = StateDictManifest.from_state_dict(
        sd, transfer_quant="int4_block", quant_block=256
    )
    by_key = {e.key: e for e in man.entries}
    assert by_key["w"].request_nbytes == (
        quant_wire_nbytes("int4_block", 256, 32000, 2),
    )
    assert by_key["w"].nbytes < sd["w"].nbytes / 6  # ~8x minus overhead
    assert by_key["idx"].nbytes == sd["idx"].nbytes
