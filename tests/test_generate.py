"""KV-cached decoding: the jitted prefill+step loop must produce exactly
the tokens a full-forward recompute produces (the cache is an
optimization, never a semantics change), across model families."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from torchstore_tpu.models.generate import Decoder  # noqa: E402
from torchstore_tpu.models.llama import Llama, LlamaConfig  # noqa: E402


def _greedy_recompute(cfg, params, prompt, steps):
    """Oracle: argmax decode recomputing the FULL forward every step."""
    model = Llama(cfg)
    tokens = jnp.asarray(prompt, jnp.int32)
    for _ in range(steps):
        logits = model.apply(params, tokens)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1)[:, None].astype(jnp.int32)
        tokens = jnp.concatenate([tokens, nxt], axis=1)
    return tokens


@pytest.mark.parametrize(
    "cfg_name", ["tiny", "tiny_moe", "tiny_gemma"], ids=["llama", "moe", "gemma"]
)
def test_cached_decode_matches_full_recompute(cfg_name):
    cfg = getattr(LlamaConfig, cfg_name)()
    import dataclasses

    cfg = dataclasses.replace(cfg, dtype=jnp.float32, param_dtype=jnp.float32)
    model = Llama(cfg)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 5)), jnp.int32
    )
    params = model.init(jax.random.key(0), prompt)
    want = _greedy_recompute(cfg, params, prompt, steps=6)
    dec = Decoder(cfg, max_len=16)
    got = dec.generate(params, prompt, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_temperature_sampling_shape_and_determinism():
    cfg = LlamaConfig.tiny()
    model = Llama(cfg)
    prompt = jnp.zeros((2, 3), jnp.int32)
    params = model.init(jax.random.key(0), prompt)
    dec = Decoder(cfg, max_len=12)
    key = jax.random.key(7)
    a = dec.generate(params, prompt, 4, temperature=0.8, key=key)
    b = dec.generate(params, prompt, 4, temperature=0.8, key=key)
    assert a.shape == (2, 7)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))  # same key
    with pytest.raises(ValueError, match="PRNG key"):
        dec.generate(params, prompt, 2, temperature=0.5)


def test_cache_length_enforced():
    cfg = LlamaConfig.tiny()
    model = Llama(cfg)
    prompt = jnp.zeros((1, 5), jnp.int32)
    params = model.init(jax.random.key(0), prompt)
    dec = Decoder(cfg, max_len=8)
    with pytest.raises(ValueError, match="exceeds the cache"):
        dec.generate(params, prompt, max_new_tokens=4)


async def test_generate_after_store_sync():
    """The RL flow end to end: trainer publishes weights, a generator pulls
    them through the store and decodes with the KV cache."""
    import torchstore_tpu as ts

    cfg = LlamaConfig.tiny()
    model = Llama(cfg)
    prompt = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.key(1), prompt)
    await ts.initialize(store_name="gen")
    try:
        await ts.put_state_dict("policy", params, store_name="gen")
        pulled = await ts.get_state_dict("policy", store_name="gen")
        pulled = jax.tree.map(jnp.asarray, pulled)
        dec = Decoder(cfg, max_len=16)
        got = dec.generate(pulled, prompt, max_new_tokens=5)
        want = dec.generate(params, prompt, max_new_tokens=5)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    finally:
        await ts.shutdown("gen")
