"""Tiny-size smoke test for bench.py (VERDICT r5: the round-5 bench crashed
AFTER all sections ran, so no headline was recorded and nothing failed in
CI). Executes the REAL ``run()`` code path — all three measured sections,
the latency loop, calibration, and the JSON assembly — on KB-scale tensors,
so a bench regression fails tier-1 instead of silently zeroing a round."""

import json
import pathlib
import sys

import numpy as np
import pytest

REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[1])


@pytest.mark.anyio
async def test_bench_run_tiny(capsys):
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.remove(REPO_ROOT)

    result = await bench.run(
        n_tensors=2,
        tensor_mb=0.0625,
        iters=2,
        calib_mb=1,
        lat_iters=4,
        many_keys_n=16,
        many_keys_kb=4,
        recovery_n_keys=8,
        recovery_key_kb=4,
        ledger_keys=16,
        ledger_reps=2,
        streamed_layers=4,
        streamed_layer_kb=4,
        streamed_train_ms=5.0,
        streamed_decode_ms=5.0,
        streamed_iters=1,
        capacity_versions=4,
        capacity_keys=4,
        capacity_key_kb=4,
        delta_tensors=4,
        delta_tensor_kb=16,
        delta_versions=3,
        meta_shard_counts=(1, 2),
        meta_drivers=2,
        meta_logical=2,
        meta_duration_s=0.5,
        fleet_drivers=2,
        fleet_logical=4,
        fleet_duration_s=1.2,
        fleet_volumes=2,
        fleet_gate_ms=2000.0,
        placement_drivers=2,
        placement_logical=4,
        placement_duration_s=1.2,
        placement_volumes=2,
    )

    # The headline record: the exact contract the driver parses.
    assert result["metric"] == "state_dict_weight_sync_round_trip"
    assert result["unit"] == "GB/s"
    assert result["value"] > 0
    assert result["vs_baseline"] > 0
    assert 0 < result["calib_ratio"] <= 1.0
    assert result["host_memcpy_gbps"] > 0
    # Section stats carry the rerun-on-WARN policy's full output.
    for section in ("buffered", "direct", "direct_registered"):
        stats = result["sections"][section]
        assert stats["median"] > 0
        assert {"best", "warm_min", "warm_cv", "warn", "reruns"} <= set(stats)
    assert result["p50_put_ms"] > 0 and result["p50_get_ms"] > 0

    # Machine-readable metrics snapshot sourced from the new registry, with
    # nonzero per-transport byte counters from the run itself.
    metrics = result["metrics"]
    tbytes = metrics["ts_transport_bytes_total"]["series"]
    put_bytes = sum(
        s["value"] for s in tbytes if s["labels"].get("op") == "put"
    )
    assert put_bytes >= 2 * 0.0625 * 1024 * 1024

    # The merged fleet snapshot rides the record too: process-labeled
    # series covering the controller and the volume, no scrape errors.
    fleet = result["fleet"]
    assert fleet["errors"] == {}
    procs = {p["process"] for p in fleet["processes"]}
    assert {"client", "controller", "volume"} <= procs
    vol_puts = [
        s
        for s in fleet["metrics"]["ts_volume_put_ops_total"]["series"]
        if s["labels"].get("process") == "volume"
    ]
    assert vol_puts and sum(s["value"] for s in vol_puts) > 0

    # Cold-path acceptance keys ride the headline JSON (ISSUE 3): the
    # ratios at top level, the full section under "cold". At KB scale the
    # RATIO values are noise — only structure and positivity are asserted
    # here; the >= 2x bar is the full-scale BENCH run's contract.
    assert result["cold_vs_steady"] > 0
    assert result["cold_prewarmed_vs_steady"] > 0
    cold = result["cold"]
    for key in (
        "cold_gbps",
        "cold_prewarmed_gbps",
        "steady_gbps",
        "prewarm_seconds",
    ):
        assert cold[key] > 0, (key, cold)
    assert cold["prewarm"]["ok"] is True
    assert cold["prewarm"]["errors"] == {}

    # Many-keys section (ISSUE 5): headline stats at top level, the full
    # section dict alongside. At KB scale the VALUES are noise — structure
    # and positivity only; the >=2x-vs-pre-PR bar is the full-scale run's.
    assert result["many_keys_gbps"] > 0
    assert result["per_key_put_us"] > 0
    assert result["many_keys"]["n_keys"] == 16
    assert result["many_keys"]["put_s"] > 0

    # One-sided get leg (ISSUE 7): per-key get cost, delivered get rate,
    # distance from the memcpy ceiling, and the warm 1KB p50 — all present
    # and positive (the <=0.35 ms / <=2.5x bars are the full-scale run's).
    assert result["per_key_get_us"] > 0
    assert result["many_keys_get_gbps"] > 0
    assert result["get_memcpy_ratio"] > 0
    assert result["p50_get_1kb_ms"] > 0

    # Decision-telemetry overhead (ISSUE 10): the always-on recorder +
    # ledger cost on the warm one-sided get leg. KB-scale values are
    # noise — structure only; the <=2% bar is the full-scale run's.
    assert "ledger_overhead_pct" in result
    lo = result["ledger_overhead"]
    assert lo["on_us_per_key"] > 0 and lo["off_us_per_key"] > 0
    assert lo["n_keys"] == 16

    # Streamed-sync section (ISSUE 9): overlap metrics at top level, the
    # full section under "streamed_sync". At KB scale the VALUES are noise
    # — structure + positivity of the wall clocks only; the overlap_ratio
    # > 0 acceptance is the standalone section test's (larger sleeps).
    assert result["streamed_sync"]["barrier_s"] > 0
    assert result["streamed_sync"]["streamed_s"] > 0
    assert "overlap_ratio" in result
    assert "first_token_after_publish_ms" in result

    # Recovery section (ISSUE 6): time-to-heal keys at top level, full
    # timings under "recovery" — a real kill + quarantine + auto-repair.
    assert result["heal_s"] > 0
    assert result["failover_get_s"] > 0
    rec = result["recovery"]
    assert rec["detect_s"] > 0 and rec["rereplicate_s"] > 0
    assert rec["victim_keys"] > 0

    # Tiered-capacity section (ISSUE 12): headline keys at top level, the
    # full section under "capacity". KB-scale TIMES are noise — structure,
    # positivity, and the structural invariants (working set over budget,
    # bytes actually spilled, zero warm get RPCs) are asserted; the
    # latency bars are the full-scale run's bench_compare contract.
    assert result["warm_get_after_spill_us"] > 0
    assert result["fault_in_p50_ms"] > 0
    assert result["spilled_bytes_ratio"] > 0
    cap = result["capacity"]
    assert cap["working_set_mb"] >= 2 * cap["budget_mb"]
    assert cap["spilled_bytes"] > 0
    assert cap["warm_get_rpcs"] == 0
    assert cap["fault_in_keys"] > 0

    # Quantized + delta wire tier (ISSUE 13): headline keys at top level,
    # the full section under "delta_sync". KB-scale SPEEDUPS are noise —
    # structure plus the structural compression/error invariants only; the
    # >=2x / >=3x bars are the full-scale run's bench_compare contract.
    assert result["delta_speedup_int8_block"] > 0
    assert result["delta_speedup_delta"] > 0
    assert result["delta_wire_compression_delta"] > 5.0
    assert result["delta_max_abs_err"] >= 0
    ds = result["delta_sync"]
    assert ds["delta_wire_compression_int8_block"] > 3.0
    assert ds["delta_max_abs_err_none"] == 0.0

    # Fleet-scale section (ISSUE 15): the section ASSERTS its own gates
    # (p99 under the SLO, telemetry budget under load, induced-violation
    # stage attribution) — reaching here means they held at smoke scale;
    # the headline keys must still ride the record.
    assert result["fleet_ops_per_s"] > 0
    assert result["fleet_get_p99_ms"] > 0
    assert isinstance(result["fleet_ledger_overhead_pct"], float)
    fs = result["fleet_scale"]
    assert fs["logical_clients"] == 8 and fs["drivers"] == 2
    assert fs["violation"]["dominant_stage"] == "landing"
    assert fs["violation"]["violations"] > 0

    # Placement section (ISSUE 16): the section asserts its own gates
    # (control_plan non-empty on the skewed workload, decisions applied,
    # zero failed drivers / op errors while keys migrate mid-leg) —
    # reaching here means they held at smoke scale; the headline keys
    # must still ride the record. The >=70%-recovery / <=1.5x-isolation
    # bars are the full-scale run's bench_compare contract.
    assert result["rebalance_recovery_ratio"] > 0
    assert result["migration_bytes"] >= 0
    pl = result["placement"]
    assert pl["plan_actions"], pl
    assert pl["decisions"], pl
    assert pl["by_tenant_skewed_on"], pl

    # The whole record (what bench prints as its one stdout JSON line)
    # must serialize.
    json.dumps(result)


@pytest.mark.anyio
async def test_bench_many_keys_section_tiny():
    """The many-keys section standalone at KB scale: the real arena/plan
    path through a real fleet, so the section can never ship broken."""
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.remove(REPO_ROOT)

    out = await bench.many_keys_section(n_keys=24, key_kb=4, iters=2)
    assert out["n_keys"] == 24
    assert out["many_keys_gbps"] > 0
    assert out["per_key_put_us"] > 0
    assert out["per_key_get_us"] > 0
    assert out["get_gbps"] > 0 and out["get_memcpy_ratio"] > 0
    assert out["put_s"] > 0 and out["get_s"] > 0
    json.dumps(out)


@pytest.mark.anyio
async def test_bench_recovery_section_tiny():
    """The recovery section standalone (``bench.py --recovery``) at KB
    scale: a real volume kill under load, supervisor detection, failover
    get, and automatic re-replication — so time-to-heal can never ship
    broken."""
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.remove(REPO_ROOT)

    out = await bench.recovery_section(n_keys=8, key_kb=4)
    assert out["detect_s"] > 0
    assert out["first_get_s"] > 0
    assert out["rereplicate_s"] >= out["detect_s"]
    assert out["heal_s"] == out["rereplicate_s"]
    json.dumps(out)


@pytest.mark.anyio
async def test_bench_streamed_sync_section_tiny():
    """The streamed-sync section standalone (``bench.py --streamed-sync``)
    at small scale with compute sleeps large enough to dominate host
    noise: the streamed leg must demonstrably overlap acquire with
    publish (overlap_ratio > 0 — the ISSUE-9 acceptance shape) and beat
    the barrier wall clock."""
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.remove(REPO_ROOT)

    out = await bench.streamed_sync_section(
        n_layers=4, layer_kb=8, train_ms=40.0, decode_ms=40.0, iters=1
    )
    assert out["barrier_s"] > 0 and out["streamed_s"] > 0
    # Train (4 x 40 ms) + decode (4 x 40 ms) serialize on the barrier path
    # and overlap on the streamed one: the win must be visible even on a
    # noisy host, and the acquire must overlap the publish window.
    assert out["overlap_ratio"] > 0, out
    assert out["streamed_s"] < out["barrier_s"], out
    assert (
        out["first_token_after_publish_ms"]
        < out["barrier_first_token_after_publish_ms"]
    ), out
    json.dumps(out)


@pytest.mark.anyio
async def test_bench_cold_path_section_tiny():
    """The cold-path section standalone (what ``bench.py --cold-path`` and
    tpu_watch's device capture run) at KB scale: real prewarm against real
    fleets, segments actually provisioned, both ratios computed — so the
    cold section can never ship broken (the r5 lesson)."""
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.remove(REPO_ROOT)

    cold = await bench.cold_path_section(
        n_tensors=2, tensor_mb=0.25, steady_iters=2
    )
    assert cold["prewarm"]["ok"] is True
    # 256 KB tensors sit at the arena threshold: both pack into ONE
    # provisioned arena segment (steady-state pipeline).
    assert cold["prewarm"]["segments"] == 1
    assert cold["prewarm"]["bytes"] == 2 * 256 * 1024
    assert cold["cold_gbps"] > 0 and cold["cold_prewarmed_gbps"] > 0
    assert cold["cold_vs_steady"] > 0
    assert cold["cold_prewarmed_vs_steady"] > 0
    json.dumps(cold)


@pytest.mark.anyio
async def test_bench_ledger_overhead_section_tiny():
    """The ledger_overhead section standalone at KB scale: real warm
    one-sided gets timed telemetry-on vs telemetry-off, and the toggles
    restored afterwards (a bench crash must never leave telemetry off)."""
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.remove(REPO_ROOT)
    from torchstore_tpu.observability import ledger as obs_ledger
    from torchstore_tpu.observability import recorder as obs_recorder

    out = await bench.ledger_overhead_section(n_keys=16, key_kb=4, reps=2)
    assert out["on_us_per_key"] > 0 and out["off_us_per_key"] > 0
    assert "overhead_pct" in out
    assert obs_ledger.ledger().enabled
    assert obs_recorder.recorder().enabled
    json.dumps(out)


@pytest.mark.anyio
async def test_bench_history_overhead_section_tiny():
    """The history_overhead section standalone at KB scale: real warm
    one-sided gets timed with the sampler+detectors hot (50 ms sweeps) vs
    disabled, and both the enabled flag and the interval env restored
    afterwards (a bench crash must never leave history off or stuck at
    the 20x sweep rate)."""
    import os

    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.remove(REPO_ROOT)
    from torchstore_tpu.observability import history as obs_history

    interval_before = os.environ.get(obs_history.ENV_HISTORY_INTERVAL)
    enabled_before = obs_history.series_store().enabled
    out = await bench.history_overhead_section(n_keys=16, key_kb=4, reps=2)
    assert out["on_us_per_key"] > 0 and out["off_us_per_key"] > 0
    assert "overhead_pct" in out
    assert out["sample_interval_s"] == 0.05
    # The ON legs actually retained series (the sampler ran hot).
    assert out["retained_series"] > 0
    assert os.environ.get(obs_history.ENV_HISTORY_INTERVAL) == interval_before
    assert obs_history.series_store().enabled == enabled_before
    json.dumps(out)


@pytest.mark.anyio
async def test_bench_capacity_section_tiny():
    """The capacity section standalone (``bench.py --capacity``) at KB
    scale: a real tier-enabled fleet whose working set is 2x the pool
    budget with one leased-hot version — the spill writer demotes the
    cold rest, the warm leased leg stays zero-RPC, and cold versions
    fault back in with the right bytes. The ISSUE-12 acceptance shape can
    never ship broken."""
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.remove(REPO_ROOT)

    out = await bench.capacity_section(n_versions=4, n_keys=4, key_kb=4)
    assert out["working_set_mb"] >= 2 * out["budget_mb"]
    assert out["spilled_bytes"] > 0 and out["spilled_bytes_ratio"] > 0
    # Warm leased-version reps issued ZERO get RPCs: the one-sided path
    # survived the spill sweep (the "unchanged warm latency" acceptance).
    assert out["warm_get_rpcs"] == 0, out
    assert out["warm_get_after_spill_us"] > 0
    assert out["fault_in_p50_ms"] > 0 and out["fault_in_keys"] > 0
    assert out["cold_versions_measured"], out
    json.dumps(out)


@pytest.mark.anyio
async def test_bench_delta_sync_section_tiny():
    """The delta_sync section standalone (``bench.py --delta-sync``) at KB
    scale: a real bulk-path fleet publishing at none / int8_block /
    int4_block+delta through the weight channel. Wire compression and the
    analytic dequant-error bound are structural (asserted inside the
    section too) — the ISSUE-13 acceptance shape can never ship broken.
    Speedups are not asserted here: at KB scale fixed costs dominate; the
    full-scale run + bench_compare own those numbers."""
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.remove(REPO_ROOT)

    out = await bench.delta_sync_section(
        n_tensors=4, tensor_kb=16, versions=4, dcn_gbps=0.05
    )
    assert out["delta_none_gbps"] > 0
    assert out["delta_max_abs_err_none"] == 0.0
    # Structural: int8 blobs are ~4x smaller than f32 (minus header/scale
    # overhead), the low-churn delta leg far smaller still.
    assert out["delta_wire_compression_int8_block"] > 3.0, out
    assert out["delta_wire_compression_int4_delta"] > 5.0, out
    # The in-section analytic bound already asserted; keep the headline
    # fields present and finite for bench_compare.
    for k in ("delta_speedup_int8_block", "delta_speedup_delta",
              "delta_max_abs_err"):
        assert isinstance(out[k], float) and out[k] >= 0, (k, out[k])
    json.dumps(out)


@pytest.mark.anyio
async def test_bench_fanout_section_tiny():
    """The fanout section standalone (``bench.py --fanout``) at KB scale:
    a real K-fleet broadcast against real per-"host" volumes, both legs
    measured from the traffic matrix — the ISSUE-11 acceptance bound
    (tree/p2p trainer-host egress <= 1.5/K) and the deep-hop overlap
    (first layers before the seal through >= 2 relay hops) can never
    ship broken."""
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.remove(REPO_ROOT)

    out = await bench.fanout_section(
        k_fleets=4, n_layers=4, layer_kb=16, train_ms=40.0
    )
    assert out["p2p_trainer_egress_mb"] > 0
    assert out["fanout_egress_ratio"] is not None
    # O(1) trainer-host egress: the acceptance bound, not just a trend.
    assert out["fanout_egress_ratio"] <= out["egress_bound"], out
    # The deepest fleet sits >= 2 relay hops from the origin and still
    # overlaps the publish window (layers flow per hop, not per version).
    assert out["relay_hops"] >= 2, out
    assert out["fanout_overlap_ratio"] > 0, out
    json.dumps(out)


@pytest.mark.anyio
async def test_bench_metadata_scale_section_tiny():
    """The metadata_scale section standalone (``bench.py
    --metadata-scale``) at tiny load: real multi-process drivers against a
    real 1-shard and 2-shard fleet — the fan-out spawn/drive/merge
    machinery behind the ISSUE-14 acceptance (>= 2.5x locate/notify
    throughput at 4 shards, measured at full scale) can never ship
    broken. At smoke scale the load is driver-bound, so only positivity
    and shape are asserted, never the scaling factor itself."""
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.remove(REPO_ROOT)

    out = await bench.metadata_scale_section(
        shard_counts=(1, 2), n_drivers=2, n_logical=2, duration_s=0.5
    )
    assert out["metadata_ops_per_s_1shard"] > 0, out
    assert out["metadata_ops_per_s_sharded"] > 0, out
    assert out["metadata_scale_x"] > 0, out
    for leg in out["legs"].values():
        assert leg["failed_drivers"] == 0, leg
        assert leg["mix"]["locate"] > 0 and leg["mix"]["notify"] > 0, leg
        assert leg["mix"]["poll"] > 0, leg
    json.dumps(out)


@pytest.mark.anyio
async def test_bench_fleet_scale_section_tiny():
    """The fleet_scale section standalone (``bench.py --fleet-scale``) at
    tiny load: real loadgen driver processes against a real 2-volume
    fleet. The section asserts its own acceptance gates internally — p99
    under the SLO gate, the under-load telemetry budget (<= 2% plus the
    run's own demonstrated measurement-noise floor), zero failed drivers
    / op errors, and the induced ``shm.landing_stamp`` violation naming
    the landing stage — so this smoke proves the assertions themselves
    can never ship broken. The >= 1k-clients-over->=8-drivers bar is the
    full-scale run's contract (its defaults: 8 x 128)."""
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.remove(REPO_ROOT)

    out = await bench.fleet_scale_section(
        n_drivers=2,
        n_logical=4,
        duration_s=1.2,
        n_volumes=2,
        shared_keys=16,
        rate_hz=10.0,
        get_p99_gate_ms=2000.0,
        overhead_reps=8,
        violation_duration_s=1.0,
    )
    assert out["fleet_ops_per_s"] > 0, out
    assert 0 < out["fleet_get_p99_ms"] < out["get_p99_gate_ms"], out
    assert out["by_op"]["get"]["count"] > 0, out
    assert out["by_op"]["put"]["count"] > 0, out
    assert out["violation"]["dominant_stage"] == "landing", out["violation"]
    assert out["violation"]["violations"] > 0, out["violation"]
    assert "noise_floor_pct" in out["ledger_overhead_under_load"], out
    json.dumps(out)


@pytest.mark.anyio
async def test_bench_placement_section_tiny():
    """The placement section standalone (``bench.py --placement``) at
    tiny load: real loadgen driver processes with tenant cohorts and a
    Zipf-skewed key pick against a real 2-volume fleet, the control
    engine planning and acting through ``ts.control_plan`` /
    ``ts.rebalance``. The section asserts its own acceptance internally
    — non-empty plan on skew, at least one decision applied, zero failed
    drivers / op errors while a rebalance rides inside the skewed leg —
    so this smoke proves those assertions can never ship broken. The
    >= 70% recovery / <= 1.5x isolation bars are the full-scale run's
    bench_compare contract."""
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.remove(REPO_ROOT)

    out = await bench.placement_section(
        n_drivers=2,
        n_logical=4,
        duration_s=1.2,
        n_volumes=2,
        value_kb=8.0,
        shared_keys=16,
        rate_hz=10.0,
        tenants=2,
        zipf_alpha=1.6,
        rebalance_rounds=2,
    )
    assert out["uniform_ops_per_s"] > 0, out
    assert out["skewed_on_ops_per_s"] > 0, out
    assert out["rebalance_recovery_ratio"] > 0, out
    assert out["plan_actions"], out
    acted = [
        d
        for d in out["decisions"]
        if str(d.get("outcome", "")).startswith(("applied", "deferred"))
    ]
    assert acted, out["decisions"]
    # Tenant labels flow through to the merged scoreboard: both cohorts
    # observed ops, and the quiet tenant carries its own get p99.
    tenants = out["by_tenant_skewed_on"]
    assert set(tenants) == {"t0", "t1"}, tenants
    assert all(row["count"] > 0 for row in tenants.values()), tenants
    assert out["migration_bytes"] >= 0, out
    json.dumps(out)


@pytest.mark.anyio
async def test_bench_autoscale_section_tiny():
    """The autoscale section standalone (``bench.py --autoscale``) at
    tiny load: real diurnal loadgen drivers against a real fleet, the
    autoscale engine scaling 1 -> N -> back while the sampler integrates
    volume-seconds, then blob checkpoint -> full teardown -> cold
    restore. The section asserts its own acceptance internally — zero
    failed drivers / op errors, p99 under the gate, the fleet actually
    breathed, the volume-seconds gate, byte-valid restore — so this
    smoke proves those assertions can never ship broken. The <= 0.60
    elasticity dividend is the full-scale run's bench_compare contract;
    the smoke's gate is relaxed (2-volume ceiling leaves little room)."""
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.remove(REPO_ROOT)

    out = await bench.autoscale_section(
        n_drivers=2,
        n_logical=4,
        period_s=3.0,
        periods=1.0,
        n_volumes_fixed=2,
        value_kb=8.0,
        shared_keys=8,
        base_rate_hz=1.0,
        peak_rate_hz=40.0,
        get_p99_gate_ms=2000.0,
        out_window_mb=0.5,
        idle_window_mb=0.25,
        ledger_window_s=1.0,
        volume_seconds_gate=1.05,
        autoscale_tick_s=0.3,
        settle_s=3.0,
    )
    assert out["peak_fleet"] > 1, out
    assert out["final_fleet"] < out["peak_fleet"], out
    assert 0 < out["autoscale_volume_seconds_ratio"] <= 1.05, out
    assert 0 < out["autoscale_get_p99_ms"] < out["get_p99_gate_ms"], out
    assert out["cold_restore_s"] > 0, out
    assert out["restored_keys"] > 0, out
    json.dumps(out)


@pytest.mark.anyio
async def test_bench_cross_host_section_tiny():
    """The cross_host section standalone (``bench.py --cross-host``) at KB
    scale: an emulated 3-host topology over a paced 0.2 Gbps DCN, real
    metadata mirrors fanned through the relay tree and a real push
    session staging layers ahead of the read. The ISSUE-20 acceptance
    trio — push first-layer >= 2x faster than doorbell-pull, zero warm
    metadata RPCs, index-host egress <= 1.5/K of delivered mirror bytes
    — is asserted here at smoke scale so it can never ship broken."""
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.remove(REPO_ROOT)

    out = await bench.cross_host_section(
        k_hosts=3, layer_kb=64, rounds=2, emulate_gbps=0.05
    )
    # Push-staged reads skip the paced wire entirely; even at 64 KB the
    # doorbell leg pays ~1.3 ms of emulated DCN the push leg does not.
    assert out["push_speedup"] >= 2.0, out
    assert out["push_serves"] > 0, out
    # Warm remote gets resolve everything against the local mirror: no
    # metadata RPC counter cell moved (dict of moved cells, empty = none).
    assert not out["warm_metadata_rpcs"], out
    # Relay tree: root serves one image copy regardless of subscribers.
    assert out["meta_egress_ratio"] <= out["meta_egress_bound"], out
    json.dumps(out)
