"""Layer-streamed weight sync: publish and acquire as a pipeline.

The barrier protocol (state_dict_utils) publishes a whole state dict, THEN
readers acquire a whole state dict — RL iteration time is train + sync +
generate with zero overlap. This module makes sync a pipeline instead:

- :class:`StreamedPut` accepts tensors incrementally (per layer, or per
  arena batch) as they become ready and pushes each batch immediately.
  Every batch's metadata notify carries a **per-key version watermark**
  (``Controller.notify_put_batch(watermark=...)``), so partial versions are
  first-class: a store key is trusted at version v the moment its bytes are
  committed AND watermarked, long before the dict is complete. ``seal()``
  writes the classic MAPPING commit marker last (barrier readers are
  untouched — they still wake only on a complete dict) plus the terminal
  ``stream_seal`` record.

- :func:`get_state_dict_streamed` acquires layer by layer: a long-poll on
  the controller (``wait_for_stream`` — notify-woken, never a spin) hands
  back each batch of freshly watermarked keys, which are fetched through
  the normal data plane (warm layers ride the one-sided stamped-read path
  with zero RPCs) and optionally handed to an ``on_layer`` callback in
  model-forward order — generation starts before the last layer lands.

Consistency: a reader NEVER mixes generations. Every served key must carry
the exact target version watermark; a key watermarked newer (a faster
publisher overwrote it mid-acquire), a superseded stream, or a final
re-check mismatch restarts the acquire at the newest version — loudly
(``ts_stream_fallbacks_total``), bounded by ``config.stream_retries`` —
exactly the fallback-ladder discipline of the one-sided data plane.

Watermark reads are concentrated HERE: acquire-side code elsewhere must go
through :func:`watermark_of` / :func:`inconsistent_keys` (enforced by the
tslint ``stream-discipline`` rule) so the consistency proof has one home.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional

from torchstore_tpu import faults
from torchstore_tpu.logging import get_logger
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import recorder as obs_recorder
from torchstore_tpu.observability import timeline as obs_timeline
from torchstore_tpu.observability.tracing import span
from torchstore_tpu.utils import maybe_await

logger = get_logger("torchstore_tpu.stream_sync")

_LAYER_BATCHES = obs_metrics.counter(
    "ts_stream_layer_batches_total",
    "Streamed layer batches published (watermarked put batches)",
)
_SEALS = obs_metrics.counter(
    "ts_stream_seals_total", "Streamed publishes sealed"
)
_ACQUIRES = obs_metrics.counter(
    "ts_stream_acquires_total", "Streamed acquires completed consistently"
)
_FALLBACKS = obs_metrics.counter(
    "ts_stream_fallbacks_total",
    "Streamed acquires that fell back or restarted, by reason",
)
# Per-subscriber stream lag: store keys watermarked at the target version
# but not yet served by this process's in-flight streamed acquire. Moves
# during every stream (publisher ahead of consumer) and settles at 0.
_LAG = obs_metrics.gauge(
    "ts_stream_lag_keys",
    "Watermarked-but-unserved keys in this process's streamed acquire",
)
# The bench-only numbers turned production signals (ISSUE 10): how much of
# the publish window this subscriber's acquire overlapped, and how long its
# first layer took after stream begin — both per completed streamed
# acquire, SLO-checked against TORCHSTORE_TPU_SLO_OVERLAP_MIN /
# _SLO_FIRST_LAYER_MS.
_OVERLAP = obs_metrics.gauge(
    "ts_stream_overlap_ratio",
    "Fraction of the publish window the last streamed acquire ran inside",
)
_FIRST_LAYER = obs_metrics.gauge(
    "ts_stream_first_layer_seconds",
    "Stream begin to this subscriber's first served layer",
)


class MixedGenerationError(RuntimeError):
    """A streamed acquire could not complete a single-generation serve."""


class _Restart(Exception):
    """Internal: restart the acquire at the newest stream version."""

    def __init__(self, reason: str) -> None:
        super().__init__(reason)
        self.reason = reason


# --------------------------------------------------------------------------
# blessed watermark accessors (tslint stream-discipline)
# --------------------------------------------------------------------------


def watermark_of(state: Optional[dict], store_key: str) -> Optional[int]:
    """The version whose bytes a store key currently holds, per the stream
    record — None when unknown (never watermarked, or record gone)."""
    if state is None:
        return None
    return (state.get("watermarks") or {}).get(store_key)


def inconsistent_keys(
    state: Optional[dict], store_keys, version: int
) -> list[str]:
    """Store keys whose watermark does NOT equal ``version`` — the served
    set is a consistent single-generation snapshot iff this is empty."""
    return [sk for sk in store_keys if watermark_of(state, sk) != version]


# --------------------------------------------------------------------------
# publish side
# --------------------------------------------------------------------------


def _merge_mapping(a: dict, b: dict) -> dict:
    """Merge two flatten-mapping templates from different fragments of one
    streamed publish. Dict containers merge per child; any other container
    kind must arrive whole in one fragment (its leaves would otherwise
    collide as duplicate flat keys anyway)."""
    if a["kind"] != b["kind"]:
        raise ValueError(
            "streamed fragments disagree on container structure "
            f"({a['kind']!r} vs {b['kind']!r})"
        )
    if a["kind"] == "dict":
        items = dict(a["items"])
        for k, v in b["items"].items():
            items[k] = _merge_mapping(items[k], v) if k in items else v
        key_types = dict(a.get("key_types", {}))
        key_types.update(b.get("key_types", {}))
        return {"kind": "dict", "items": items, "key_types": key_types}
    if a == b:
        return a
    raise ValueError(
        "streamed fragments overlap inside a non-dict container; publish "
        "list/tuple containers whole in one fragment"
    )


class StreamedPut:
    """One streamed publish of a state dict under ``key``.

    >>> stream = stream_state_dict(client, "policy/sd")
    >>> for name, layer in trainer.layers():        # as they become ready
    ...     await stream.put({"layers": {name: layer}})
    >>> await stream.seal()

    ``put`` accepts nested fragments; flat keys must be disjoint across
    fragments (a layer is published exactly once per stream). ``seal``
    writes the MAPPING commit marker LAST — barrier readers still only ever
    see complete dicts — and the controller's terminal seal record. An
    abandoned stream (publisher crash before ``seal``) leaves the previous
    sealed version fully acquirable: readers only trust watermarked keys,
    and barrier readers key on the absent/old marker.
    """

    def __init__(
        self,
        client,
        key: str,
        transfer_dtype=None,
        transfer_quant: Optional[str] = None,
        delta_ctx: Optional[dict] = None,
    ) -> None:
        from torchstore_tpu import state_dict_utils as sdu

        self._client = client
        self.key = key
        self.version: Optional[int] = None
        self._transfer_dtype = transfer_dtype
        config = getattr(client, "_config", None)
        self._quant = sdu.resolve_transfer_quant(
            transfer_quant, transfer_dtype, config
        )
        if self._quant is not None and transfer_dtype is not None:
            raise ValueError(
                "transfer_quant and transfer_dtype are mutually exclusive "
                "(quantization defines the wire format)"
            )
        if delta_ctx is not None and self._quant not in (
            "int8_block", "int4_block"
        ):
            raise ValueError(
                "delta streaming requires transfer_quant "
                f"int8_block/int4_block (got {self._quant!r})"
            )
        self._qblock = getattr(config, "quant_block", 256) if config else 256
        self._delta_ctx = delta_ctx
        self._qkeys: list[str] = []
        self._qdtypes: dict[str, str] = {}
        self._aliases: dict[str, int] = {}  # flat key -> base channel version
        self._mapping: Optional[dict] = None
        self._leaf_sigs: dict[str, tuple] = {}
        self._sealed = False

    async def begin(self) -> int:
        """Open the stream on the controller (implicit on first ``put``).
        Eager ``begin()`` lets consumers start their long-poll before the
        first layer is even trained."""
        if self.version is None:
            quant = None
            if self._quant is not None:
                # Static decode meta readers need BEFORE the seal's commit
                # marker exists: which wire format, and — for delta — the
                # channel whose version directory the chain walks.
                quant = {
                    "fmt": self._quant,
                    "block": self._qblock,
                    "delta": (
                        {
                            "channel": self._delta_ctx["channel"],
                            "version": int(self._delta_ctx["version"]),
                        }
                        if self._delta_ctx is not None
                        else None
                    ),
                }
            self.version = await self._client.stream_begin(
                self.key, quant=quant
            )
        return self.version

    @property
    def published_keys(self) -> list[str]:
        return sorted(self._leaf_sigs)

    async def put(self, fragment: Any) -> int:
        """Publish one fragment (nested dict / flat dict of leaves) and
        watermark every key at this stream's version. Returns the number
        of flat keys pushed. Safe to call from the training loop the
        moment a layer's tensors stop changing."""
        from torchstore_tpu import state_dict_utils as sdu

        await faults.afire("channel.publish_layer")
        if self._sealed:
            raise RuntimeError(f"stream for {self.key!r} is already sealed")
        version = await self.begin()
        flat, mapping = sdu.flatten_state_dict(fragment)
        if not flat:
            return 0
        if sdu.MAPPING_KEY in flat:
            raise ValueError(
                f"{sdu.MAPPING_KEY!r} is a reserved top-level state-dict "
                "key (it is the commit marker); rename that entry"
            )
        dup = sorted(set(flat) & set(self._leaf_sigs))
        if dup:
            raise ValueError(
                f"flat keys republished within one stream: {dup[:5]} — a "
                "layer is published exactly once per stream"
            )
        self._mapping = (
            mapping
            if self._mapping is None
            else _merge_mapping(self._mapping, mapping)
        )
        for k, v in flat.items():
            self._leaf_sigs[k] = sdu._leaf_signature(v)
        if self._transfer_dtype is not None:
            flat = sdu.cast_floating_tensors(flat, self._transfer_dtype)
        fragment_aliases: dict[str, tuple] = {}
        if self._quant is not None:
            flat, fragment_aliases = await self._encode_quant(flat, sdu)
        n_keys = len(flat) + len(fragment_aliases)
        with span(
            "stream.publish_layer",
            key=self.key,
            version=version,
            keys=n_keys,
        ):
            if flat:
                await self._client.put_batch(
                    {sdu._store_key(self.key, k): v for k, v in flat.items()},
                    watermark=(self.key, version),
                    unchanged=fragment_aliases or None,
                )
            elif fragment_aliases:
                # Every key of this fragment is unchanged: no bytes land,
                # the aliases alone watermark the keys (their base bytes
                # committed with a previous version's notify).
                await self._client.stream_mark_unchanged(
                    self.key, version, fragment_aliases
                )
        _LAYER_BATCHES.inc()
        return n_keys

    async def _encode_quant(
        self, flat: dict, sdu
    ) -> tuple[dict, dict[str, tuple]]:
        """Quantize one fragment's floating leaves into wire blobs.
        Returns (flat_to_put, unchanged_aliases): delta-unchanged keys ship
        NOTHING — they are aliased (new store key -> base store key) for
        the same watermark step."""
        from torchstore_tpu import torch_interop

        out: dict = {}
        aliases: dict[str, tuple] = {}
        codec = (self._delta_ctx or {}).get("codec")
        for fk, value in flat.items():
            if torch_interop.is_torch_tensor(value):
                value = torch_interop.to_numpy_view(value)
            if not sdu._is_floating(value):
                out[fk] = value
                continue
            sdu._guard_quantizable(fk, value)
            self._qkeys.append(fk)
            self._qdtypes[fk] = str(value.dtype)
            if codec is not None:
                version = int(self._delta_ctx["version"])
                blob, base = await codec.encode(fk, value, version)
                if blob is None:
                    self._aliases[fk] = int(base)
                    new_sk = sdu._store_key(self.key, fk)
                    base_sk = sdu._store_key(
                        sdu._delta_version_key(
                            self._delta_ctx["channel"], base
                        ),
                        fk,
                    )
                    aliases[new_sk] = (base_sk, int(base))
                    continue
                out[fk] = blob
            else:
                blob, _, _, _ = sdu._encode_keyframe_blob(
                    fk, value, self._quant,
                    sdu._quant_leaf_block(self._quant, self._qblock, value),
                )
                sdu._record_quant_bytes(
                    self._quant, getattr(value, "nbytes", 0), blob.nbytes
                )
                out[fk] = blob
        return out, aliases

    async def seal(self) -> int:
        """Write the terminal records: the MAPPING commit marker (barrier
        readers wake on a complete dict, exactly as before) then the
        controller's seal. Returns the stream version. Idempotent."""
        from torchstore_tpu import state_dict_utils as sdu

        if self._sealed:
            return self.version
        if self._mapping is None:
            raise RuntimeError("seal() before any put(): nothing to commit")
        # Plan-cache discipline mirrors put_state_dict: a restructure this
        # client cannot PROVE unchanged (dropped keys delete nothing, so
        # the index alone cannot see it) bumps the placement epoch so
        # consumers' cached get plans never serve the old structure.
        cache = getattr(self._client, "plan_cache", None)
        signature = tuple(sorted(self._leaf_sigs.items())) + (
            ("cast", str(self._transfer_dtype), self._quant, self._qblock),
        )
        if cache is not None:
            if cache.last_put_sig.get(self.key) != signature:
                await self._client.bump_placement_epoch()
            cache.last_put_sig[self.key] = signature
        else:
            await self._client.bump_placement_epoch()
        marker = {
            "mapping": self._mapping,
            "stream": {"version": self.version},
        }
        if self._quant is not None:
            quant_meta: dict = {
                "fmt": self._quant,
                "block": self._qblock,
                "keys": self._qkeys,
                "dtypes": self._qdtypes,
            }
            if self._delta_ctx is not None:
                quant_meta["delta"] = {
                    "channel": self._delta_ctx["channel"],
                    "version": int(self._delta_ctx["version"]),
                    "aliases": dict(self._aliases),
                }
            marker["quant"] = quant_meta
        with span(
            "stream.seal",
            key=self.key,
            version=self.version,
            keys=len(self._leaf_sigs),
        ):
            await self._client.put(
                sdu._store_key(self.key, sdu.MAPPING_KEY), marker
            )
            await self._client.stream_seal(self.key, self.version)
        self._sealed = True
        _SEALS.inc()
        return self.version


def stream_state_dict(
    client,
    key: str,
    transfer_dtype=None,
    transfer_quant: Optional[str] = None,
    delta_ctx: Optional[dict] = None,
) -> StreamedPut:
    """Open an incremental (layer-streamed) publish of ``key``."""
    return StreamedPut(
        client,
        key,
        transfer_dtype=transfer_dtype,
        transfer_quant=transfer_quant,
        delta_ctx=delta_ctx,
    )


# --------------------------------------------------------------------------
# acquire side
# --------------------------------------------------------------------------


async def get_state_dict_streamed(
    client,
    key: str,
    user_state_dict: Any = None,
    key_order: Optional[list[str]] = None,
    on_layer: Optional[Callable[[str, Any], Any]] = None,
    strict: bool = True,
    timeout: Optional[float] = None,
    wait_for_stream_s: Optional[float] = None,
    relay_volume: Optional[str] = None,
    delta_state: Any = None,
) -> Any:
    """Acquire a streamed state dict layer by layer.

    ``delta_state`` (a ``state_dict_utils.DeltaDecoder``) is this reader's
    accumulated delta-tier state: quantized layers decode through it, and
    unchanged-key layers (aliased to v-1 bytes) are served straight from
    the accumulation with ZERO re-transfer. Without it, an ephemeral
    decoder chain-fetches baselines as needed (fresh-joiner semantics).

    ``relay_volume`` routes the acquire through this host's BROADCAST
    RELAY copy (see torchstore_tpu/relay.py): the long-poll reports a
    layer ready only once the tree has landed it on that volume, and the
    fetch prefers that replica — so K fleets cost O(1) trainer-host
    egress instead of K×. Fail-safe: when the volume is not a live relay
    member the gate is ignored and reads serve from the origin volumes.

    Each store key is fetched the moment its watermark lands (long-poll on
    the controller — notify-woken, no spin; warm layers are served by the
    one-sided stamped-read path with zero RPCs). ``key_order`` (typically
    model-forward order, e.g. ``StateDictManifest.key_order`` or
    ``models.generate.forward_key_order``) makes delivery IN-ORDER: layer
    k+1 is held until layer k has been served, so an ``on_layer`` callback
    can start forward computation before the last layer lands. Without
    ``key_order``, layers are served in arrival order.

    ``on_layer(flat_key, value)`` (sync or async) runs once per leaf as it
    is served. ``wait_for_stream_s`` long-polls for the stream to BEGIN
    when no record exists yet (a consumer starting before the publisher's
    first layer); with no record and no wait budget, this falls back to
    the barrier ``get_state_dict`` path.

    ``key_order`` should list only keys this publish will actually write:
    an entry the publisher never pushes blocks in-order delivery of its
    successors until the seal (only the seal proves it absent), costing
    the publish/decode overlap — though delivery still completes, in
    key_order positions, and the dict is still validated complete.

    Never mixes generations: every served key must carry the target
    version's watermark, re-verified once after the final layer; any drift
    restarts at the newest version (``config.stream_retries`` budget) and
    then fails loudly with :class:`MixedGenerationError`.
    """
    from torchstore_tpu.config import default_config
    from torchstore_tpu.state_dict_utils import get_state_dict

    config = getattr(client, "_config", None) or default_config()
    retries = max(0, int(config.stream_retries))
    deadline = None if timeout is None else time.monotonic() + timeout
    for attempt in range(retries + 1):
        state = await client.stream_state(key)
        if state is None and wait_for_stream_s:
            try:
                res = await client.wait_for_stream(
                    key, 1, -1, timeout=wait_for_stream_s
                )
            except TimeoutError:
                res = {"missing": True}
            if not res.get("missing"):
                state = await client.stream_state(key)
        if state is None:
            # Never streamed (or the record was evicted / lost to a
            # controller restart): the barrier path owns the serve — and
            # the loud NoMatchingPush when nothing was pushed at all.
            _FALLBACKS.inc(reason="no_stream")
            return await get_state_dict(
                client, key, user_state_dict, strict=strict,
                delta_state=delta_state,
            )
        target = int(state["version"])
        try:
            return await _acquire_stream(
                client,
                key,
                target,
                user_state_dict,
                key_order,
                on_layer,
                strict,
                deadline,
                config,
                relay_volume=relay_volume,
                delta_state=delta_state,
            )
        except _Restart as exc:
            _FALLBACKS.inc(reason=exc.reason)
            _LAG.set(0)
            obs_recorder.record(
                "error",
                "stream_restart",
                key=key,
                version=target,
                reason=exc.reason,
            )
            logger.warning(
                "streamed acquire of %r v%d restarting (%s; attempt %d/%d)",
                key,
                target,
                exc.reason,
                attempt + 1,
                retries + 1,
            )
            if exc.reason in ("incomplete_seal", "marker_drift"):
                # Retrying cannot help here: "incomplete_seal" means the
                # publisher sealed without rewriting every mapping key this
                # stream (e.g. skipped unchanged layers) — a single-
                # generation streamed serve is impossible BY CONSTRUCTION;
                # "marker_drift" means the commit marker belongs to a
                # different publish than the stream record (typically a
                # BARRIER put over a previously streamed key, whose
                # notifies never touch the record) and would drift
                # identically on every attempt. The barrier path serves
                # the dict as of the commit marker, classic semantics.
                return await get_state_dict(
                    client, key, user_state_dict, strict=strict,
                    delta_state=delta_state,
                )
            continue
    # A wedged/mixed stream is a postmortem-grade event: flush the flight
    # ring before surfacing so "what happened in the last five seconds"
    # is on disk even if the caller dies on the raise.
    obs_recorder.record("error", "stream_wedged", key=key)
    obs_recorder.dump_postmortem("wedged_stream")
    raise MixedGenerationError(
        f"streamed acquire of {key!r} could not complete a consistent "
        f"single-generation serve in {retries + 1} attempts (publishers "
        "are overwriting keys faster than this consumer acquires them)"
    )


async def _acquire_stream(
    client,
    key: str,
    target: int,
    user_state_dict: Any,
    key_order: Optional[list[str]],
    on_layer,
    strict: bool,
    deadline: Optional[float],
    config,
    relay_volume: Optional[str] = None,
    delta_state: Any = None,
) -> Any:
    from torchstore_tpu import state_dict_utils as sdu

    user_flat = user_mapping = None
    if user_state_dict is not None:
        user_flat, user_mapping = sdu.flatten_state_dict(user_state_dict)
    # store key -> (flat key, fetch target): with a user dict only its keys
    # are fetched (subset pulls under strict=False, in-place landings).
    targets_of: dict[str, Any] = {}
    flat_of: dict[str, str] = {}
    if user_flat is not None:
        for fk, v in user_flat.items():
            sk = sdu._store_key(key, fk)
            flat_of[sk] = fk
            targets_of[sk] = v if sdu._is_fetch_target(v) else None
    prefix_len = len(key) + len(sdu._SEP)
    ordered_sks = (
        [sdu._store_key(key, fk) for fk in key_order] if key_order else None
    )
    served: dict[str, Any] = {}  # flat key -> value
    served_sks: list[str] = []
    served_set: set[str] = set()
    known = 0
    sealed = False
    poll = max(0.1, float(config.stream_poll_s))
    first_serve_ts: Optional[float] = None
    # Quantized stream: the record's static meta (registered at
    # stream_begin) drives per-layer blob decode BEFORE the seal's marker
    # exists; the reader's decoder accumulates delta state and serves
    # unchanged-alias keys with zero re-transfer.
    qmeta: Optional[dict] = None
    decoder = None
    qchannel: Optional[str] = None
    alias_of: dict[str, tuple] = {}  # new store key -> (base sk, base ver)

    def _adopt_quant(meta: Optional[dict]) -> None:
        nonlocal qmeta, decoder, qchannel
        if meta is None or qmeta is not None:
            return
        qmeta = meta
        decoder = delta_state if delta_state is not None else sdu.DeltaDecoder()
        qchannel = (meta.get("delta") or {}).get("channel")

    async def _decode_one(fk: str, raw: Any):
        """Raw fetched value -> user-facing value (quant streams only).
        Non-blob values (non-floating leaves) pass through untouched."""
        info = sdu.parse_quant_blob(raw)
        if info is None:
            return raw
        st = await decoder.decode(
            fk, info, fetch_base=sdu._chain_fetcher(client, qchannel, fk)
        )
        user_leaf = user_flat.get(fk) if user_flat is not None else None
        return sdu._quant_result(
            st, user_leaf if sdu._is_fetch_target(user_leaf) else None
        )

    async def serve(sks: list[str]) -> None:
        nonlocal first_serve_ts
        if user_flat is not None:
            sks = [sk for sk in sks if sk in flat_of]
        if not sks:
            return
        to_fetch: dict[str, tuple] = {}  # sk -> (fetch key, target)
        local_vals: dict[str, Any] = {}
        for sk in sks:
            fk = flat_of.get(sk, sk[prefix_len:])
            alias = alias_of.get(sk) if qmeta is not None else None
            if alias is not None:
                st = decoder.serve_unchanged(fk, alias[1])
                if st is not None:
                    # Bit-identical v-1 bytes already accumulated: serve
                    # from local state, ZERO re-transfer.
                    user_leaf = (
                        user_flat.get(fk) if user_flat is not None else None
                    )
                    local_vals[sk] = sdu._quant_result(
                        st,
                        user_leaf
                        if sdu._is_fetch_target(user_leaf)
                        else None,
                    )
                    continue
                to_fetch[sk] = (alias[0], None)
            elif qmeta is not None:
                # Floating leaves of a quant stream are blobs: fetch raw,
                # decode lands in place. Non-floating leaves ship raw and
                # keep their in-place targets.
                tgt = targets_of.get(sk)
                if tgt is not None and not sdu._is_floating(tgt):
                    to_fetch[sk] = (sk, tgt)
                else:
                    to_fetch[sk] = (sk, None)
            else:
                to_fetch[sk] = (sk, targets_of.get(sk))
        fetched = {}
        if to_fetch:
            fetched = await client.get_batch(
                {src: tgt for src, tgt in to_fetch.values()},
                _seed_plan=False,
                # Nearest-copy routing: the relay tree landed this host's
                # own replica — read it instead of the origin volumes.
                prefer_volume=relay_volume,
            )
        if first_serve_ts is None:
            first_serve_ts = time.time()
        for sk in sks:
            fk = flat_of.get(sk, sk[prefix_len:])
            if sk in local_vals:
                value = local_vals[sk]
            else:
                value = fetched[to_fetch[sk][0]]
                if qmeta is not None:
                    value = await _decode_one(fk, value)
            served[fk] = value
            served_sks.append(sk)
            served_set.add(sk)
            if on_layer is not None:
                await maybe_await(on_layer(fk, value))

    with span("stream.acquire", key=key, version=target):
        while not sealed:
            remaining = (
                None if deadline is None else deadline - time.monotonic()
            )
            if remaining is not None and remaining <= 0:
                raise TimeoutError(
                    f"streamed acquire of {key!r} v{target} timed out with "
                    f"{len(served_sks)} layer(s) served"
                )
            chunk = poll if remaining is None else min(poll, remaining)
            t_wait = time.perf_counter()
            try:
                res = await client.wait_for_stream(
                    key, target, known, timeout=chunk, volume_id=relay_volume
                )
            except TimeoutError:
                continue  # re-poll (refreshes lag + deadline accounting)
            finally:
                # Stage attribution: time this acquire spent blocked on
                # per-key watermarks (stamped poll or RPC long-poll) — the
                # dominant stage of a starved subscriber.
                obs_timeline.observe_stage(
                    "stream", "watermark_wait", time.perf_counter() - t_wait
                )
            if res.get("missing"):
                # Record evicted/reset mid-acquire: restart; the outer loop
                # re-reads the state and falls back to the barrier path.
                raise _Restart("stream_gone")
            if res["superseded"]:
                raise _Restart("superseded")
            _adopt_quant(res.get("quant"))
            alias_of.update(res.get("aliases") or {})
            ready = res["ready"]
            known = len(ready)
            drift = inconsistent_keys(res, ready, target)
            if drift:
                # A key already watermarked NEWER than our target: serving
                # it would mix generations — restart at the new version.
                raise _Restart("mixed_generation")
            sealed = bool(res["sealed"])
            fresh = [sk for sk in ready if sk not in served_set]
            if ordered_sks is not None:
                # In-order delivery: serve the contiguous ready prefix of
                # the caller's key order; out-of-order arrivals wait their
                # turn (any remainder — keys outside the order — is served
                # at seal below).
                ready_set = set(ready)
                wave: list[str] = []
                for sk in ordered_sks:
                    if sk in served_set:
                        continue
                    if sk not in ready_set:
                        break
                    wave.append(sk)
                if sealed:
                    # Remainder at seal — keys outside the caller's order,
                    # plus everything held back behind a key_order entry
                    # the publisher never pushed (a phantom key blocks the
                    # contiguous-prefix scan; only the seal proves it is
                    # absent from the mapping) — still served in key_order
                    # position so on_layer ordering survives.
                    pos = {sk: i for i, sk in enumerate(ordered_sks)}
                    in_wave = set(wave)
                    rest = sorted(
                        (sk for sk in fresh if sk not in in_wave),
                        key=lambda sk: (pos.get(sk, len(pos)), sk),
                    )
                    wave += rest
                await serve(wave)
            else:
                await serve(fresh)
            _LAG.set(known - len(served_sks))

        # ---- finalize: seal record + structure + consistency re-check ----
        marker_sk = sdu._store_key(key, sdu.MAPPING_KEY)
        try:
            # Same nearest-copy preference as the layers: the relay tree
            # forwards the commit marker at seal, so a leaf host finalizes
            # against its local copy too.
            marker = (
                await client.get_batch(
                    {marker_sk: None},
                    _seed_plan=False,
                    prefer_volume=relay_volume,
                )
            )[marker_sk]
        except KeyError as exc:
            raise _Restart("marker_gone") from exc
        if (marker.get("stream") or {}).get("version") != target:
            # The marker belongs to a different publish (a barrier push or
            # a newer stream raced the seal): our served set cannot be
            # trusted against it.
            raise _Restart("marker_drift")
        mapping = marker["mapping"]
        leaf_keys = sdu._leaf_keys(mapping)
        if user_flat is not None:
            extra = set(user_flat) - leaf_keys
            if extra:
                raise ValueError(
                    f"user dict keys not present in push {key!r}: "
                    f"{sorted(extra)[:5]}"
                )
            missing = leaf_keys - set(user_flat)
            if strict and missing:
                raise ValueError(
                    f"state dict structure mismatch for {key!r}: missing "
                    f"in user dict: {sorted(missing)[:5]} (pass "
                    "strict=False to pull a subset)"
                )
            unserved = [fk for fk in user_flat if fk not in served]
        else:
            unserved = [fk for fk in sorted(leaf_keys) if fk not in served]
        if unserved:
            # Sealed but some mapping keys never reached our target
            # watermark (a publisher that skipped unchanged layers): a
            # single-generation serve is impossible — restart; the barrier
            # fallback path serves mixed-watermark dicts the classic way.
            raise _Restart("incomplete_seal")
        state2 = await client.stream_state(key)
        if state2 is None:
            raise _Restart("stream_gone")
        if int(state2["version"]) != target:
            # A newer stream has BEGUN: its begin strictly precedes any of
            # its byte landings (publisher program order), so bytes we may
            # have read from it exist only if this check fires — the
            # watermark alone can lag those landings by an in-flight
            # notify, which is exactly the window this closes.
            raise _Restart("superseded")
        bad = inconsistent_keys(state2, served_sks, target)
        if bad:
            raise _Restart("mixed_generation")
        flat = (
            {fk: served[fk] for fk in user_flat}
            if user_flat is not None
            else {fk: served[fk] for fk in sorted(leaf_keys)}
        )
        result = sdu.unflatten_state_dict(
            flat, user_mapping if user_flat is not None else mapping
        )
    _LAG.set(0)
    _ACQUIRES.inc()
    _publish_acquire_telemetry(state2, first_serve_ts, time.time())
    obs_recorder.record(
        "stream", "acquire", key=key, version=target, layers=len(served_sks)
    )
    try:
        # Per-subscriber completion on the controller's generation
        # timeline (ts.sync_timeline). Advisory: telemetry, not protocol.
        await client.stream_ack(key, target, obs_timeline.subscriber_id())
    except Exception:  # noqa: BLE001 - a lost ack must not fail the serve
        pass
    return result


def _publish_acquire_telemetry(
    state: Optional[dict],
    first_serve_ts: Optional[float],
    done_ts: float,
) -> None:
    """Turn one completed streamed acquire into the live production gauges
    + SLO checks: first-layer latency (stream begin -> this subscriber's
    first served layer) and overlap ratio (fraction of the publish window
    the acquire ran inside — the bench's ``overlap_ratio``, live).
    Timestamps come from the controller's stream record (wall clock; skew
    is a cross-host caveat, exact on the same host)."""
    if state is None or first_serve_ts is None:
        return
    begin_ts = state.get("begin_ts")
    seal_ts = state.get("seal_ts")
    if begin_ts is None:
        return
    first_layer_s = max(0.0, first_serve_ts - begin_ts)
    _FIRST_LAYER.set(first_layer_s)
    obs_timeline.check_slo(
        obs_timeline.SLO_FIRST_LAYER_MS, first_layer_s * 1e3
    )
    if seal_ts is None or seal_ts <= begin_ts:
        return
    window = seal_ts - begin_ts
    overlap = max(
        0.0, min(seal_ts, done_ts) - max(begin_ts, first_serve_ts)
    )
    ratio = min(1.0, overlap / window)
    _OVERLAP.set(ratio)
    obs_timeline.check_slo(
        obs_timeline.SLO_OVERLAP_MIN, ratio, worse="below"
    )
