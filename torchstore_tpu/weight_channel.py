"""Versioned weight channel: the RL weight-sync steady state as one object.

The reference leaves the publish/consume loop to users: trainers invent
version-numbered keys ("v0", "v1", ...) and generators poll
``get_state_dict`` in try/except loops (reference example/torchstore_rl.py).
This layer packages the whole pattern:

- ``WeightPublisher.publish(sd)`` writes the state dict under
  ``name/v{n}``, atomically advances the ``name/LATEST`` pointer, and
  garbage-collects versions older than ``keep`` — unbounded-memory-free by
  construction.
- ``WeightSubscriber.acquire()`` BLOCKS until a version newer than the last
  one it returned is committed (woken by the controller's update
  notification, no polling), pulls it — optionally in place into
  ``user_state_dict`` targets, resharding as usual — and returns
  ``(state_dict, version)``.

Ordering guarantee: ``LATEST`` is written only after the version's commit
marker, so a subscriber woken by the pointer update always finds a complete
state dict. GC trails ``keep`` versions behind, so a subscriber mid-pull on
version n is safe while n+1 publishes (keep >= 2).
"""

from __future__ import annotations

import asyncio
import contextlib
from typing import Any, Optional

from torchstore_tpu.logging import get_logger
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import recorder as obs_recorder
from torchstore_tpu.observability import timeline as obs_timeline
from torchstore_tpu.observability.tracing import span
from torchstore_tpu.state_dict_utils import NoMatchingPush

logger = get_logger("torchstore_tpu.weight_channel")

# Publisher side and subscriber side each run in their own process; gauges
# are labeled by channel so one scrape of both processes yields the
# publish→subscribe version lag (published_version - acquired_version).
_PUBLISHES = obs_metrics.counter(
    "ts_weight_channel_publishes_total", "Versions published, per channel"
)
_PUBLISHED_VERSION = obs_metrics.gauge(
    "ts_weight_channel_published_version", "Latest version published"
)
_ACQUIRED_VERSION = obs_metrics.gauge(
    "ts_weight_channel_acquired_version", "Latest version a subscriber pulled"
)
_VERSION_LAG = obs_metrics.gauge(
    "ts_weight_channel_version_lag",
    "Versions between the channel pointer and what this subscriber last "
    "acquired, measured at wakeup (0 = consuming every publish)",
)
_SKIPPED = obs_metrics.counter(
    "ts_weight_channel_versions_skipped_total",
    "Published versions a subscriber never pulled (lagged past)",
)
_PINNED_ACQUIRES = obs_metrics.counter(
    "ts_weight_channel_pinned_acquires_total",
    "Version-pinned acquires served under a cohort lease, per channel",
)

_LATEST = "LATEST"
# In-flight streamed-publish announce: written when a ChannelStream's first
# layer opens, BEFORE any seal — the streaming subscriber's wakeup pointer.
_STREAM_PTR = "STREAM"


def _version_key(name: str, version: int) -> str:
    return f"{name}/v{version}"


def _parse_pointer(value) -> tuple[int, int]:
    """(version, epoch) from a LATEST pointer. Plain ints (pre-epoch
    pointers recovered from a durable store) read as epoch 0."""
    if isinstance(value, (tuple, list)):
        return int(value[0]), int(value[1])
    return int(value), 0


class WeightPublisher:
    """Trainer side of a versioned weight channel."""

    def __init__(
        self,
        name: str,
        store_name: str = "default",
        keep: int = 2,
        client: Any = None,
        transfer_quant: Optional[str] = None,
        delta: bool = False,
        keyframe_every: Optional[int] = None,
    ) -> None:
        if keep < 1:
            raise ValueError("keep must be >= 1 (the latest version must live)")
        self.name = name
        self.keep = keep
        self._store_name = store_name
        self._client = client
        self._next_version: Optional[int] = None
        # Wire-tier defaults for this publisher: ``transfer_quant`` (None =
        # the TORCHSTORE_TPU_TRANSFER_QUANT default) and ``delta=True`` for
        # delta encoding between consecutive versions (requires a blockwise
        # mode; the publisher keeps the last-shipped baseline per key and
        # ships sparse residuals, re-keyframing every ``keyframe_every``
        # versions — default TORCHSTORE_TPU_DELTA_KEYFRAME).
        self._transfer_quant = transfer_quant
        self._delta = delta
        self._keyframe_every = keyframe_every
        self._codec = None
        # Channel epoch: minted when this publisher CREATES the channel,
        # inherited when it resumes one. Lets subscribers distinguish a
        # deleted-then-recreated channel (fresh epoch, numbering restarts)
        # from a duplicate wakeup of the same publish (ADVICE r2).
        self._epoch: Optional[int] = None

    def _resolve_client(self):
        if self._client is None:
            from torchstore_tpu import api

            self._client = api.client(self._store_name)
        return self._client

    async def register(
        self, state_dict: Any, transfer_dtype=None, direct: bool = False
    ) -> dict:
        """Provision the store for this channel's working set BEFORE the
        first publish (the cold-start hint path): derives a manifest from
        the state dict (metadata only — no bytes move) and prewarms volume
        pools, transport connections, and — with ``direct=True`` — the
        client-local staging segments the direct source will draw. Call it
        during model setup, while the trainer is still compiling/loading,
        so the first publish lands in pre-faulted segments. Advisory:
        failures are reported in the returned dict, never raised, and the
        first publish falls back to the lazy path."""
        from torchstore_tpu import provision

        try:
            client = self._resolve_client()
            from torchstore_tpu.config import default_config

            cfg = getattr(client, "_config", None) or default_config()
            # Quantized channels prewarm pools sized for the fused blobs
            # (scale-bearing arena segments), not full-precision tensors.
            # The "none" sentinel (explicitly disabled) must not reach the
            # manifest, which treats any non-None value as a quant format.
            quant = None
            if transfer_dtype is None:
                quant = self._resolve_quant(client, None)
                if quant == "none":
                    quant = None
            manifest = provision.as_manifest(
                state_dict,
                transfer_dtype=transfer_dtype,
                transfer_quant=quant,
                quant_block=cfg.quant_block,
            )
        except Exception as exc:  # noqa: BLE001 - advisory: the first
            # publish surfaces real problems loudly; register never does.
            logger.warning(
                "channel %s register failed (%s); first publish will take "
                "the lazy path",
                self.name,
                exc,
            )
            return {"ok": False, "errors": {"register": str(exc)}}
        with span(
            "weight_channel.register",
            channel=self.name,
            nbytes=manifest.total_bytes,
        ):
            return await provision.prewarm_manifest(
                client, manifest, direct=direct
            )

    async def _resolve_next_version(self, client) -> int:
        """Resume after the channel's existing LATEST (a restarted publisher
        must not clobber live versions) — and reclaim any PARTIAL version a
        crashed predecessor left beyond the pointer: an abandoned stream's
        layer keys (never sealed, so never pointed at) would otherwise leak
        until their version number is reused and GC'd.

        Versions that SURVIVE the reclaim (pinned by live cohort leases —
        including versions of a closed-and-recreated channel, whose fresh
        epoch restarts numbering at 0) advance the counter past them: a
        publish must never land in a retained version's directory, where
        its keys would mix with the survivor's into a two-generation dict.
        Skipping the numbers also routes the survivors into ``_gc``'s
        retention window once their leases lapse, so a skipped partial is
        reclaimed by a later publish instead of leaking forever."""
        if self._next_version is None:
            try:
                current, epoch = _parse_pointer(
                    await client.get(f"{self.name}/{_LATEST}")
                )
                self._next_version = current + 1
                self._epoch = epoch
            except KeyError:
                import secrets

                self._next_version = 0
                self._epoch = secrets.randbits(62) or 1
                current = -1
            survivors = await self._reclaim_partials(client, current)
            if survivors:
                self._next_version = max(
                    self._next_version - 1, max(survivors)
                ) + 1
        return self._next_version

    async def _commit(self, client, version: int) -> None:
        """The ONE commit tail for a published version, shared by the
        barrier ``publish`` and ``ChannelStream.seal``: advance the LATEST
        pointer (subscribers woken by it always find a committed dict —
        callers must have finished the data/seal writes first), step the
        version counter, and publish the channel metrics."""
        await client.put(f"{self.name}/{_LATEST}", (version, self._epoch))
        self._next_version = version + 1
        _PUBLISHES.inc(channel=self.name)
        _PUBLISHED_VERSION.set(version, channel=self.name)
        obs_recorder.record(
            "stream", "publish", channel=self.name, version=version
        )

    async def _leased_versions(self, client) -> Optional[set[int]]:
        """Versions of this channel pinned by live cohort leases — GC and
        partial-reclaim skip them. Advisory here (a skip avoids pointless
        delete RPCs): the HARD guarantee is the controller's
        notify_delete_batch lease guard, which refuses the delete however
        it is issued, so a lease-plane hiccup degrades to noise, never to
        a reaped pinned version. Returns None when the lease plane is
        unreachable — callers fall back to the guard and, where it
        matters, verify their deletes actually removed keys."""
        try:
            pins = await client.lease_list(self.name)
        except Exception:  # noqa: BLE001 - advisory; the controller guard
            # still enforces retention
            logger.warning(
                "channel %s: lease_list failed; relying on the "
                "controller's delete guard for pinned versions",
                self.name,
            )
            return None
        return {int(v) for v in pins.get(self.name, {})}

    async def _reclaim_partials(self, client, current: int) -> set[int]:
        """Delete every version directory BEYOND the committed pointer
        (keys a crashed publisher streamed but never sealed). Runs once per
        publisher lifetime, on resume. LEASED versions survive — a canary
        cohort may legitimately pin an experimental version published past
        the main pointer — and are returned so the caller can advance the
        version counter past them instead of publishing into them."""
        stale: set[int] = set()
        for key in await client.keys(self.name):
            seg = key[len(self.name) + 1 :].split("/", 1)[0]
            if seg.startswith("v") and seg[1:].isdigit() and int(seg[1:]) > current:
                stale.add(int(seg[1:]))
        survivors: set[int] = set()
        if stale:
            survivors = (await self._leased_versions(client) or set()) & stale
            stale -= survivors
        for v in sorted(stale):
            removed = await client.delete_prefix(_version_key(self.name, v))
            if await client.keys(_version_key(self.name, v)):
                # Keys remain after the delete: the controller's lease
                # guard refused it (the version is pinned, but lease_list
                # failed above so we did not know). A survivor is a
                # survivor however we learn of it — numbering must still
                # advance past it, never publish into its directory.
                survivors.add(v)
                logger.warning(
                    "channel %s: v%d survived reclaim (lease-guarded "
                    "delete refused); resuming numbering past it",
                    self.name,
                    v,
                )
            elif removed:
                logger.warning(
                    "channel %s: reclaimed partial v%d (%d keys) left by a "
                    "crashed publisher",
                    self.name,
                    v,
                    removed,
                )
        return survivors

    def _resolve_quant(self, client, override: Optional[str]) -> Optional[str]:
        from torchstore_tpu import state_dict_utils as sdu

        explicit = override if override is not None else self._transfer_quant
        mode = sdu.resolve_transfer_quant(
            explicit, None, getattr(client, "_config", None)
        )
        if mode is None and explicit is not None:
            # Explicitly disabled ("none") at the publisher/call level:
            # keep the sentinel so put_state_dict does not re-apply the
            # TORCHSTORE_TPU_TRANSFER_QUANT default.
            return "none"
        return mode

    def _ensure_codec(self, client, mode: str):
        """The publisher's DeltaEncoder (lazy; one per publisher lifetime —
        a restarted publisher has no baselines and re-keyframes naturally).
        Enforces keep >= keyframe cadence: a fresh reader chain-walks back
        to the newest keyframe, which must still be retained."""
        from torchstore_tpu import state_dict_utils as sdu
        from torchstore_tpu.config import default_config

        if self._codec is None:
            cfg = getattr(client, "_config", None) or default_config()
            kf = int(self._keyframe_every or cfg.delta_keyframe)
            if kf > self.keep:
                raise ValueError(
                    f"delta publishing on channel {self.name!r} needs "
                    f"keep >= keyframe cadence ({kf}): readers chain-walk "
                    "deltas back to the newest keyframe, which must still "
                    "be retained — raise keep or lower keyframe_every / "
                    "TORCHSTORE_TPU_DELTA_KEYFRAME"
                )
            self._codec = sdu.DeltaEncoder(
                mode, cfg.quant_block, kf, cfg.delta_skip_eps
            )
        return self._codec

    def _delta_ctx_for(
        self, client, version: int, transfer_quant: Optional[str],
        delta: Optional[bool],
    ) -> tuple[Optional[str], Optional[dict]]:
        """(effective quant mode, delta_ctx) for one publish."""
        mode = self._resolve_quant(client, transfer_quant)
        use_delta = self._delta if delta is None else delta
        if not use_delta:
            return mode, None
        if mode not in ("int8_block", "int4_block"):
            raise ValueError(
                "delta publishing requires a blockwise transfer_quant "
                f"(int8_block/int4_block), got {mode!r}"
            )
        return mode, {
            "codec": self._ensure_codec(client, mode),
            "version": int(version),
            "channel": self.name,
        }

    def stream(
        self,
        transfer_dtype=None,
        transfer_quant: Optional[str] = None,
        delta: Optional[bool] = None,
    ) -> "ChannelStream":
        """Open a LAYER-STREAMED publish of the next version: push
        fragments with ``await cs.put(...)`` as the trainer produces them,
        then ``await cs.seal()`` to advance LATEST/GC exactly like
        ``publish``. Streaming subscribers (``acquire_streamed``) wake on
        the in-flight announce and start pulling layers before the seal;
        barrier subscribers (``acquire``) still wake only on the sealed
        pointer. ``transfer_quant``/``delta`` override the publisher's
        wire-tier defaults for this version. See
        torchstore_tpu/stream_sync.py."""
        return ChannelStream(
            self,
            transfer_dtype=transfer_dtype,
            transfer_quant=transfer_quant,
            delta=delta,
        )

    async def publish(
        self,
        state_dict: Any,
        transfer_dtype=None,
        transfer_quant: Optional[str] = None,
        direct: bool = False,
        delta: Optional[bool] = None,
    ) -> int:
        """Write the next version, advance LATEST, GC old versions. Returns
        the published version number. A restarted publisher resumes after
        the channel's existing LATEST instead of clobbering live versions.

        ``direct=True`` publishes through the one-hop path under a single
        STABLE key (``name/direct``): the first publish registers staging
        buffers, later ones are refreshes — no per-version registrations to
        leak, and the version number is purely the subscriber wakeup
        ordinal. A pull concurrent with a refresh is detected by the
        source's seqlock generation and retried, so the returned dict is
        always internally consistent (one step's weights, never a mix)."""
        from torchstore_tpu import state_dict_utils

        client = self._resolve_client()
        version = await self._resolve_next_version(client)
        data_key = (
            f"{self.name}/direct" if direct else _version_key(self.name, version)
        )
        if direct:
            quant_mode, delta_ctx = None, None
        else:
            quant_mode, delta_ctx = self._delta_ctx_for(
                client, version, transfer_quant, delta
            )
        with span(
            "weight_channel.publish",
            channel=self.name,
            version=version,
            direct=direct,
        ):
            await state_dict_utils.put_state_dict(
                client,
                data_key,
                state_dict,
                transfer_dtype=transfer_dtype,
                transfer_quant=quant_mode if not direct else transfer_quant,
                direct=direct,
                delta_ctx=delta_ctx,
            )
            # Pointer write LAST: subscribers woken by it see a committed dict.
            await self._commit(client, version)
        if not direct:
            await self._gc(client, version)
        return version

    async def _gc(self, client, version: int) -> None:
        """Retain the newest ``keep`` versions at or below the one just
        published and delete EVERY other version still present — not just
        the one this publish expires — so versions orphaned by a crash
        between pointer write and GC, or by restarting with a smaller
        ``keep``, are reclaimed on the next publish rather than leaking
        forever. The window counts EXISTING versions, not ``version -
        keep`` arithmetic: a publisher that resumed past a leased
        survivor publishes with a numbering gap, and a numeric cutoff
        would leap across it and reap the previous LATEST out from under
        a mid-pull subscriber. Versions beyond ``version`` (beyond-pointer
        partials a lease retained) are never touched here — they fall
        into the window once numbering passes them.

        Lease-aware (torchstore_tpu/tiering/): versions pinned by live
        cohort leases are skipped — an evaluation cohort on v_{t−k} keeps
        its weights however far LATEST advances — and reaped by a later
        publish's GC once the last lease expires or is released. Old
        retained versions cost tmpfs nothing in a tiered store: the spill
        writer demotes them to disk and reads fault them back in."""
        present: set[int] = set()
        for key in await client.keys(self.name):
            # Keys look like "{name}/v{n}/..." — prefix filtering is
            # segment-bounded, so list the channel root and parse.
            seg = key[len(self.name) + 1 :].split("/", 1)[0]
            if seg.startswith("v") and seg[1:].isdigit():
                present.add(int(seg[1:]))
        window = sorted(v for v in present if v <= version)
        stale = set(window[: -self.keep])
        lease_plane_ok = True
        if stale:
            leased = await self._leased_versions(client)
            lease_plane_ok = leased is not None
            leased = (leased or set()) & set(window)
            if leased:
                # Leased versions are exempt AND excluded from the window:
                # a pinned survivor must neither be reaped nor consume a
                # retention slot (pushing the previous LATEST out of the
                # keep window while a subscriber may still be pulling it).
                stale = set(
                    [v for v in window if v not in leased][: -self.keep]
                )
                logger.debug(
                    "channel %s: GC retaining leased version(s) %s",
                    self.name,
                    sorted(leased),
                )
        for v in sorted(stale):
            removed = await client.delete_prefix(_version_key(self.name, v))
            if not removed:
                continue
            if not lease_plane_ok and await client.keys(
                _version_key(self.name, v)
            ):
                # With lease_list down we could not exempt pinned
                # versions up front; the controller guard refused this
                # delete — retained, not GC'd.
                continue
            logger.debug("channel %s: GC'd v%d (%d keys)", self.name, v, removed)

    async def close(self, delete: bool = False) -> None:
        """Optionally remove every key the channel owns. Versions pinned
        by live cohort leases SURVIVE this delete (the controller's lease
        guard refuses them) and are reaped by a future publisher's GC on
        this channel once the leases lapse — a close racing a pinned read
        must never win; if the channel is truly done, release the leases
        (or let their TTLs expire) and close again."""
        if delete:
            client = self._resolve_client()
            await client.delete_prefix(self.name)


class ChannelStream:
    """One layer-streamed publish of a channel version (see
    :meth:`WeightPublisher.stream`). The first ``put`` resolves the next
    version number, opens the stream, and announces it on the channel's
    ``STREAM`` pointer so streaming subscribers wake immediately;
    ``seal()`` commits the marker, advances ``LATEST`` (barrier
    subscribers wake here), and GCs old versions. An abandoned stream
    (publisher crash before seal) never advances a pointer — the previous
    version stays fully acquirable, and the next publisher's resume
    reclaims the partial keys."""

    def __init__(
        self,
        publisher: WeightPublisher,
        transfer_dtype=None,
        transfer_quant: Optional[str] = None,
        delta: Optional[bool] = None,
    ) -> None:
        self._pub = publisher
        self._transfer_dtype = transfer_dtype
        self._transfer_quant = transfer_quant
        self._delta = delta
        self._stream = None
        self.version: Optional[int] = None

    async def put(self, fragment: Any) -> int:
        from torchstore_tpu import stream_sync

        if self._stream is None:
            pub = self._pub
            client = pub._resolve_client()
            self.version = await pub._resolve_next_version(client)
            quant_mode, delta_ctx = pub._delta_ctx_for(
                client, self.version, self._transfer_quant, self._delta
            )
            self._stream = stream_sync.stream_state_dict(
                client,
                _version_key(pub.name, self.version),
                transfer_dtype=self._transfer_dtype,
                transfer_quant=quant_mode,
                delta_ctx=delta_ctx,
            )
            await self._stream.begin()
            # Announce the IN-FLIGHT version before any layer lands:
            # streaming subscribers wake on this pointer and long-poll the
            # stream's watermarks — decode starts before the seal. A
            # regular put, so a crashed publisher leaves at worst a stale
            # announce that the next subscriber wakeup skips.
            await client.put(
                f"{pub.name}/{_STREAM_PTR}", (self.version, pub._epoch)
            )
        return await self._stream.put(fragment)

    async def seal(self) -> int:
        if self._stream is None:
            raise RuntimeError("seal() before any put(): nothing published")
        pub = self._pub
        client = pub._resolve_client()
        version = self.version
        with span(
            "weight_channel.publish",
            channel=pub.name,
            version=version,
            streamed=True,
        ):
            await self._stream.seal()
            # Pointer write LAST: barrier subscribers woken by it always
            # see a committed (sealed) dict, exactly like publish().
            await pub._commit(client, version)
        await pub._gc(client, version)
        return version


class WeightSubscriber:
    """Consumer side: blocks for fresh versions instead of polling.

    ``relay=True`` joins the channel's BROADCAST tree (torchstore_tpu/
    relay.py): the controller assigns this host's relay volume, published
    versions flow to it volume-to-volume, and streamed acquires are gated
    on + routed to that one host-local copy — K generator fleets cost O(1)
    trainer-host egress instead of K×. ``relay_volume`` pins an explicit
    member volume (tests/benches emulating multi-host fleets). Membership
    is elastic: the subscription happens lazily on the first streamed
    acquire and ``unsubscribe_relay()`` leaves mid-run (the tree re-parents
    around the departed host)."""

    def __init__(
        self,
        name: str,
        store_name: str = "default",
        client: Any = None,
        relay: bool = False,
        relay_volume: Optional[str] = None,
        cohort: Optional[str] = None,
    ) -> None:
        import os as _os

        self.name = name
        self._store_name = store_name
        self._client = client
        # Cohort identity for version-pinned acquires: the lease owner in
        # ts.version_catalog() / the flight recorder. Defaults to a
        # process-unique id; name it (e.g. "eval-fleet-2") so retention is
        # attributable.
        self.cohort = cohort or f"sub-{_os.getpid()}-{id(self):x}"
        # Lease-owner prefix for pinned acquires: ALWAYS process- and
        # instance-unique, even under a shared named cohort ("eval-fleet-2"
        # across a fleet) — the registry coalesces same-owner pins, so two
        # subscribers reusing an owner string would share one lease the
        # first finisher releases under the second. The cohort stays the
        # prefix for attribution in ts.version_catalog()/telemetry.
        self._lease_owner = f"{self.cohort}:{_os.getpid()}:{id(self):x}"
        # Monotonic per-subscriber read counter: each pinned acquire's
        # lease owner is "{_lease_owner}:r{n}" (see _pinned_lease).
        self._read_seq = 0
        self._last_gen = 0
        self._last_stream_gen = 0
        self.last_version: Optional[int] = None
        self._last_epoch: Optional[int] = None
        self._relay = relay or relay_volume is not None
        self._relay_volume = relay_volume
        self._relay_home: Optional[str] = None
        # Delta wire tier: this subscriber's accumulated per-key state.
        # Lazily built, shared across acquires so consecutive versions
        # accumulate (and unchanged-key layers serve with zero
        # re-transfer); empty-cost for unquantized channels.
        self._decoder = None
        self._decoder_epoch: Optional[int] = None

    def _delta_decoder(self, epoch: Optional[int] = None):
        from torchstore_tpu import state_dict_utils as sdu

        if self._decoder is None:
            self._decoder = sdu.DeltaDecoder()
            self._decoder_epoch = epoch
        elif epoch is not None and epoch != self._decoder_epoch:
            # A deleted-then-recreated channel restarts version numbering
            # under a fresh epoch: accumulated state from the OLD epoch
            # could collide with the new numbering (same version ints,
            # different weights) and silently serve stale accumulations —
            # drop it so the new epoch's first acquire re-keyframes/
            # chain-walks from real bytes.
            self._decoder.drop()
            self._decoder_epoch = epoch
        return self._decoder

    def _resolve_client(self):
        if self._client is None:
            from torchstore_tpu import api

            self._client = api.client(self._store_name)
        return self._client

    async def _ensure_relay(self, client) -> Optional[str]:
        """Join the channel's relay tree once (lazy, idempotent); returns
        the assigned home volume id, or None when relay is off/disabled."""
        if not self._relay:
            return None
        if self._relay_home is None:
            res = await client.relay_subscribe(
                self.name, volume_id=self._relay_volume
            )
            self._relay_home = res.get("volume_id")
            if self._relay_home is None:
                # Disabled fleet-wide (TORCHSTORE_TPU_RELAY_ENABLED=0):
                # stop retrying the control RPC on every acquire.
                self._relay = False
            else:
                obs_recorder.record(
                    "stream",
                    "relay_join",
                    channel=self.name,
                    volume=self._relay_home,
                )
        return self._relay_home

    async def unsubscribe_relay(self) -> None:
        """Elastic leave: drop this subscriber from the channel's broadcast
        tree (live runs re-parent around the host). Idempotent."""
        if self._relay_home is None:
            return
        client = self._resolve_client()
        await client.relay_unsubscribe(self.name, self._relay_home)
        self._relay_home = None

    async def _pinned_lease(self, client, version: int):
        """Acquire the read-scoped retention lease for a pinned acquire:
        while it lives, the version can be neither GC'd (controller delete
        guard) nor demoted off the warm path by the next spill sweep.

        The lease owner is a per-READ identity
        (``{cohort}:{pid}:{instance}:r{n}``), never the bare cohort: the
        registry coalesces same-owner pins, so a read under a shared name
        would RENEW — and its release DROP — a pin another read (or a
        long-lived cohort lease) still depends on. The pid/instance parts
        keep owners unique across subscribers SHARING a named cohort and
        across a restarted process whose read counter resets within a
        live lease's TTL; should an acquire still coalesce
        (``renewed: True``), :meth:`_pinned_read` leaves the shared pin
        live instead of releasing it under the other holder."""
        self._read_seq += 1
        owner = f"{self._lease_owner}:r{self._read_seq}"
        # Bracket contract lives in the CALLER: _pinned_read releases in
        # its finally; the normal return here hands the lease over open by
        # design, and the renewed-pin KeyError path deliberately leaves a
        # COALESCED lease to its other holder (releasing it would strip a
        # live read's GC protection).
        lease = await client.lease_acquire(owner, self.name, version)  # tslint: disable=bracket-discipline
        if lease.get("resident_keys") == 0:
            # Nothing indexed under this version: GC'd or never published.
            # Fail BEFORE the pull with a precise error (the pull's
            # NoMatchingPush would be indistinguishable from a torn push).
            if not lease.get("renewed"):
                await client.lease_release(lease["lease_id"])
            raise KeyError(
                f"channel {self.name!r} does not retain v{version} (GC'd "
                "or never published); pin versions with a cohort lease "
                "before LATEST advances past keep"
            )
        return lease

    async def _renew_pinned(self, client, lease: dict) -> None:
        """Heartbeat a pinned read's lease while the pull is in flight:
        state dicts routinely take longer than the default 30 s TTL to
        transfer, and a lease that lapses mid-read would hand the version
        back to GC/spill. Renews at a third of the TTL; a failed renewal
        (transient RPC blip, controller restart, lease expired under a
        long stall) falls back to RE-ACQUIRING the same owner's pin — one
        hiccup must not strip a long pull's protection for its remaining
        duration. Only when the re-acquire also fails does the heartbeat
        stop: the read degrades to best-effort, it never errors."""
        interval = max(0.1, float(lease.get("ttl_s") or 1.0) / 3.0)
        while True:
            await asyncio.sleep(interval)
            try:
                await client.lease_renew(lease["lease_id"])
            except Exception as renew_exc:  # noqa: BLE001 - degrade,
                # never fail the read: the pin is advisory protection,
                # the pull is the deliverable.
                try:
                    fresh = await client.lease_acquire(
                        lease["cohort"],
                        lease["channel"],
                        lease["version"],
                        lease.get("ttl_s"),
                    )
                    # Same owner: the registry coalesces onto the live
                    # lease when it still exists, or mints a replacement.
                    # Keep the ORIGINAL "renewed" flag — whether release
                    # is ours to do was decided at the first acquire.
                    lease["lease_id"] = fresh["lease_id"]
                    logger.info(
                        "channel %s: pinned-read lease renewal failed "
                        "(%s); re-acquired as %s",
                        self.name,
                        renew_exc,
                        fresh["lease_id"],
                    )
                except Exception as exc:  # noqa: BLE001
                    logger.warning(
                        "channel %s: pinned-read lease %s renewal and "
                        "re-acquire both failed (%s); read continues "
                        "without GC/spill protection",
                        self.name,
                        lease["lease_id"],
                        exc,
                    )
                    return

    @contextlib.asynccontextmanager
    async def _pinned_read(self, client, version: int):
        """Hold the read-scoped lease for the duration of a pinned pull:
        acquires it, renews it in the background (long pulls stay
        protected past the TTL), and on exit releases it — unless the
        acquire merely coalesced with an existing same-owner pin
        (``renewed: True``), which must survive for its other holder."""
        lease = await self._pinned_lease(client, version)
        renewer = asyncio.ensure_future(self._renew_pinned(client, lease))
        try:
            yield lease
        finally:
            renewer.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await renewer
            if lease.get("renewed"):
                logger.warning(
                    "channel %s: pinned-read lease owner collided with a "
                    "live pin (lease %s); leaving the shared lease to its "
                    "other holder",
                    self.name,
                    lease["lease_id"],
                )
            else:
                try:
                    await client.lease_release(lease["lease_id"])
                except Exception as exc:  # noqa: BLE001 - best-effort:
                    # the pull already succeeded (or raised its own
                    # error); the TTL reaps an unreleased pin anyway.
                    logger.warning(
                        "channel %s: pinned-read lease %s release failed "
                        "(%s); its TTL will expire it",
                        self.name,
                        lease["lease_id"],
                        exc,
                    )

    async def acquire(
        self,
        user_state_dict: Any = None,
        timeout: Optional[float] = None,
        direct: bool = False,
        strict: bool = True,
        version: Optional[int] = None,
    ) -> tuple[Any, int]:
        """Block until a version is published that this subscriber has not
        yet acquired, pull it, and return (state_dict, version). The first
        call returns the channel's current version immediately when one
        exists; each publish is delivered at most once (a deleted-then-
        recreated channel restarts numbering and delivers its v0). Raises
        TimeoutError if nothing new arrives in ``timeout`` seconds.

        ``version=N`` PINS the read instead (multi-version serving,
        torchstore_tpu/tiering/): a cohort retention lease is held — and
        renewed in the background, so pulls longer than the lease TTL stay
        protected — for the read's duration: the version cannot be GC'd
        mid-read, and spilled segments fault back in through the normal
        transport ladder. ``(state_dict, N)`` returns without touching
        this subscriber's LATEST tracking; ``timeout`` bounds the pull
        itself (there is no wait phase) and raises TimeoutError —
        cancelling a pull mid-flight, so after a timeout an IN-PLACE
        ``user_state_dict`` may hold a mix of its old leaves and
        already-landed v``N`` leaves: treat its contents as undefined.
        Raises KeyError when the channel no longer retains ``N``."""
        import time

        from torchstore_tpu import state_dict_utils

        client = self._resolve_client()
        if version is not None:
            if direct:
                raise ValueError(
                    "acquire(version=...) is incompatible with direct=True "
                    "(the direct path serves one stable key, not versions)"
                )
            version = int(version)
            async with self._pinned_read(client, version):
                with span(
                    "weight_channel.acquire_pinned",
                    channel=self.name,
                    version=version,
                ):
                    pull = state_dict_utils.get_state_dict(
                        client,
                        _version_key(self.name, version),
                        user_state_dict=user_state_dict,
                        strict=strict,
                        delta_state=self._delta_decoder(),
                    )
                    if timeout is None:
                        sd = await pull
                    else:
                        try:
                            sd = await asyncio.wait_for(pull, timeout)
                        except asyncio.TimeoutError:
                            raise TimeoutError(
                                f"pinned acquire of {self.name}/v{version} "
                                f"did not complete within {timeout}s"
                            ) from None
            _PINNED_ACQUIRES.inc(channel=self.name)
            obs_recorder.record(
                "tier",
                "pinned_acquire",
                channel=self.name,
                version=version,
                cohort=self.cohort,
            )
            return sd, version
        pointer = f"{self.name}/{_LATEST}"
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = None
            if deadline is not None:
                remaining = max(0.0, deadline - time.monotonic())
            change = await client.wait_for_change(
                pointer, self._last_gen, timeout=remaining
            )
            self._last_gen = change["gen"]
            if change["state"] != "committed":
                continue  # deleted channel or mid-rewrite; wait for the next
            data_key = None
            try:
                version, epoch = _parse_pointer(await client.get(pointer))
                if (
                    version == self.last_version
                    and epoch == self._last_epoch
                ):
                    # Duplicate wakeup: the gen we woke for belongs to a
                    # publish whose successor we ALREADY returned (the
                    # pointer is read in a later RPC than the gen, so a
                    # publish landing in between makes the next wake see
                    # the same version again). Each publish is delivered
                    # at most once — wait for a genuinely new one. A
                    # deleted-then-recreated channel mints a fresh epoch,
                    # so its restarted numbering still delivers (ADVICE r2).
                    continue
                data_key = (
                    f"{self.name}/direct"
                    if direct
                    else _version_key(self.name, version)
                )
                # Lag at wakeup: versions published since this subscriber's
                # last acquire that it will never pull (same epoch only — a
                # recreated channel restarts numbering). Consuming every
                # publish means waking at last_version + 1, i.e. lag 0.
                if (
                    self.last_version is not None
                    and epoch == self._last_epoch
                ):
                    skipped = version - self.last_version - 1
                    _VERSION_LAG.set(max(0, skipped), channel=self.name)
                    if skipped > 0:
                        _SKIPPED.inc(skipped, channel=self.name)
                    obs_timeline.check_slo(
                        obs_timeline.SLO_VERSION_LAG,
                        max(0, skipped),
                        channel=self.name,
                    )
                with span(
                    "weight_channel.acquire",
                    channel=self.name,
                    version=version,
                    direct=direct,
                ):
                    sd = await state_dict_utils.get_state_dict(
                        client,
                        data_key,
                        user_state_dict=user_state_dict,
                        direct=direct,
                        strict=strict,
                        delta_state=(
                            None if direct else self._delta_decoder(epoch)
                        ),
                    )
            except (NoMatchingPush, KeyError):
                # The pointer or version vanished between wakeup and pull
                # (channel deleted, or we lagged > keep versions behind);
                # wait for the next publish.
                logger.info(
                    "channel %s: %s vanished before pull (deleted channel "
                    "or lagging subscriber); waiting for next version",
                    self.name,
                    data_key or pointer,
                )
                continue
            self.last_version = version
            self._last_epoch = epoch
            _ACQUIRED_VERSION.set(version, channel=self.name)
            return sd, version

    async def acquire_streamed(
        self,
        user_state_dict: Any = None,
        key_order: Optional[list] = None,
        on_layer: Any = None,
        timeout: Optional[float] = None,
        strict: bool = True,
        version: Optional[int] = None,
    ) -> tuple[Any, int]:
        """Like :meth:`acquire`, but against layer-streamed publishes
        (:meth:`WeightPublisher.stream`): wakes on the channel's IN-FLIGHT
        announce (written before any layer lands) and pulls layer by layer
        as watermarks land — with ``key_order`` (model-forward order, e.g.
        ``models.generate.forward_key_order`` or
        ``StateDictManifest.key_order``) and an ``on_layer`` callback,
        generation starts before the publisher seals. The returned dict is
        always a single version's weights (stream_sync's watermark
        consistency ladder), and versions are delivered at most once.
        Requires streamed publishes; raises TimeoutError when nothing is
        announced within ``timeout``.

        ``version=N`` PINS the acquire to a retained historical version
        under a read-scoped cohort lease (see :meth:`acquire`); a sealed
        stream serves its layers immediately (in ``key_order`` when
        given), and a version whose stream record is gone falls back to
        the barrier read inside stream_sync."""
        import time

        from torchstore_tpu import stream_sync

        client = self._resolve_client()
        if version is not None:
            version = int(version)
            async with self._pinned_read(client, version):
                with span(
                    "weight_channel.acquire_pinned",
                    channel=self.name,
                    version=version,
                    streamed=True,
                ):
                    sd = await stream_sync.get_state_dict_streamed(
                        client,
                        _version_key(self.name, version),
                        user_state_dict=user_state_dict,
                        key_order=key_order,
                        on_layer=on_layer,
                        strict=strict,
                        timeout=timeout,
                        delta_state=self._delta_decoder(),
                    )
            _PINNED_ACQUIRES.inc(channel=self.name)
            obs_recorder.record(
                "tier",
                "pinned_acquire",
                channel=self.name,
                version=version,
                cohort=self.cohort,
            )
            return sd, version
        relay_home = await self._ensure_relay(client)
        pointer = f"{self.name}/{_STREAM_PTR}"
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = None
            if deadline is not None:
                remaining = max(0.0, deadline - time.monotonic())
            change = await client.wait_for_change(
                pointer, self._last_stream_gen, timeout=remaining
            )
            self._last_stream_gen = change["gen"]
            if change["state"] != "committed":
                continue  # deleted channel mid-rewrite; wait for the next
            try:
                version, epoch = _parse_pointer(await client.get(pointer))
            except KeyError:
                continue
            if version == self.last_version and epoch == self._last_epoch:
                continue  # duplicate wakeup: delivered at most once
            data_key = _version_key(self.name, version)
            if self.last_version is not None and epoch == self._last_epoch:
                skipped = version - self.last_version - 1
                _VERSION_LAG.set(max(0, skipped), channel=self.name)
                if skipped > 0:
                    _SKIPPED.inc(skipped, channel=self.name)
                obs_timeline.check_slo(
                    obs_timeline.SLO_VERSION_LAG,
                    max(0, skipped),
                    channel=self.name,
                )
            with span(
                "weight_channel.acquire",
                channel=self.name,
                version=version,
                streamed=True,
            ):
                try:
                    sd = await stream_sync.get_state_dict_streamed(
                        client,
                        data_key,
                        user_state_dict=user_state_dict,
                        key_order=key_order,
                        on_layer=on_layer,
                        strict=strict,
                        timeout=(
                            None
                            if deadline is None
                            else max(0.0, deadline - time.monotonic())
                        ),
                        relay_volume=relay_home,
                        delta_state=self._delta_decoder(epoch),
                    )
                except (NoMatchingPush, KeyError):
                    # The announced version vanished before the pull (GC'd
                    # under a lagging subscriber, or a crashed publisher's
                    # partial was reclaimed); wait for the next announce.
                    logger.info(
                        "channel %s: streamed %s vanished before pull; "
                        "waiting for next version",
                        self.name,
                        data_key,
                    )
                    continue
            self.last_version = version
            self._last_epoch = epoch
            _ACQUIRED_VERSION.set(version, channel=self.name)
            return sd, version
