from torchstore_tpu.models.llama import Llama, LlamaConfig, init_params

__all__ = ["Llama", "LlamaConfig", "init_params"]
