"""HuggingFace Llama checkpoint -> torchstore_tpu flax params.

The reference's end-to-end model test loads an HF model and pushes its
state dict through the store (/root/reference/tests/test_models.py:33-136).
This converter provides the same interop for the jax model family: map a
``transformers`` Llama/Mixtral-style state dict (torch CPU tensors or numpy)
onto ``torchstore_tpu.models.llama.Llama`` params, so HF checkpoints can be
published through the store and served by the flax model. Logits parity with
the HF implementation is covered by tests/test_hf_convert.py.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from torchstore_tpu.models.llama import LlamaConfig


def _to_np(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    try:
        return t.detach().cpu().numpy()  # torch tensor
    except AttributeError:
        return np.asarray(t)


def config_from_hf(hf_config) -> LlamaConfig:
    """LlamaConfig from a transformers Llama/Mixtral config object."""
    scaling = getattr(hf_config, "rope_scaling", None)
    if scaling:
        raise NotImplementedError(
            f"rope_scaling={scaling!r} is not implemented by models.llama.rope "
            "— converting this checkpoint would produce silently wrong logits"
        )
    if getattr(hf_config, "use_sliding_window", False):
        raise NotImplementedError(
            "use_sliding_window=True checkpoints are not representable "
            "(attention here is full-causal) — converting would produce "
            "silently wrong logits beyond the window"
        )
    head_dim = getattr(hf_config, "head_dim", None) or (
        hf_config.hidden_size // hf_config.num_attention_heads
    )
    # Gemma-1: tanh-gelu MLP, (1+w) RMSNorm offsets, sqrt(hidden)-scaled
    # embeddings, tied lm_head. Gemma2+ adds softcapping/pre-post norms not
    # representable here — the unmapped-tensor check rejects those.
    is_gemma = hf_config.__class__.__name__ == "GemmaConfig"
    hidden_act = getattr(hf_config, "hidden_act", None) or getattr(
        hf_config, "hidden_activation", None
    )
    if is_gemma:
        # HF's GemmaMLP runs gelu_pytorch_tanh regardless of a legacy
        # hidden_act value (the original release's config said "gelu" but
        # ran tanh-gelu; transformers warns and overrides the same way).
        mlp_act = "gelu_tanh"
    elif hidden_act in (None, "silu"):
        mlp_act = "silu"
    elif hidden_act == "gelu_pytorch_tanh":
        mlp_act = "gelu_tanh"
    else:
        # Exact-erf "gelu", "gelu_new", "relu", ... have no representation
        # here — converting would produce silently diverging logits, the
        # outcome every other guard in this function exists to prevent.
        raise NotImplementedError(
            f"hidden_act={hidden_act!r} is not representable "
            "(supported: silu, gelu_pytorch_tanh)"
        )
    return LlamaConfig(
        mlp_act=mlp_act,
        rms_offset=is_gemma,
        scale_embeddings=is_gemma,
        tie_embeddings=is_gemma,
        # Qwen2Config (exactly — Qwen2Moe etc. have different structure and
        # fail the unmapped-tensor check) carries q/k/v biases implicitly.
        attention_bias=bool(getattr(hf_config, "attention_bias", False))
        or hf_config.__class__.__name__ == "Qwen2Config",
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.intermediate_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(
            hf_config, "num_key_value_heads", hf_config.num_attention_heads
        ),
        head_dim=head_dim,
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        rms_eps=getattr(hf_config, "rms_norm_eps", 1e-5),
        num_experts=getattr(hf_config, "num_local_experts", 0),
        num_experts_per_tok=getattr(hf_config, "num_experts_per_tok", 2),
    )


def convert_hf_llama(
    hf_state_dict: Mapping[str, Any], cfg: LlamaConfig
) -> dict:
    """Map an HF ``LlamaForCausalLM.state_dict()`` onto our param tree.

    Weight layout notes: HF linear weights are (out, in) — ours are flax
    DenseGeneral kernels (in, ...out); attention projections reshape the
    flat head dim into (heads, head_dim). HF's rotate-half RoPE convention
    matches ``models.llama.rope`` (verified by logits parity)."""
    sd = {k: _to_np(v) for k, v in hf_state_dict.items()}
    h, nh, nkv, hd = cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    consumed: set = set()

    def w(name: str) -> np.ndarray:
        consumed.add(name)
        return sd[name]

    params: dict = {
        "embed": {"embedding": w("model.embed_tokens.weight")},
        "final_norm": {"scale": w("model.norm.weight")},
    }
    if cfg.tie_embeddings:
        # The model attends through the embedding table; there is no
        # lm_head param (HF Gemma checkpoints carry none either, but a
        # materialized tied copy is consumed if present).
        if "lm_head.weight" in sd:
            consumed.add("lm_head.weight")
    else:
        params["lm_head"] = {
            "kernel": (
                w("lm_head.weight")
                if "lm_head.weight" in sd
                else w("model.embed_tokens.weight")  # tied embeddings
            ).T
        }
    for i in range(cfg.num_layers):
        pre = f"model.layers.{i}."
        layer = {
            "attn_norm": {"scale": w(pre + "input_layernorm.weight")},
            "mlp_norm": {"scale": w(pre + "post_attention_layernorm.weight")},
            "attn": {
                "q_proj": {
                    "kernel": w(pre + "self_attn.q_proj.weight").T.reshape(h, nh, hd)
                },
                "k_proj": {
                    "kernel": w(pre + "self_attn.k_proj.weight").T.reshape(h, nkv, hd)
                },
                "v_proj": {
                    "kernel": w(pre + "self_attn.v_proj.weight").T.reshape(h, nkv, hd)
                },
                "o_proj": {
                    "kernel": w(pre + "self_attn.o_proj.weight").T.reshape(nh, hd, h)
                },
            },
        }
        if cfg.attention_bias:
            if pre + "self_attn.o_proj.bias" in sd:
                # transformers-Llama applies attention_bias to o_proj too;
                # this model family (like Qwen2) has a bias-free o_proj.
                raise NotImplementedError(
                    "checkpoint carries an o_proj bias; only q/k/v biases "
                    "(Qwen2-style) are representable"
                )
            # Qwen2-style: q/k/v carry biases, o_proj does not.
            layer["attn"]["q_proj"]["bias"] = w(
                pre + "self_attn.q_proj.bias"
            ).reshape(nh, hd)
            layer["attn"]["k_proj"]["bias"] = w(
                pre + "self_attn.k_proj.bias"
            ).reshape(nkv, hd)
            layer["attn"]["v_proj"]["bias"] = w(
                pre + "self_attn.v_proj.bias"
            ).reshape(nkv, hd)
        if cfg.num_experts:
            # Mixtral: per-expert w1/w3/w2 linears stack into our
            # (expert, in, out) kernels; the router gate transposes.
            moe = pre + "block_sparse_moe."
            layer["mlp"] = {
                "router": {"kernel": w(moe + "gate.weight").T},
                "gate_proj": np.stack(
                    [w(f"{moe}experts.{e}.w1.weight").T for e in range(cfg.num_experts)]
                ),
                "up_proj": np.stack(
                    [w(f"{moe}experts.{e}.w3.weight").T for e in range(cfg.num_experts)]
                ),
                "down_proj": np.stack(
                    [w(f"{moe}experts.{e}.w2.weight").T for e in range(cfg.num_experts)]
                ),
            }
        else:
            layer["mlp"] = {
                "gate_proj": {"kernel": w(pre + "mlp.gate_proj.weight").T},
                "up_proj": {"kernel": w(pre + "mlp.up_proj.weight").T},
                "down_proj": {"kernel": w(pre + "mlp.down_proj.weight").T},
            }
        params[f"layer_{i}"] = layer

    # Any unmapped weight means the checkpoint has structure this model
    # cannot represent — fail loudly instead of converting to silently
    # wrong params (rotary inv_freq buffers are derived, safe to drop).
    leftover = {
        k
        for k in sd
        if k not in consumed and not k.endswith("rotary_emb.inv_freq")
    }
    if leftover:
        raise ValueError(
            f"{len(leftover)} checkpoint tensors have no mapping onto this "
            f"model (first few: {sorted(leftover)[:4]}); the architectures "
            "do not match"
        )
    return {"params": params}
