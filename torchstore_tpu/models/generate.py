"""KV-cached autoregressive generation.

The reference exercises its store with generator workers that run
inference after weight sync (reference example/torchstore_rl.py); this
module gives the flax model family a real decode loop: one jitted PREFILL
over the prompt builds per-layer k/v caches (flax ``cache`` collection,
static ``max_len`` shapes, ``dynamic_update_slice`` writes — fully
XLA-compatible), then one jitted STEP per token attends over the cached
prefix. Greedy (temperature=0) and temperature sampling.

Works with freshly trained params or params pulled through the store
(``get_state_dict`` / ``WeightSubscriber.acquire``) — the decode-mode
model shares the exact parameter structure of the training model.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchstore_tpu.models.llama import Llama, LlamaConfig


def forward_key_order(params: Any) -> list:
    """Flat param keys of a :class:`Llama` tree in MODEL-FORWARD order:
    embedding, then ``layer_0 .. layer_N`` numerically, then the final
    norm, then the lm head (anything else after, lexically). This is the
    ``key_order`` a layer-streamed acquire consumes layers in so the
    decoder's forward pass can start at the embedding while deeper layers
    are still in flight (``ts.get_state_dict(stream=True, key_order=...)``
    / ``WeightSubscriber.acquire_streamed``)."""
    from torchstore_tpu.state_dict_utils import flatten_state_dict

    flat, _ = flatten_state_dict(params)

    def rank(key: str) -> tuple:
        for part in key.split("/"):
            if part == "embed":
                return (0, 0)
            if part.startswith("layer_") and part[6:].isdigit():
                return (1, int(part[6:]))
            if part == "final_norm":
                return (2, 0)
            if part == "lm_head":
                return (3, 0)
        return (4, 0)

    return sorted(flat, key=lambda k: (rank(k), k))


class Decoder:
    """Jitted prefill + per-token step over a KV cache.

    >>> dec = Decoder(cfg, max_len=128)
    >>> tokens = dec.generate(params, prompt, max_new_tokens=32)
    """

    def __init__(self, cfg: LlamaConfig, max_len: int) -> None:
        if cfg.attn_impl != "dense":
            # Sequence-parallel attention is a training-time layout; decode
            # attends over a cache and is dense by construction.
            cfg = dataclasses.replace(cfg, attn_impl="dense", mesh=None)
        self.cfg = dataclasses.replace(
            cfg, decode=True, max_cache_len=int(max_len)
        )
        self.max_len = int(max_len)
        self._model = Llama(self.cfg)

        def prefill(params, tokens):
            logits, variables = self._model.apply(
                params, tokens, mutable=["cache"]
            )
            return logits[:, -1, :], variables["cache"]

        def step(params, cache, token):
            logits, variables = self._model.apply(
                {**params, "cache": cache}, token, mutable=["cache"]
            )
            return logits[:, -1, :], variables["cache"]

        self._prefill = jax.jit(prefill)
        # Donating the cache lets XLA update its buffers in place — without
        # it every decoded token copies the full num_layers x batch x
        # max_len x kv_heads x head_dim cache (GBs at model scale).
        self._step = jax.jit(step, donate_argnums=(1,))

    def generate(
        self,
        params: dict,
        prompt: Any,
        max_new_tokens: int,
        temperature: float = 0.0,
        key: Optional[jax.Array] = None,
    ) -> jax.Array:
        """Generate ``max_new_tokens`` continuations of ``prompt``
        (shape (batch, prompt_len) int32). Returns (batch, prompt_len +
        max_new_tokens). temperature=0 is greedy; otherwise softmax
        sampling with ``key`` (required)."""
        prompt = jnp.asarray(prompt, jnp.int32)
        if prompt.ndim != 2:
            raise ValueError(f"prompt must be (batch, len), got {prompt.shape}")
        total = prompt.shape[1] + max_new_tokens
        if total > self.max_len:
            raise ValueError(
                f"prompt_len + max_new_tokens = {total} exceeds the cache "
                f"length {self.max_len}"
            )
        if temperature > 0.0 and key is None:
            raise ValueError("temperature sampling requires a PRNG key")
        logits, cache = self._prefill(params, prompt)
        out = [prompt]
        for i in range(max_new_tokens):
            if temperature <= 0.0:
                token = jnp.argmax(logits, axis=-1)[:, None].astype(jnp.int32)
            else:
                key, sub = jax.random.split(key)
                token = jax.random.categorical(
                    sub, logits / temperature, axis=-1
                )[:, None].astype(jnp.int32)
            out.append(token)
            if i + 1 < max_new_tokens:
                logits, cache = self._step(params, cache, token)
        return jnp.concatenate(out, axis=1)
