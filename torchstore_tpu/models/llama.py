"""Llama-family transformer in flax — the flagship model for weight-sync
benchmarks and examples.

The reference exercises its store with HF models (Qwen3 FSDP reshard,
/root/reference/tests/test_models.py:33-136) and the driver's BASELINE
configs name Llama-3-8B / Llama-3-70B / Mixtral-8x7B state_dict exchange.
This module provides those model families TPU-first: bfloat16 matmuls on the
MXU, RoPE + GQA attention via ``jax.nn.dot_product_attention`` (flash kernel
on TPU), SwiGLU MLP, RMSNorm, and optional MoE (Mixtral-style) layers whose
experts shard cleanly over an ``ep`` mesh axis. Logical sharding annotations
(``nn.with_logical_partitioning``) map params onto tp/fsdp/ep axes — see
``torchstore_tpu.parallel`` for the rules.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # MoE (Mixtral-style): 0 experts = dense MLP.
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # Qwen2-style: biases on the q/k/v projections only.
    attention_bias: bool = False
    # Gemma-style knobs: tanh-gelu MLP ("silu" | "gelu_tanh"), RMSNorm
    # scale stored as an offset applied as (1 + w), embeddings scaled by
    # sqrt(hidden) after lookup, and the lm_head tied to the embedding.
    mlp_act: str = "silu"
    rms_offset: bool = False
    scale_embeddings: bool = False
    tie_embeddings: bool = False
    # Long-context attention: "dense" | "ring" | "ulysses". The sharded
    # impls engage when ``mesh`` has an sp axis of size > 1 (sequence
    # parallelism); otherwise dense is used.
    attn_impl: str = "dense"
    mesh: Any = None
    # Autoregressive decoding: when True, attention maintains a per-layer
    # k/v cache (flax 'cache' collection, created lazily under
    # mutable=["cache"]) of length max_cache_len. See models/generate.py.
    decode: bool = False
    max_cache_len: int = 0

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls(
            vocab_size=128256, hidden_size=4096, intermediate_size=14336,
            num_layers=32, num_heads=32, num_kv_heads=8, head_dim=128,
        )

    @classmethod
    def llama3_70b(cls) -> "LlamaConfig":
        return cls(
            vocab_size=128256, hidden_size=8192, intermediate_size=28672,
            num_layers=80, num_heads=64, num_kv_heads=8, head_dim=128,
        )

    @classmethod
    def mixtral_8x7b(cls) -> "LlamaConfig":
        return cls(
            vocab_size=32000, hidden_size=4096, intermediate_size=14336,
            num_layers=32, num_heads=32, num_kv_heads=8, head_dim=128,
            rope_theta=1e6, num_experts=8, num_experts_per_tok=2,
        )

    @classmethod
    def qwen2_7b(cls) -> "LlamaConfig":
        return cls(
            vocab_size=152064, hidden_size=3584, intermediate_size=18944,
            num_layers=28, num_heads=28, num_kv_heads=4, head_dim=128,
            rope_theta=1e6, rms_eps=1e-6, attention_bias=True,
        )

    @classmethod
    def gemma_7b(cls) -> "LlamaConfig":
        return cls(
            vocab_size=256000, hidden_size=3072, intermediate_size=24576,
            num_layers=28, num_heads=16, num_kv_heads=16, head_dim=256,
            rope_theta=10000.0, rms_eps=1e-6, mlp_act="gelu_tanh",
            rms_offset=True, scale_embeddings=True, tie_embeddings=True,
        )

    @classmethod
    def tiny_gemma(cls) -> "LlamaConfig":
        return cls(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=8, num_kv_heads=8, head_dim=8,
            rms_eps=1e-6, mlp_act="gelu_tanh", rms_offset=True,
            scale_embeddings=True, tie_embeddings=True,
        )

    @classmethod
    def tiny(cls, vocab_size: int = 256) -> "LlamaConfig":
        # Head/mlp/vocab dims all divide 8 so the config shards on any
        # tp<=8 mesh in tests and dry runs.
        return cls(
            vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=8, num_kv_heads=8, head_dim=8,
        )

    @classmethod
    def tiny_moe(cls) -> "LlamaConfig":
        return cls(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=8, num_kv_heads=8, head_dim=8,
            num_experts=4, num_experts_per_tok=2,
        )


class RMSNorm(nn.Module):
    eps: float
    dtype: Any
    # Gemma convention: the stored param is an OFFSET applied as (1 + w),
    # zero-initialized (HF Gemma checkpoints carry the same layout).
    offset: bool = False

    @nn.compact
    def __call__(self, x):
        scale = self.param(
            "scale",
            nn.with_logical_partitioning(
                nn.initializers.zeros_init() if self.offset
                else nn.initializers.ones,
                (None,),
            ),
            (x.shape[-1],),
            jnp.float32,
        )
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        out = x.astype(jnp.float32) * jax.lax.rsqrt(var + self.eps)
        if self.offset:
            scale = 1.0 + scale
        return (out * scale).astype(self.dtype)


def _mlp_act(cfg: LlamaConfig):
    if cfg.mlp_act == "silu":
        return nn.silu
    if cfg.mlp_act == "gelu_tanh":
        return lambda x: nn.gelu(x, approximate=True)
    raise ValueError(f"unknown mlp_act {cfg.mlp_act!r}")


def rope(q, k, positions, theta: float):
    """Rotary position embeddings applied to q/k: (..., seq, heads, head_dim)."""
    head_dim = q.shape[-1]
    freqs = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    angles = positions[..., :, None].astype(jnp.float32) * freqs  # (b, s, hd/2)
    cos = jnp.cos(angles)[..., :, None, :]  # (b, s, 1, hd/2)
    sin = jnp.sin(angles)[..., :, None, :]

    def rotate(x):
        x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
        return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)

    return rotate(q).astype(q.dtype), rotate(k).astype(k.dtype)


class Attention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        dense = lambda feats, name, axes: nn.DenseGeneral(  # noqa: E731
            feats,
            axis=-1,
            # Qwen2-style checkpoints carry q/k/v biases (sharded over the
            # same head axis as the kernel's output dims).
            use_bias=cfg.attention_bias,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), axes
            ),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), axes[1:]
            ),
            name=name,
        )
        q = dense((cfg.num_heads, cfg.head_dim), "q_proj", ("embed", "heads", None))(x)
        k = dense((cfg.num_kv_heads, cfg.head_dim), "k_proj", ("embed", "kv_heads", None))(x)
        v = dense((cfg.num_kv_heads, cfg.head_dim), "v_proj", ("embed", "kv_heads", None))(x)
        if cfg.decode:
            out = self._cached_attention(q, k, v)
        else:
            q, k = rope(q, k, positions, cfg.rope_theta)
            out = _attend(cfg, q, k, v)
        out = nn.DenseGeneral(
            cfg.hidden_size,
            axis=(-2, -1),
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("heads", None, "embed")
            ),
            name="o_proj",
        )(out)
        return out

    def _cached_attention(self, q, k, v):
        """Decode-mode attention: roll q/k/v into a static-shape k/v cache
        (``lax.dynamic_update_slice`` at the running index — XLA-friendly,
        no growing shapes) and attend over the written prefix. Handles both
        the prefill call (q_len > 1, writes [0, L)) and single-token steps
        (q_len == 1, writes at idx). Cache variables are created lazily on
        the first ``mutable=["cache"]`` apply."""
        cfg = self.cfg
        if cfg.max_cache_len <= 0:
            raise ValueError("decode=True requires max_cache_len > 0")
        b, q_len = q.shape[0], q.shape[1]
        cached_k = self.variable(
            "cache",
            "k",
            jnp.zeros,
            (b, cfg.max_cache_len, cfg.num_kv_heads, cfg.head_dim),
            cfg.dtype,
        )
        cached_v = self.variable(
            "cache",
            "v",
            jnp.zeros,
            (b, cfg.max_cache_len, cfg.num_kv_heads, cfg.head_dim),
            cfg.dtype,
        )
        idx_var = self.variable(
            "cache", "idx", lambda: jnp.zeros((), jnp.int32)
        )
        idx = idx_var.value
        positions = jnp.broadcast_to(
            idx + jnp.arange(q_len)[None, :], (b, q_len)
        )
        q, k = rope(q, k, positions, cfg.rope_theta)
        new_k = jax.lax.dynamic_update_slice(
            cached_k.value, k.astype(cfg.dtype), (0, idx, 0, 0)
        )
        new_v = jax.lax.dynamic_update_slice(
            cached_v.value, v.astype(cfg.dtype), (0, idx, 0, 0)
        )
        cached_k.value, cached_v.value = new_k, new_v
        idx_var.value = idx + q_len
        # Causal over the WRITTEN prefix: kv position j participates for
        # query position p iff j <= p (unwritten tail is masked out too).
        q_pos = idx + jnp.arange(q_len)
        kv_pos = jnp.arange(cfg.max_cache_len)
        mask = kv_pos[None, None, None, :] <= q_pos[None, None, :, None]
        return jax.nn.dot_product_attention(q, new_k, new_v, mask=mask)


def _attend(cfg: LlamaConfig, q, k, v):
    """Causal attention dispatch: dense flash kernel, or sequence-parallel
    ring / Ulysses over the mesh's sp axis for long contexts."""
    use_sp = (
        cfg.attn_impl in ("ring", "ulysses")
        and cfg.mesh is not None
        and "sp" in cfg.mesh.axis_names
        and cfg.mesh.shape["sp"] > 1
    )
    if not use_sp:
        # Inside jit, XLA's fused flash attention runs near MXU peak
        # (~290 TFLOP/s on v5e at these shapes) and beats our pallas kernel
        # (~120 TFLOP/s; see ops/flash_attention.py) — so the model's dense
        # path stays on the XLA kernel. GQA handled natively.
        return jax.nn.dot_product_attention(q, k, v, is_causal=True)
    from torchstore_tpu.ops._sharded import make_sharded_attention
    from torchstore_tpu.ops.ring_attention import ring_attention
    from torchstore_tpu.ops.ulysses_attention import ulysses_attention

    sp_size = cfg.mesh.shape["sp"]
    # Keep heads tensor-parallel inside the shard_map (the bodies only
    # collective over sp) instead of redundantly all-gathering over tp.
    # Both q and kv head counts must divide tp for that.
    head_axis = None
    tp_size = 1
    if "tp" in cfg.mesh.axis_names:
        size = cfg.mesh.shape["tp"]
        if (
            size > 1
            and cfg.num_heads % size == 0
            and cfg.num_kv_heads % size == 0
        ):
            head_axis = "tp"
            tp_size = size
    impl = cfg.attn_impl
    if impl == "ulysses":
        # Divisibility applies to the SHARD-LOCAL head counts (after any tp
        # split); kv heads pass through unrepeated (GQA-native). Indivisible
        # head counts FALL BACK to ring attention (which has no head
        # constraint — k/v blocks rotate whole) instead of failing the
        # forward pass: the model keeps training, one warning names the
        # boundary that was hit.
        local_heads = cfg.num_heads // tp_size
        local_kv = cfg.num_kv_heads // tp_size
        if local_heads % sp_size != 0 or local_kv % sp_size != 0:
            from torchstore_tpu.logging import get_logger

            get_logger("torchstore_tpu.models.llama").warning(
                "ulysses attention needs per-shard head counts (q=%d, kv=%d) "
                "divisible by the sp axis size (%d); falling back to ring "
                "attention for this config",
                local_heads,
                local_kv,
                sp_size,
            )
            impl = "ring"
    body = ring_attention if impl == "ring" else ulysses_attention
    fn = make_sharded_attention(
        body, cfg.mesh, "sp", True, head_axis,
        # Ring's default ("auto") body may run the fused pallas kernel.
        relax_vma=impl == "ring",
    )
    return fn(q, k, v)


class MLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda feats, name, axes: nn.Dense(  # noqa: E731
            feats,
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), axes
            ),
            name=name,
        )
        gate = dense(cfg.intermediate_size, "gate_proj", ("embed", "mlp"))(x)
        up = dense(cfg.intermediate_size, "up_proj", ("embed", "mlp"))(x)
        return dense(cfg.hidden_size, "down_proj", ("mlp", "embed"))(
            _mlp_act(cfg)(gate) * up
        )


class MoE(nn.Module):
    """Mixtral-style sparse MoE: top-k routing over experts stored as stacked
    kernels with a leading ``expert`` axis (shards over the ep mesh axis and
    maps onto the store's expert-parallel put/get pattern)."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, s, h = x.shape
        router = nn.Dense(
            cfg.num_experts,
            use_bias=False,
            dtype=jnp.float32,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", None)
            ),
            name="router",
        )(x.astype(jnp.float32))
        weights, selected = jax.lax.top_k(
            jax.nn.softmax(router, axis=-1), cfg.num_experts_per_tok
        )
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)

        def expert_kernel(name, shape, axes):
            return self.param(
                name,
                nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(), ("expert",) + axes
                ),
                (cfg.num_experts,) + shape,
                cfg.param_dtype,
            )

        w_gate = expert_kernel("gate_proj", (h, cfg.intermediate_size), ("embed", "mlp"))
        w_up = expert_kernel("up_proj", (h, cfg.intermediate_size), ("embed", "mlp"))
        w_down = expert_kernel("down_proj", (cfg.intermediate_size, h), ("mlp", "embed"))

        # Dense-einsum MoE (every expert computes, tokens select via one-hot):
        # compiler-friendly (static shapes, no gather/scatter) and exact; a
        # capacity-based sparse kernel is the optimization path for scale.
        one_hot = jax.nn.one_hot(selected, cfg.num_experts, dtype=cfg.dtype)
        gates = jnp.einsum("bske,bsk->bse", one_hot, weights.astype(cfg.dtype))
        xe = x.astype(cfg.dtype)
        hidden = _mlp_act(cfg)(
            jnp.einsum("bsh,ehm->besm", xe, w_gate.astype(cfg.dtype))
        ) * jnp.einsum("bsh,ehm->besm", xe, w_up.astype(cfg.dtype))
        out = jnp.einsum("besm,emh->besh", hidden, w_down.astype(cfg.dtype))
        return jnp.einsum("besh,bse->bsh", out, gates)


def _constrain(x, axes):
    """Activation sharding constraint via logical axes; a no-op outside a
    flax logical_axis_rules context (see parallel.activation_rules). 'seq'
    maps to the sp mesh axis — sequence parallelism for long contexts."""
    return nn.with_logical_constraint(x, axes)


class Block(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        x = _constrain(x, ("batch", "seq", "embed"))
        x = x + Attention(cfg, name="attn")(
            RMSNorm(cfg.rms_eps, cfg.dtype, cfg.rms_offset, name="attn_norm")(x),
            positions,
        )
        mlp_cls = MoE if cfg.num_experts else MLP
        x = x + mlp_cls(cfg, name="mlp")(
            RMSNorm(cfg.rms_eps, cfg.dtype, cfg.rms_offset, name="mlp_norm")(x)
        )
        return _constrain(x, ("batch", "seq", "embed"))


class Llama(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        embed = nn.Embed(
            cfg.vocab_size,
            cfg.hidden_size,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("vocab", "embed")
            ),
            name="embed",
        )
        x = embed(tokens)
        if cfg.scale_embeddings:
            # Gemma normalizer: sqrt(hidden) in the embedding dtype (HF
            # casts the normalizer to the activation dtype before scaling).
            x = x * jnp.asarray(
                jnp.sqrt(jnp.float32(cfg.hidden_size)), x.dtype
            )
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[-1]), tokens.shape
        )
        for i in range(cfg.num_layers):
            x = Block(cfg, name=f"layer_{i}")(x, positions)
        x = RMSNorm(cfg.rms_eps, cfg.dtype, cfg.rms_offset, name="final_norm")(x)
        if cfg.tie_embeddings:
            # Gemma ties the output head to the embedding table. Compute in
            # f32 like the untied lm_head Dense below — Embed.attend would
            # round the big vocab matmul to cfg.dtype (bf16) first.
            return jnp.einsum(
                "bsh,vh->bsv",
                x.astype(jnp.float32),
                embed.embedding.astype(jnp.float32),
            )
        logits = nn.Dense(
            cfg.vocab_size,
            use_bias=False,
            dtype=jnp.float32,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "vocab")
            ),
            name="lm_head",
        )(x)
        return logits


def init_params(cfg: LlamaConfig, rng=None, batch: int = 1, seq: int = 8):
    rng = rng if rng is not None else jax.random.key(0)
    model = Llama(cfg)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    return model, model.init(rng, tokens)
