"""Scale-out metadata plane (ROADMAP item 4).

The key -> volume index that used to live inline in the ``Controller``
actor is owned here, in three pieces:

- :mod:`index_core` — ``IndexCore``, the index-owning state machine
  (StorageInfo maps, commit tracking, update generations, conditional
  stale-replica reclaims). Exactly ONE process owns any given key's
  entry: the classic single controller (shards=1), or one of N
  ``ControllerShard`` actors partitioned by stable key hash.
- :mod:`shards` — the ``ControllerShard`` actor hosting one partition,
  plus ``RemoteIndex``, the coordinator-side fan-out authority whose
  method surface matches ``IndexCore`` so every coordinator engine
  (relay forwarding, auto-repair, tier sweeps, catalogs) runs unchanged
  against local or sharded indexes.
- :mod:`router` / :mod:`stamped` — the client side: a shard router that
  fans batched metadata ops out per shard and merges replies, and the
  one-sided stamped-segment readers that resolve warm-path metadata
  (locate, plan validation, stream polling) with ZERO controller RPCs.

The tslint ``shard-discipline`` rule enforces the ownership boundary:
index-owning state is only ever touched inside this package.
"""

from torchstore_tpu.metadata.index_core import (  # noqa: F401
    IndexCore,
    ObjectType,
    PartiallyCommittedError,
    StorageInfo,
    StoreKeyError,
    resolve_manifests,
    shard_of,
)

INDEX_OPS = frozenset(
    {
        "locate_volumes",
        "contains",
        "notify_put_batch",
        "notify_delete_batch",
        "keys",
        "wait_for_committed",
        "wait_for_change",
    }
)
