"""Controller shard actors + the coordinator's fan-out index authority.

``ControllerShard`` hosts one hash partition of the key -> volume index
(an :class:`~torchstore_tpu.metadata.index_core.IndexCore`): clients route
``locate/notify/delete/keys/contains`` and the blocking waits straight to
the owning shard (see metadata/router.py), so metadata throughput scales
with shard count instead of funneling through one actor queue. Fleet-
scoped state (placement epoch, health supervisor, streams/relay/leases,
strategy) stays on the tiny coordinator — cross-shard invariants route
through it: a shard reports every STRUCTURAL index change with one
``bump_placement_epoch`` RPC before acking its notify, the coordinator
pushes quarantine transitions back down, and stream watermarks are
recorded by the coordinator strictly AFTER the owning shards indexed the
batch (so a watermark is never visible before its bytes' metadata).

``RemoteIndex`` gives the coordinator's engines (relay forwarding,
auto-repair, tier sweeps, catalogs, rebuild) the same method surface as a
local ``IndexCore``, fanned out over the shard fleet — one code path
whatever the topology.
"""

from __future__ import annotations

import asyncio
from typing import Any, Optional

from torchstore_tpu import faults
from torchstore_tpu.logging import get_logger
from torchstore_tpu.metadata.index_core import IndexCore, shard_of
from torchstore_tpu.runtime import Actor, ActorRef, endpoint
from torchstore_tpu.transport.types import Request

logger = get_logger("torchstore_tpu.metadata.shards")

# The one error string the reshard protocol speaks: a retired shard raises
# it, the router's sharded dispatch recognizes it, reloads the metadata
# topology from the coordinator, and retries once against the new mesh —
# so a client op that raced a reshard completes instead of failing.
STALE_TOPOLOGY_MSG = (
    "stale metadata topology: shard retired by reshard; reload topology"
)


def is_stale_topology(exc: BaseException) -> bool:
    """True when ``exc`` means the caller's cached metadata topology is
    stale and a reload+retry will succeed: either a retired shard from a
    reshard swap, or the coordinator refusing an index op because the plane
    went sharded after the client loaded topology (1→N reshard)."""
    if not isinstance(exc, RuntimeError):
        return False
    text = str(exc)
    return (
        "stale metadata topology" in text
        or "metadata plane is sharded" in text
    )


def partition_keys(keys, n_shards: int) -> dict[int, list]:
    out: dict[int, list] = {}
    for key in keys:
        out.setdefault(shard_of(key, n_shards), []).append(key)
    return out


def partition_metas(metas: list[Request], n_shards: int) -> dict[int, list]:
    out: dict[int, list] = {}
    for meta in metas:
        out.setdefault(shard_of(meta.key, n_shards), []).append(meta)
    return out


def slice_write_gens(
    write_gens: Optional[dict[str, dict[str, int]]], keys: set
) -> Optional[dict[str, dict[str, int]]]:
    """Restrict {volume_id: {key: gen}} to one shard's keys."""
    if not write_gens:
        return write_gens
    return {
        vid: {k: g for k, g in gens.items() if k in keys}
        for vid, gens in write_gens.items()
    }


class ControllerShard(Actor):
    """One partition of the metadata index. Spawned by ``ts.initialize(
    controller_shards=N)``; wired by the coordinator's ``attach_shards``."""

    def __init__(self) -> None:
        self.core = IndexCore(self)
        self.shard_id = 0
        self.n_shards = 1
        self.coordinator: Optional[ActorRef] = None
        self.volume_refs: dict[str, ActorRef] = {}
        self.volume_hostnames: dict[str, str] = {}
        self._quarantined: set = set()
        self._last_epoch: Optional[int] = None
        # Elastic-reshard lifecycle: freeze-via-park. While ``_frozen`` is
        # an (unset) Event, mutations PARK on it instead of failing — the
        # coordinator exports this shard's entries meanwhile (reads still
        # serve). ``shard_retire`` then wakes the parked ops to raise
        # STALE_TOPOLOGY_MSG, which the router turns into a reload+retry
        # against the new mesh: zero failed client ops across the window.
        self._frozen: Optional[asyncio.Event] = None
        self._retired = False

    # ---- IndexCore host surface ------------------------------------------

    def quarantined_ids(self) -> set:
        return set(self._quarantined)

    async def on_structural(self) -> Optional[int]:
        """A structural index change on this shard invalidates fleet-wide
        plans: report it to the coordinator BEFORE acking the client, so
        by the time a publisher sees its notify reply the epoch has moved.
        A dead coordinator fails the notify loudly — indexing without the
        epoch bump would let stale plans validate forever."""
        if self.coordinator is None:
            return None
        self._last_epoch = await self.coordinator.bump_placement_epoch.call_one()
        return self._last_epoch

    # ---- bootstrap -------------------------------------------------------

    @endpoint
    async def shard_init(
        self,
        shard_id: int,
        n_shards: int,
        coordinator: ActorRef,
        volume_refs: dict[str, ActorRef],
        volume_hostnames: dict[str, str],
        quarantined: Optional[list[str]] = None,
    ) -> dict[str, Any]:
        """Adopt this shard's slot in the fleet; idempotent across store
        re-initialization (the core resets with it). Returns the shard's
        stamped-segment descriptor for the coordinator's topology."""
        self.core.teardown()
        self.core = IndexCore(self)
        self.shard_id = int(shard_id)
        self.n_shards = int(n_shards)
        self._frozen = None
        self._retired = False
        self.coordinator = coordinator
        self.volume_refs = dict(volume_refs)
        self.volume_hostnames = dict(volume_hostnames)
        self._quarantined = set(quarantined or ())
        from torchstore_tpu.metadata import stamped as stamped_mod

        desc = None
        if stamped_mod.enabled():
            self.core.meta_writer = stamped_mod.MetaStampWriter(
                self.core.meta_payload
            )
            desc = self.core.meta_writer.describe()
        from torchstore_tpu.observability import recorder as obs_recorder

        obs_recorder.recorder().arm_exit_dump()
        return {"shard_id": self.shard_id, "stamped": desc}

    @endpoint
    async def set_quarantined(self, volume_ids: list[str]) -> None:
        """Health-supervisor push from the coordinator: locates filter the
        new quarantine picture immediately, and the stamped index
        republishes so one-sided readers see it too."""
        self._quarantined = set(volume_ids)
        self.core.mark_meta_dirty()

    @endpoint
    async def update_volume_ref(
        self, volume_id: str, ref: ActorRef, hostname: str
    ) -> None:
        self.volume_refs[volume_id] = ref
        self.volume_hostnames[volume_id] = hostname

    # ---- elastic-reshard lifecycle ---------------------------------------

    def _check_retired(self) -> None:
        if self._retired:
            raise RuntimeError(STALE_TOPOLOGY_MSG)

    async def _mutation_gate(self) -> None:
        """Park mutations while frozen; raise stale-topology once retired.
        Reads bypass this (a frozen shard's index is immutable, so serving
        reads from it is exactly as consistent as before the freeze)."""
        self._check_retired()
        if self._frozen is not None:
            await self._frozen.wait()
            self._check_retired()

    @endpoint
    async def shard_freeze(self) -> int:
        """Stop the index moving: mutations park until retire (or thaw via
        re-init). Returns the number of index keys frozen — the count the
        coordinator cross-checks against its export."""
        if self._frozen is None:
            self._frozen = asyncio.Event()
        return len(self.core.index)

    @endpoint
    async def export_entries(self) -> list:
        """This shard's whole slice in ``reindex`` input shape (call while
        frozen — exporting a moving index would lose racing notifies)."""
        return self.core.export_entries()

    @endpoint
    async def shard_thaw(self) -> None:
        """Abort a reshard before the swap: wake parked mutations to run
        against THIS still-authoritative shard (not retired, so the gate
        falls through). Idempotent."""
        if self._frozen is not None and not self._retired:
            gate, self._frozen = self._frozen, None
            gate.set()

    @endpoint
    async def shard_retire(self) -> None:
        """Terminal: wake parked mutations to raise stale-topology, close
        the stamped segment (one-sided readers fall back to RPC, which
        reloads them onto the new mesh), and drop the index. Pending
        reclaim drainers keep running — their volume refs stay valid and
        the stale bytes they guard must still be deleted."""
        self._retired = True
        if self._frozen is not None:
            self._frozen.set()
        if self.core.meta_writer is not None:
            self.core.meta_writer.close()
            self.core.meta_writer = None

    # ---- client-routed index ops -----------------------------------------

    @endpoint
    async def locate_volumes(
        self,
        keys: list[str],
        missing_ok: bool = False,
        require_fully_committed: bool = True,
    ):
        await faults.afire("controller.shard_dispatch")
        self._check_retired()
        return await self.core.locate(keys, missing_ok, require_fully_committed)

    @endpoint
    async def contains(self, key: str) -> str:
        await faults.afire("controller.shard_dispatch")
        self._check_retired()
        return await self.core.contains(key)

    @endpoint
    async def notify_put_batch(
        self,
        metas: list[Request],
        volume_id,
        detach_volume_ids: Optional[list[str]] = None,
        write_gens: Optional[dict[str, dict[str, int]]] = None,
        supersede: bool = False,
    ) -> Optional[int]:
        """The shard half of a notify: index + detach + reclaim scheduling
        for THIS shard's keys. Stream watermarks never reach a shard — the
        router records them on the coordinator after every owning shard
        acked (bytes-committed before watermark-visible, as ever). Returns
        the fresh placement epoch after a structural change (learned from
        the coordinator in the same dispatch), else None."""
        await faults.afire("controller.shard_dispatch")
        await faults.afire("controller.notify")
        await self._mutation_gate()
        volume_ids = [volume_id] if isinstance(volume_id, str) else volume_id
        structural = await self.core.apply_put_batch(
            metas,
            volume_ids,
            detach_volume_ids=detach_volume_ids,
            write_gens=write_gens,
            supersede=supersede,
        )
        await self.core.bump({meta.key for meta in metas})
        return self._last_epoch if structural else None

    @endpoint
    async def delete_keys(self, keys: list[str]) -> dict[str, list[str]]:
        """Index-drop for this shard's keys (the router already ran the
        coordinator's lease guard). Deletions are structural."""
        await faults.afire("controller.shard_dispatch")
        await self._mutation_gate()
        self.core.count_deletes(len(keys))
        by_volume = self.core.delete_keys(keys)
        deleted = {k for vkeys in by_volume.values() for k in vkeys}
        if deleted:
            await self.on_structural()
            await self.core.bump(deleted)
        return by_volume

    @endpoint
    async def keys(self, prefix: Optional[str] = None) -> list[str]:
        await faults.afire("controller.shard_dispatch")
        self._check_retired()
        return await self.core.keys_list(prefix)

    @endpoint
    async def count_prefix(self, prefix: str) -> int:
        return await self.core.count_prefix(prefix)

    @endpoint
    async def wait_for_committed(
        self, keys: list[str], timeout: Optional[float] = None
    ) -> None:
        await self.core.wait_for_committed(keys, timeout)

    @endpoint
    async def wait_for_change(
        self, key: str, last_gen: int = 0, timeout: Optional[float] = None
    ) -> dict[str, Any]:
        return await self.core.wait_for_change(key, last_gen, timeout)

    # ---- coordinator-engine services -------------------------------------

    @endpoint
    async def index_get(self, key: str):
        return await self.core.get_entry(key)

    @endpoint
    async def merge_copies(
        self, volume_id: str, metas: list[Request], write_gens: dict[str, int]
    ) -> list[str]:
        await self._mutation_gate()
        return sorted(await self.core.merge_copies(volume_id, metas, write_gens))

    @endpoint
    async def migrate_key(
        self, key: str, src: str, dst: str, drop_src: bool = True
    ) -> dict[str, Any]:
        await self._mutation_gate()
        return await self.core.migrate_key(key, src, dst, drop_src=drop_src)

    @endpoint
    async def auto_repair(self, volume_id: str, healthy: list[str]) -> int:
        return await self.core.auto_repair_pass(volume_id, healthy)

    @endpoint
    async def detach_volume(self, volume_id: str) -> dict[str, Any]:
        result = await self.core.detach_volume(volume_id)
        await self.on_structural()
        return result

    @endpoint
    async def set_tiers(
        self, volume_id: str, spilled: list[str], fault_ins: list[str]
    ) -> None:
        await self.core.set_tiers(volume_id, spilled, fault_ins)

    @endpoint
    async def reindex(self, survivors: list) -> int:
        count = await self.core.reindex(survivors)
        await self.on_structural()
        return count

    @endpoint
    async def summary(self) -> dict:
        return await self.core.summary()

    @endpoint
    async def catalog(self, channel: Optional[str] = None) -> dict:
        return await self.core.catalog(channel)

    @endpoint
    async def meta_flush(self) -> None:
        """Publish the stamped index NOW (tests/benches pin down 'the
        one-sided view is current' without sleeping out the debounce)."""
        if self.core.meta_writer is not None:
            self.core.meta_writer.publish_now()

    # ---- fault injection / teardown --------------------------------------

    @endpoint
    async def inject_fault(
        self,
        name: str,
        action: str,
        count: Optional[int] = None,
        prob: Optional[float] = None,
        delay_ms: Optional[float] = None,
    ) -> dict:
        return faults.arm(name, action, count=count, prob=prob, delay_ms=delay_ms)

    @endpoint
    async def clear_faults(self, name: Optional[str] = None) -> int:
        return faults.disarm(name)

    @endpoint
    async def list_faults(self) -> list:
        return faults.armed()

    @endpoint
    async def flight_record(self) -> list:
        from torchstore_tpu.observability import recorder as obs_recorder

        return obs_recorder.snapshot()

    @endpoint
    async def shard_teardown(self) -> None:
        if self.core.meta_writer is not None:
            self.core.meta_writer.close()
            self.core.meta_writer = None
        self.core.teardown()


class RemoteIndex:
    """Coordinator-side index authority over a shard fleet: the same
    method names as :class:`IndexCore`, implemented as per-shard fan-out.
    Engines written against the core run unchanged against this."""

    def __init__(self, shard_refs: list[ActorRef]) -> None:
        self.shard_refs = list(shard_refs)
        self.n = len(shard_refs)

    def _ref(self, key: str) -> ActorRef:
        return self.shard_refs[shard_of(key, self.n)]

    async def locate(
        self,
        keys: list[str],
        missing_ok: bool = False,
        require_fully_committed: bool = True,
    ) -> dict:
        parts = partition_keys(keys, self.n)
        results = await asyncio.gather(
            *(
                self.shard_refs[i].locate_volumes.call_one(
                    ks, missing_ok, require_fully_committed
                )
                for i, ks in parts.items()
            )
        )
        merged: dict = {}
        for part in results:
            merged.update(part)
        return merged

    async def contains(self, key: str) -> str:
        return await self._ref(key).contains.call_one(key)

    async def keys_list(self, prefix: Optional[str] = None) -> list[str]:
        results = await asyncio.gather(
            *(ref.keys.call_one(prefix) for ref in self.shard_refs)
        )
        return sorted(k for part in results for k in part)

    async def count_prefix(self, prefix: str) -> int:
        return sum(
            await asyncio.gather(
                *(ref.count_prefix.call_one(prefix) for ref in self.shard_refs)
            )
        )

    async def get_entry(self, key: str):
        return await self._ref(key).index_get.call_one(key)

    async def merge_copies(
        self, volume_id: str, metas: list[Request], write_gens: dict[str, int]
    ) -> set:
        parts = partition_metas(metas, self.n)
        results = await asyncio.gather(
            *(
                self.shard_refs[i].merge_copies.call_one(
                    volume_id,
                    ms,
                    {m.key: write_gens.get(m.key, 0) for m in ms},
                )
                for i, ms in parts.items()
            )
        )
        return {k for part in results for k in part}

    async def migrate_key(
        self, key: str, src: str, dst: str, drop_src: bool = True
    ) -> dict[str, Any]:
        return await self._ref(key).migrate_key.call_one(
            key, src, dst, drop_src
        )

    async def export_entries(self) -> list:
        parts = await asyncio.gather(
            *(ref.export_entries.call_one() for ref in self.shard_refs)
        )
        return [entry for part in parts for entry in part]

    async def auto_repair_pass(self, volume_id: str, healthy: list[str]) -> int:
        return sum(
            await asyncio.gather(
                *(
                    ref.auto_repair.call_one(volume_id, healthy)
                    for ref in self.shard_refs
                )
            )
        )

    async def detach_volume(self, volume_id: str) -> dict[str, Any]:
        results = await asyncio.gather(
            *(ref.detach_volume.call_one(volume_id) for ref in self.shard_refs)
        )
        merged = {"recoverable": {}, "lost": []}
        for part in results:
            merged["recoverable"].update(part["recoverable"])
            merged["lost"].extend(part["lost"])
        return merged

    async def set_tiers(
        self, volume_id: str, spilled: list[str], fault_ins: list[str]
    ) -> None:
        # Every shard ignores keys it doesn't own: the per-sweep lists are
        # small, so a broadcast beats client-side partitioning here.
        await asyncio.gather(
            *(
                ref.set_tiers.call_one(volume_id, spilled, fault_ins)
                for ref in self.shard_refs
            )
        )

    async def reindex(self, survivors: list) -> int:
        parts: dict[int, list] = {}
        for vid, meta, gen in survivors:
            parts.setdefault(shard_of(meta.key, self.n), []).append(
                (vid, meta, gen)
            )
        return sum(
            await asyncio.gather(
                *(
                    self.shard_refs[i].reindex.call_one(entries)
                    for i, entries in parts.items()
                )
            )
        )

    async def summary(self) -> dict:
        parts = await asyncio.gather(
            *(ref.summary.call_one() for ref in self.shard_refs)
        )
        merged: dict[str, Any] = {
            "puts": 0,
            "put_bytes": 0,
            "locates": 0,
            "deletes": 0,
            "num_keys": 0,
            "sharded_keys": 0,
            "indexed_bytes_approx": 0,
            "pending_reclaims": {},
        }
        for part in parts:
            for field in (
                "puts",
                "put_bytes",
                "locates",
                "deletes",
                "num_keys",
                "sharded_keys",
                "indexed_bytes_approx",
            ):
                merged[field] += part.get(field, 0)
            for vid, n in (part.get("pending_reclaims") or {}).items():
                merged["pending_reclaims"][vid] = (
                    merged["pending_reclaims"].get(vid, 0) + n
                )
        return merged

    async def catalog(self, channel: Optional[str] = None) -> dict:
        parts = await asyncio.gather(
            *(ref.catalog.call_one(channel) for ref in self.shard_refs)
        )
        merged: dict = {}
        for part in parts:
            for chan, versions in part.items():
                for ver, rec in versions.items():
                    agg = merged.setdefault(chan, {}).setdefault(
                        ver,
                        {
                            "keys": 0,
                            "bytes": 0,
                            "resident_keys": 0,
                            "spilled_keys": 0,
                            "volumes": set(),
                            "leases": [],
                        },
                    )
                    for field in (
                        "keys",
                        "bytes",
                        "resident_keys",
                        "spilled_keys",
                    ):
                        agg[field] += rec.get(field, 0)
                    agg["volumes"].update(rec.get("volumes") or ())
        return merged

    async def wait_for_committed(
        self, keys: list[str], timeout: Optional[float] = None
    ) -> None:
        parts = partition_keys(keys, self.n)
        await asyncio.gather(
            *(
                self.shard_refs[i].wait_for_committed.with_timeout(
                    0 if timeout is None else timeout + 10.0
                ).call_one(ks, timeout)
                for i, ks in parts.items()
            )
        )

    async def wait_for_change(
        self, key: str, last_gen: int = 0, timeout: Optional[float] = None
    ) -> dict[str, Any]:
        return await self._ref(key).wait_for_change.with_timeout(
            0 if timeout is None else timeout + 10.0
        ).call_one(key, last_gen, timeout)

    async def teardown(self) -> None:
        await asyncio.gather(
            *(ref.shard_teardown.call_one() for ref in self.shard_refs),
            return_exceptions=True,
        )


# Re-exported for the router's use (one partitioning vocabulary).
__all__ = [
    "STALE_TOPOLOGY_MSG",
    "ControllerShard",
    "RemoteIndex",
    "is_stale_topology",
    "partition_keys",
    "partition_metas",
    "slice_write_gens",
    "shard_of",
]
