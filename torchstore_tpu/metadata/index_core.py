"""The index-owning half of the metadata plane: ``IndexCore``.

Everything that reads or writes the key -> {volume_id: StorageInfo} index
lives HERE — commit tracking, update generations, layout invalidation,
detach/supersede semantics, and the conditional stale-replica reclaim
drainers. The classic single ``Controller`` hosts one core; a sharded
metadata plane hosts one core per ``ControllerShard`` actor, partitioned
by :func:`shard_of`. Either way, exactly one process owns a key's entry,
so none of the single-writer invariants change with the topology.

The host (Controller or ControllerShard) provides fleet context through a
tiny duck-typed surface:

- ``host.volume_refs`` / ``host.volume_hostnames``: live volume handles
  (read dynamically — repair swaps refs underneath).
- ``host.quarantined_ids()``: the health supervisor's current verdict
  (pushed to shards on every transition).
- ``await host.on_structural()``: a structural metadata change happened —
  bump the placement epoch (locally on the coordinator, one RPC from a
  shard). Returns the new epoch when known.

tslint's ``shard-discipline`` rule forbids touching ``.index`` /
``._key_gens`` outside this package: controller.py engines reach the
index only through these methods (or their RemoteIndex fan-out twins).
"""

from __future__ import annotations

import math
import zlib
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Optional

from torchstore_tpu import faults
from torchstore_tpu import tiering
from torchstore_tpu.logging import get_logger
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.storage_utils.trie import Trie
from torchstore_tpu.transport.types import Request, TensorMeta, TensorSlice
from torchstore_tpu.utils import spawn_logged

logger = get_logger("torchstore_tpu.metadata")

# Metadata-plane instruments (live in whichever process hosts the core —
# the controller, or each shard; surfaced through ``stats()``/``summary``).
_PUTS = obs_metrics.counter("ts_controller_puts_total", "Logical puts indexed")
_PUT_BYTES = obs_metrics.counter(
    "ts_controller_put_bytes_total", "Logical bytes indexed by puts"
)
_LOCATES = obs_metrics.counter("ts_controller_locates_total", "Keys located")
_DELETES = obs_metrics.counter("ts_controller_deletes_total", "Keys deleted")
_KEYS = obs_metrics.gauge("ts_controller_keys", "Keys currently indexed")
_PENDING_RECLAIMS = obs_metrics.gauge(
    "ts_controller_pending_reclaims",
    "Stale-replica reclaims not yet drained, per volume",
)
_RECLAIMED = obs_metrics.counter(
    "ts_controller_reclaimed_keys_total",
    "Stale copies deleted by the background reclaim",
)
_AUTO_REPAIRS = obs_metrics.counter(
    "ts_auto_repairs_total",
    "Keys re-replicated automatically after a quarantine",
)


def shard_of(key: str, n_shards: int) -> int:
    """Stable key -> shard assignment (crc32, not Python hash: every
    process — clients, coordinator, shards — must agree across runs and
    interpreters)."""
    if n_shards <= 1:
        return 0
    return zlib.crc32(key.encode("utf-8", "replace")) % n_shards


class ObjectType(Enum):
    OBJECT = "object"
    TENSOR = "tensor"
    TENSOR_SLICE = "tensor_slice"


def _object_type(meta: Request) -> ObjectType:
    if meta.is_object:
        return ObjectType.OBJECT
    if meta.tensor_slice is not None:
        return ObjectType.TENSOR_SLICE
    return ObjectType.TENSOR


class PartiallyCommittedError(KeyError):
    pass


class StoreKeyError(KeyError):
    pass


@dataclass
class StorageInfo:
    """What one volume holds for one key
    (/root/reference/torchstore/controller.py:36-64)."""

    object_type: ObjectType
    tensor_meta: Optional[TensorMeta] = None
    # coords -> TensorSlice, for TENSOR_SLICE keys.
    tensor_slices: dict[tuple, TensorSlice] = field(default_factory=dict)
    # The volume-assigned write generation of the newest put indexed here
    # (volume-local timestamp; see StorageVolume._bump_write_gens). When
    # this replica is later detached, the reclaim deletes its copy only if
    # the volume's generation hasn't moved past this — an acknowledged put
    # racing the reclaim can never lose its bytes (ADVICE r3).
    write_gen: int = 0
    # Capacity tier of this replica's bytes: ``tiering.RESIDENT`` (memory/
    # tmpfs — the zero-copy warm path) or ``tiering.TIERED`` (demoted to
    # the volume's disk spill tier; the next get faults it back in).
    # Metadata only: placement and transports are tier-agnostic.
    tier: str = tiering.RESIDENT

    def merge(self, meta: Request) -> None:
        incoming = _object_type(meta)
        if incoming != self.object_type:
            raise ValueError(
                f"type confusion: stored {self.object_type} vs incoming {incoming}"
            )
        if meta.tensor_slice is not None:
            self.tensor_slices[meta.tensor_slice.coordinates] = meta.tensor_slice
        if meta.tensor_meta is not None:
            self.tensor_meta = meta.tensor_meta

    @classmethod
    def from_meta(cls, meta: Request) -> "StorageInfo":
        info = cls(object_type=_object_type(meta), tensor_meta=meta.tensor_meta)
        if meta.tensor_slice is not None:
            info.tensor_slices[meta.tensor_slice.coordinates] = meta.tensor_slice
        return info


def resolve_manifests(
    per_volume: list[tuple[str, list]],
) -> tuple[list[tuple[str, Request, int]], int]:
    """Resolve volume manifests into (volume_id, meta, write_gen) entries to
    index, keeping only the NEWEST shard layout (by file mtime) when a key
    carries mixed mesh/global shapes — see ``Controller.rebuild_index``.
    Returns (survivors, dropped_count). Accepts bare ``Request`` items from
    backends without mtimes (treated as mtime 0, write_gen 0)."""
    entries: list[tuple[str, Request, Optional[tuple], int]] = []
    layouts: dict[str, dict[tuple, float]] = {}  # key -> sig -> max mtime
    for vid, manifest in per_volume:
        for item in manifest:
            if isinstance(item, dict):
                meta, mtime = item["meta"], item.get("mtime", 0.0)
                gen = item.get("write_gen", 0)
            else:
                meta, mtime, gen = item, 0.0, 0
            sig = None
            if meta.tensor_slice is not None:
                ts = meta.tensor_slice
                sig = (
                    ts.mesh_shape,
                    ts.global_shape,
                    meta.tensor_meta.dtype if meta.tensor_meta else None,
                )
                sigs = layouts.setdefault(meta.key, {})
                sigs[sig] = max(sigs.get(sig, 0.0), mtime)
            entries.append((vid, meta, sig, gen))
    winners = {
        key: max(sigs, key=sigs.get)
        for key, sigs in layouts.items()
        if len(sigs) > 1
    }
    survivors: list[tuple[str, Request, int]] = []
    dropped = 0
    for vid, meta, sig, gen in entries:
        if sig is not None and meta.key in winners and sig != winners[meta.key]:
            dropped += 1
            continue
        survivors.append((vid, meta, gen))
    return survivors, dropped


class IndexCore:
    def __init__(self, host) -> None:
        self.host = host
        self.index = Trie()  # key -> {volume_id: StorageInfo}
        self.counters = {
            "puts": 0,
            "put_bytes": 0,
            "locates": 0,
            "deletes": 0,
        }
        # Per-key update generation + a condition notified on every index
        # change: the substrate for wait_for_committed / wait_for_change.
        self._key_gens: dict[str, int] = {}
        self._update_cond: Optional[Any] = None  # lazily created on its loop
        # Best-effort reclaims of stale copies on detached replicas:
        # {key: stale write gen} pending per volume, ONE drainer task per
        # volume, all cancelled at teardown.
        self._pending_reclaims: dict[str, dict[str, int]] = {}
        self._reclaim_running: set = set()
        self._reclaim_tasks: set = set()
        # One-sided stamped metadata publisher (metadata/stamped.py);
        # attached by the host when enabled. Every index change marks it
        # dirty; it republishes the committed view on a debounced cadence.
        self.meta_writer = None

    # ---- conditions / generations ---------------------------------------

    def cond(self):
        import asyncio

        if self._update_cond is None:
            self._update_cond = asyncio.Condition()
        return self._update_cond

    async def bump(self, keys) -> None:
        cond = self.cond()
        async with cond:
            for key in keys:
                self._key_gens[key] = self._key_gens.get(key, 0) + 1
            cond.notify_all()
        _KEYS.set(len(self.index))
        self.mark_meta_dirty()

    def mark_meta_dirty(self) -> None:
        if self.meta_writer is not None:
            self.meta_writer.mark_dirty()

    # ---- commit tracking -------------------------------------------------

    def committed_state(self, volume_infos: dict[str, StorageInfo]) -> str:
        """'committed' | 'partial' for one key. A sharded key is fully
        committed when stored coords across all volumes cover
        product(mesh_shape) (/root/reference/torchstore/controller.py:66-104)."""
        any_info = next(iter(volume_infos.values()))
        if any_info.object_type != ObjectType.TENSOR_SLICE:
            return "committed"
        coords: set[tuple] = set()
        mesh_shape: Optional[tuple] = None
        for info in volume_infos.values():
            coords.update(info.tensor_slices.keys())
            for ts in info.tensor_slices.values():
                mesh_shape = ts.mesh_shape
        expected = math.prod(mesh_shape) if mesh_shape else 0
        return "committed" if len(coords) >= expected else "partial"

    def covers(
        self,
        subset: dict[str, StorageInfo],
        full: dict[str, StorageInfo],
    ) -> bool:
        """Whether ``subset``'s replicas serve everything ``full``'s do.
        Non-sharded entries are full copies, so any surviving replica
        covers; sharded keys compare the UNION of stored coordinates."""
        any_info = next(iter(full.values()))
        if any_info.object_type != ObjectType.TENSOR_SLICE:
            return True
        sub_coords: set[tuple] = set()
        for info in subset.values():
            sub_coords.update(info.tensor_slices.keys())
        full_coords: set[tuple] = set()
        for info in full.values():
            full_coords.update(info.tensor_slices.keys())
        return sub_coords >= full_coords

    def _serving_infos(
        self, infos: dict[str, StorageInfo], quarantined: set
    ) -> dict[str, StorageInfo]:
        """The replica set a locate reports: quarantined replicas are
        omitted whenever the healthy subset alone still serves everything
        the full set does (shard-coordinate coverage, not just the coarse
        committed/partial label). A quarantined volume holding the ONLY
        copy stays listed: the client tries it and surfaces the real
        failure rather than a bogus missing-key."""
        if quarantined and any(vid in quarantined for vid in infos):
            healthy = {
                vid: info for vid, info in infos.items() if vid not in quarantined
            }
            if healthy and self.covers(healthy, infos):
                return healthy
        return infos

    # ---- core ops --------------------------------------------------------

    async def locate(
        self,
        keys: list[str],
        missing_ok: bool = False,
        require_fully_committed: bool = True,
    ) -> dict[str, dict[str, StorageInfo]]:
        await faults.afire("controller.locate")
        self.counters["locates"] += len(keys)
        _LOCATES.inc(len(keys))
        quarantined = self.host.quarantined_ids()
        out: dict[str, dict[str, StorageInfo]] = {}
        for key in keys:
            infos = self.index.get(key)
            if infos is None:
                if missing_ok:
                    continue
                raise StoreKeyError(f"Key {key!r} not found in store")
            if require_fully_committed and self.committed_state(infos) == "partial":
                raise PartiallyCommittedError(
                    f"Key {key!r} is only partially committed; not all mesh "
                    "coordinates have been stored yet"
                )
            out[key] = self._serving_infos(infos, quarantined)
        return out

    async def contains(self, key: str) -> str:
        infos = self.index.get(key)
        if infos is None:
            return "missing"
        return self.committed_state(infos)

    async def keys_list(self, prefix: Optional[str] = None) -> list[str]:
        if prefix is None:
            return sorted(self.index)
        return sorted(self.index.keys().filter_by_prefix(prefix))

    async def count_prefix(self, prefix: str) -> int:
        return sum(1 for _ in self.index.keys().filter_by_prefix(prefix))

    async def apply_put_batch(
        self,
        metas: list[Request],
        volume_ids: list[str],
        detach_volume_ids: Optional[list[str]] = None,
        write_gens: Optional[dict[str, dict[str, int]]] = None,
        supersede: bool = False,
    ) -> bool:
        """Index ``metas`` as stored on every id in ``volume_ids`` — the
        index half of ``notify_put_batch`` (see Controller.notify_put_batch
        for the full contract). Detaches failed/superseded replicas in the
        same step, schedules their conditional reclaims, and reports a
        structural change through ``host.on_structural()``. The caller owns
        the generation bump (the coordinator records stream watermarks
        between indexing and the bump so no reader wakes early)."""
        stale_gens: dict[str, dict[str, int]] = {}
        structural = bool(detach_volume_ids)
        for meta in metas:
            if meta.tensor_val is not None or meta.objects is not None:
                raise ValueError(
                    "controller must never receive data payloads; send "
                    "meta_only() requests"
                )
            infos = self.index.get(meta.key)
            # Generations of copies indexed BEFORE this notify — the
            # layout-invalidation wipe below must not erase them, or a
            # detached replica's reclaim would never be scheduled and its
            # stale old-layout bytes would stay readable via warm caches.
            pre_gens = (
                {vid: info.write_gen for vid, info in infos.items()}
                if infos is not None
                else {}
            )
            if infos is not None and meta.tensor_slice is not None:
                # Re-publishing a key under a different layout (mesh shape or
                # global shape changed) invalidates every previously indexed
                # shard — otherwise stale old-layout shards would satisfy the
                # commit check and be served alongside new data.
                stale = False
                for prev in infos.values():
                    for ts in prev.tensor_slices.values():
                        if (
                            ts.mesh_shape != meta.tensor_slice.mesh_shape
                            or ts.global_shape != meta.tensor_slice.global_shape
                        ):
                            stale = True
                if stale:
                    infos = None
                    structural = True  # layout change re-routes every fetch
            if infos is None:
                infos = {}
                self.index[meta.key] = infos
                structural = True  # key newly (re)appears in the index
            for vid in volume_ids:
                info = infos.get(vid)
                if info is None:
                    info = infos[vid] = StorageInfo.from_meta(meta)
                    structural = True  # new replica placement
                else:
                    if (
                        meta.tensor_meta is not None
                        and info.tensor_meta is not None
                        and info.tensor_meta != meta.tensor_meta
                    ):
                        # Same key, different shape/dtype: any plan built
                        # against the old meta would land wrong bytes.
                        structural = True
                    info.merge(meta)
                # Fresh bytes always land in the memory tier (the volume
                # discards any stale disk-tier copy in the same put).
                info.tier = tiering.RESIDENT
                if write_gens:
                    info.write_gen = max(
                        info.write_gen,
                        write_gens.get(vid, {}).get(meta.key, 0),
                    )
            # Count as each entry indexes, so a mid-batch rejection leaves
            # counters consistent with what actually landed in the index.
            self.counters["puts"] += 1
            _PUTS.inc()
            if meta.tensor_meta is not None:
                self.counters["put_bytes"] += meta.tensor_meta.nbytes
                _PUT_BYTES.inc(meta.tensor_meta.nbytes)
            for vid in detach_volume_ids or ():
                # Capture the generation of the copy being detached BEFORE
                # removing it — the reclaim may delete the replica's bytes
                # only while its generation hasn't moved past this.
                # pre_gens covers entries the layout-invalidation wipe
                # already dropped from `infos`. A volume with NO prior
                # indexed copy may still hold bytes from a PARTIAL batch
                # landing (some requests landed before one failed): -1
                # marks "generation unknown — resolve volume-side" so the
                # reclaim's two-phase delete can still collect them.
                prev = infos.get(vid)
                if prev is not None:
                    stale_gens.setdefault(vid, {})[meta.key] = prev.write_gen
                elif vid in pre_gens:
                    stale_gens.setdefault(vid, {})[meta.key] = pre_gens[vid]
                else:
                    stale_gens.setdefault(vid, {}).setdefault(meta.key, -1)
                # The epoch bump below is gated on `structural`, seeded
                # from bool(detach_volume_ids) — the exact condition that
                # makes this loop run, so every detach IS bump-covered.
                self.detach_meta(meta, vid)  # tslint: disable=epoch-discipline
            if supersede:
                # Full overwrite: volumes outside this put's replica set
                # that still hold THIS meta (same coordinates for shards,
                # the whole entry otherwise) now carry superseded bytes —
                # detach them here, reclaim their bytes in the background.
                for vid in [v for v in list(infos) if v not in volume_ids]:
                    prev = infos.get(vid)
                    if prev is None:
                        continue
                    if meta.tensor_slice is not None and (
                        prev.object_type != ObjectType.TENSOR_SLICE
                        or meta.tensor_slice.coordinates
                        not in prev.tensor_slices
                    ):
                        continue  # holds other shards only: not superseded
                    stale_gens.setdefault(vid, {})[meta.key] = prev.write_gen
                    # `structural = True` on the next line routes this
                    # detach into the on_structural bump below.
                    self.detach_meta(meta, vid)  # tslint: disable=epoch-discipline
                    structural = True
        if stale_gens:
            # The detached replica may be wedged-but-ALIVE and still holding
            # the old bytes: clients with warm location caches would read
            # the stale value from it, and delete_batch fans out by index
            # (which no longer lists it) so the bytes would never be
            # reclaimed. Best-effort background conditional delete once
            # it's reachable.
            for vid, keys in stale_gens.items():
                self.schedule_reclaim(vid, keys)
        if structural:
            await self.host.on_structural()
        return structural

    def count_deletes(self, n: int) -> None:
        self.counters["deletes"] += n
        _DELETES.inc(n)

    def delete_keys(self, keys: list[str]) -> dict[str, list[str]]:
        """Remove keys from the index; returns which volumes held each key
        so the caller can clear the data plane. Idempotent. The caller
        owns the structural report + generation bump (the coordinator
        retires stream records between the two)."""
        by_volume: dict[str, list[str]] = {}
        for key in keys:
            infos = self.index.pop(key, None)
            if infos is None:
                continue  # idempotent delete
            for vid in infos:
                by_volume.setdefault(vid, []).append(key)
        return by_volume

    # ---- blocking waits --------------------------------------------------

    async def wait_for_committed(
        self, keys: list[str], timeout: Optional[float] = None
    ) -> None:
        import asyncio

        cond = self.cond()

        def ready() -> bool:
            for key in keys:
                infos = self.index.get(key)
                if infos is None or self.committed_state(infos) == "partial":
                    return False
            return True

        async with cond:
            try:
                await asyncio.wait_for(cond.wait_for(ready), timeout)
            except asyncio.TimeoutError:
                missing = [
                    k
                    for k in keys
                    if self.index.get(k) is None
                    or self.committed_state(self.index.get(k)) == "partial"
                ]
                raise TimeoutError(
                    f"wait_for_committed timed out after {timeout}s; still "
                    f"missing/partial: {missing[:5]}"
                ) from None

    async def wait_for_change(
        self, key: str, last_gen: int = 0, timeout: Optional[float] = None
    ) -> dict[str, Any]:
        import asyncio

        cond = self.cond()
        async with cond:
            try:
                await asyncio.wait_for(
                    cond.wait_for(
                        lambda: self._key_gens.get(key, 0) != last_gen
                    ),
                    timeout,
                )
            except asyncio.TimeoutError:
                raise TimeoutError(
                    f"wait_for_change({key!r}) timed out after {timeout}s at "
                    f"generation {self._key_gens.get(key, 0)}"
                ) from None
            infos = self.index.get(key)
            state = (
                "missing" if infos is None else self.committed_state(infos)
            )
            return {"gen": self._key_gens.get(key, 0), "state": state}

    # ---- reclaims --------------------------------------------------------

    def _reclaim_policy(self):
        """The drainer's backoff schedule as a RetryPolicy (the unified
        retry vocabulary — config.RetryPolicy). TORCHSTORE_TPU_RECLAIM_DELAYS
        overrides the default 1,5,15,60 schedule; malformed values fall back
        (a parse error must not kill the drainer — it would leave the
        volume's running-flag set and wedge reclaims forever)."""
        import os

        from torchstore_tpu.config import RetryPolicy

        # deadline_s=inf: the schedule length IS the attempt budget (the
        # pre-policy drainer always ran every entry). A wall-clock deadline
        # here would skip the long tail exactly when a slow-recovering
        # volume makes each attempt's RPCs block until their own timeout —
        # the case the 60 s entry exists for.
        env = os.environ.get("TORCHSTORE_TPU_RECLAIM_DELAYS")
        if env:
            try:
                return RetryPolicy.from_delays(
                    env.split(","), deadline_s=float("inf")
                )
            except ValueError:
                logger.warning(
                    "ignoring malformed TORCHSTORE_TPU_RECLAIM_DELAYS=%r", env
                )
        return RetryPolicy.from_delays(
            (1.0, 5.0, 15.0, 60.0), deadline_s=float("inf")
        )

    def schedule_reclaim(self, volume_id: str, keys: dict[str, int]) -> None:
        """``keys``: {key: stale write generation} — the generation of the
        copy that was just detached (the newest bytes the reclaim is
        allowed to delete)."""
        pending = self._pending_reclaims.setdefault(volume_id, {})
        for key, gen in keys.items():
            # -1 = unknown generation (resolved volume-side at drain time);
            # a known generation always wins over unknown.
            pending[key] = max(pending[key], gen) if key in pending else gen
        _PENDING_RECLAIMS.set(len(pending), volume=volume_id)
        if volume_id in self._reclaim_running:
            return  # the volume's drainer picks the new keys up
        self._reclaim_running.add(volume_id)
        # A drainer that dies on an unexpected exception must be LOUD: the
        # volume's running-flag was cleared in its finally, but the stale
        # bytes stay resident until the next detach — spawn_logged retains
        # the task and logs + counts the failure instead of dropping it.
        spawn_logged(
            self._reclaim_detached(volume_id),
            name="controller.reclaim",
            tasks=self._reclaim_tasks,
            log=logger,
        )

    async def _reclaim_detached(self, volume_id: str) -> None:
        """Drain the volume's pending stale keys once it recovers (ADVICE
        r2). Keys re-indexed on the volume in the meantime are skipped (a
        later put/repair re-replicated fresh bytes there). The delete is
        CONDITIONAL on the stale write generation (ADVICE r3): a put
        landing any time after the detach bumped the volume's generation,
        so the volume keeps its bytes and reports them fresh — an
        acknowledged overwrite can never be destroyed by a racing reclaim,
        even at replication factor 1.

        Keys scheduled with generation -1 (partial batch landings the
        controller never saw a generation for) resolve in two phases: the
        volume reports its CURRENT generation first, then the conditional
        delete targets exactly the observed bytes — anything fresher that
        lands during the RPC is kept. As the safety net for the residual
        race (a delete landing while the bytes' notify is still in
        flight), every completed delete is reconciled against the index:
        if the index meanwhile claims this volume holds a deleted key, the
        entry is detached loudly (degraded redundancy, healed by the next
        publish) instead of pointing readers at missing bytes."""
        import asyncio

        try:
            policy = self._reclaim_policy()
            deadline = policy.start()
            attempt = 0
            while policy.should_retry(attempt, deadline):
                await asyncio.sleep(policy.backoff(attempt))
                attempt += 1
                ref = self.host.volume_refs.get(volume_id)
                pending = self._pending_reclaims.get(volume_id)
                if ref is None or not pending:
                    return
                batch = {
                    k: g
                    for k, g in pending.items()
                    if volume_id not in self.index.get(k, {})
                }
                for key in list(pending):
                    if key not in batch:
                        del pending[key]  # re-indexed keys: done
                if not batch:
                    return
                unknown = sorted(k for k, g in batch.items() if g < 0)
                try:
                    if unknown:
                        observed = await ref.write_gens.call_one(unknown)
                        for key in unknown:
                            if key in observed:
                                batch[key] = observed[key]
                            # Keys ABSENT from the reply stay in the batch at
                            # gen -1: on a durable backend after a volume
                            # restart, stale partial-landing bytes can exist
                            # with no in-memory generation — dropping them
                            # here would leave them readable via warm
                            # location caches forever. delete_batch_if
                            # deletes keys with no recorded generation, and
                            # a put racing in records one and is kept
                            # (ADVICE r4 carried fix).
                        # Keys indexed on this volume while we fetched gens
                        # are fresh again — drop them before deleting.
                        for key in list(batch):
                            if volume_id in self.index.get(key, {}):
                                del batch[key]
                        if not batch:
                            continue
                    result = await ref.delete_batch_if.call_one(
                        sorted(batch.items())
                    )
                except Exception:  # noqa: BLE001 - still wedged/dead; retry
                    continue
                for key, sent_gen in batch.items():
                    # A NEWER stale generation scheduled while the RPC was
                    # in flight must survive for the next round — pop only
                    # what this delete actually covered.
                    if pending.get(key) in (sent_gen, -1):
                        pending.pop(key, None)
                for key, gen in result.get("kept_gens", {}).items():
                    # Fresh bytes raced the reclaim. Normally the racing
                    # put's notify (re)indexes this volume and the next
                    # round filters the key out; if that notify never
                    # arrives (client died between data-plane ack and
                    # notify), the requeued generation reclaims the
                    # orphaned bytes on a later round.
                    pending[key] = max(pending.get(key, 0), gen)
                if result["kept_fresh"]:
                    logger.info(
                        "reclaim on volume %s kept %d key(s) with fresh "
                        "bytes (%s); re-verifying next round",
                        volume_id,
                        len(result["kept_fresh"]),
                        result["kept_fresh"][:3],
                    )
                await self._reconcile_clobbered(volume_id, result["removed"])
                _RECLAIMED.inc(len(result["removed"]))
                _PENDING_RECLAIMS.set(len(pending), volume=volume_id)
                logger.info(
                    "reclaimed %d stale key(s) on detached volume %s",
                    len(result["removed"]),
                    volume_id,
                )
                if not pending:
                    return
            left = self._pending_reclaims.get(volume_id) or ()
            if left:
                logger.warning(
                    "gave up reclaiming %d stale key(s) on volume %s "
                    "(unreachable)",
                    len(left),
                    volume_id,
                )
        finally:
            self._reclaim_running.discard(volume_id)
            self._pending_reclaims.pop(volume_id, None)
            _PENDING_RECLAIMS.set(0, volume=volume_id)

    async def _reconcile_clobbered(
        self, volume_id: str, removed_keys: list[str]
    ) -> None:
        """A reclaim delete whose key the index NOW claims this volume
        holds means a racing put's bytes were destroyed before its notify
        indexed them (the conditional delete narrows this to the
        gen-read/delete window of two-phase unknown-generation reclaims).
        Detach the entry so readers fail over / fail loudly instead of
        routing to missing bytes; the next publish restores redundancy."""
        clobbered = []
        for key in removed_keys:
            infos = self.index.get(key)
            if infos is not None and volume_id in infos:
                infos.pop(volume_id, None)
                if not infos:
                    self.index.pop(key, None)
                clobbered.append(key)
        if clobbered:
            logger.warning(
                "reclaim raced a fresh put on volume %s: detached %d "
                "re-indexed key(s) it deleted (%s); redundancy degraded "
                "until the next publish",
                volume_id,
                len(clobbered),
                clobbered[:3],
            )
            await self.bump(set(clobbered))

    def detach_meta(self, meta: Request, volume_id: str) -> None:
        """Remove ONE meta's footprint on ``volume_id``: the exact shard
        coords for sharded keys (sibling shards on the volume survive), the
        whole entry for tensors/objects. A key with no volumes left
        disappears; a sharded key missing coords reads as partial (loud)."""
        infos = self.index.get(meta.key)
        if infos is None or volume_id not in infos:
            return
        info = infos[volume_id]
        if (
            meta.tensor_slice is not None
            and info.object_type == ObjectType.TENSOR_SLICE
        ):
            info.tensor_slices.pop(meta.tensor_slice.coordinates, None)
            if info.tensor_slices:
                return
        del infos[volume_id]
        if not infos:
            self.index.pop(meta.key, None)

    # ---- coordinator-engine services -------------------------------------
    # The same surface RemoteIndex fans out over shards: relay forwarding,
    # auto-repair, volume replacement, durable rebuild, tier sweeps, and
    # the observability rollups all reach the index ONLY through these.

    async def get_entry(self, key: str) -> Optional[dict[str, StorageInfo]]:
        return self.index.get(key)

    async def merge_copies(
        self,
        volume_id: str,
        metas: list[Request],
        write_gens: dict[str, int],
    ) -> set:
        """Index freshly pulled copies of ``metas`` on ``volume_id`` (relay
        forwarding / targeted re-replication). Keys deleted mid-pull are
        never re-indexed. New replica placement is structural, same rule as
        apply_put_batch; the bump wakes relay-gated long-pollers."""
        touched = set()
        for meta in metas:
            infos = self.index.get(meta.key)
            if infos is None:
                continue  # deleted mid-run: never re-index
            info = infos.get(volume_id)
            if info is None:
                info = infos[volume_id] = StorageInfo.from_meta(meta)
            else:
                info.merge(meta)
            info.write_gen = max(info.write_gen, write_gens.get(meta.key, 0))
            touched.add(meta.key)
        if touched:
            await self.host.on_structural()
            await self.bump(touched)
        return touched

    async def auto_repair_pass(
        self, volume_id: str, healthy: list[str]
    ) -> int:
        """Re-replicate every key the quarantined volume held that still
        has a healthy copy onto healthy volumes (volume-to-volume over the
        RPC transport — no client involvement), restoring redundancy
        without ts.repair(). Keys whose only copy lived on the quarantined
        volume are skipped (nothing to copy from; ts.repair()/recover
        remains the story for those). Raced overwrites are detected by
        write-generation snapshot and the extra copy is reclaimed instead
        of indexed, so a repaired replica can never serve stale bytes
        under fresh metadata."""
        import asyncio

        if not healthy:
            return 0
        # Plan: (src, tgt) -> list of (key, meta-only Requests, src_gen).
        plan: dict[tuple[str, str], list] = {}
        rr = 0
        for key in list(self.index):
            infos = self.index.get(key)
            if infos is None or volume_id not in infos:
                continue
            lost = infos[volume_id]
            sources = [v for v in healthy if v in infos]
            src = None
            for cand in sources:
                have = infos[cand]
                if lost.object_type != have.object_type:
                    continue
                if lost.object_type == ObjectType.TENSOR_SLICE and not (
                    set(lost.tensor_slices) <= set(have.tensor_slices)
                ):
                    continue  # survivor lacks some of the lost shards
                src = cand
                break
            if src is None:
                continue
            targets = [v for v in healthy if v not in infos]
            if not targets:
                continue  # every healthy volume already holds a copy
            tgt = sorted(targets)[rr % len(targets)]
            rr += 1
            if lost.object_type == ObjectType.OBJECT:
                metas = [Request(key=key, is_object=True)]
            elif lost.object_type == ObjectType.TENSOR:
                metas = [Request(key=key, tensor_meta=lost.tensor_meta)]
            else:
                metas = [
                    Request(
                        key=key,
                        tensor_slice=ts,
                        tensor_meta=lost.tensor_meta,
                    )
                    for ts in lost.tensor_slices.values()
                ]
            plan.setdefault((src, tgt), []).append(
                (key, metas, self.index[key][src].write_gen)
            )
        if not plan:
            return 0
        repaired = 0
        for (src, tgt), items in plan.items():
            src_ref = self.host.volume_refs.get(src)
            tgt_ref = self.host.volume_refs.get(tgt)
            if src_ref is None or tgt_ref is None:
                continue
            # Bounded batches: one pull RPC moves up to 64 keys.
            for i in range(0, len(items), 64):
                batch = items[i : i + 64]
                metas = [m for _, ms, _ in batch for m in ms]
                try:
                    result = await tgt_ref.pull_from.call_one(
                        src_ref,
                        metas,
                        src_hostname=self.host.volume_hostnames.get(src, ""),
                        src_volume=src,
                    )
                except Exception as exc:  # noqa: BLE001 - per-batch
                    logger.warning(
                        "auto-repair pull %s -> %s failed for %d "
                        "key(s): %s",
                        src, tgt, len(batch), exc,
                    )
                    continue
                gens = result.get("write_gens", {})
                touched = set()
                for key, kmetas, src_gen in batch:
                    infos = self.index.get(key)
                    cur = infos.get(src) if infos else None
                    if cur is None or cur.write_gen != src_gen:
                        # The key was overwritten/deleted while the
                        # copy was in flight: the pulled bytes may be
                        # stale — reclaim them on the target instead
                        # of indexing (gen -1: resolve target-side).
                        self.schedule_reclaim(tgt, {key: -1})
                        continue
                    info = infos.get(tgt)
                    for m in kmetas:
                        if info is None:
                            info = infos[tgt] = StorageInfo.from_meta(m)
                        else:
                            info.merge(m)
                    info.write_gen = max(
                        info.write_gen, gens.get(key, 0)
                    )
                    touched.add(key)
                    repaired += 1
                if touched:
                    _AUTO_REPAIRS.inc(len(touched))
                    await self.host.on_structural()
                    await self.bump(touched)
                await asyncio.sleep(0)  # yield between batches
        return repaired

    @staticmethod
    def _info_metas(key: str, info: StorageInfo) -> list[Request]:
        """Meta-only Requests reconstructing one replica's footprint —
        the same idiom auto-repair plans with."""
        if info.object_type == ObjectType.OBJECT:
            return [Request(key=key, is_object=True)]
        if info.object_type == ObjectType.TENSOR:
            return [Request(key=key, tensor_meta=info.tensor_meta)]
        return [
            Request(key=key, tensor_slice=ts, tensor_meta=info.tensor_meta)
            for ts in info.tensor_slices.values()
        ]

    @staticmethod
    def _info_nbytes(info: StorageInfo) -> int:
        if info.object_type == ObjectType.TENSOR_SLICE:
            itemsize = (
                info.tensor_meta.np_dtype.itemsize
                if info.tensor_meta is not None
                else 4
            )
            return sum(
                ts.nelements * itemsize for ts in info.tensor_slices.values()
            )
        if info.tensor_meta is not None:
            return int(info.tensor_meta.nbytes)
        return 0

    async def migrate_key(
        self, key: str, src: str, dst: str, drop_src: bool = True
    ) -> dict[str, Any]:
        """Online replica move/add for the control engine: pull ``key``'s
        committed copy from ``src`` onto ``dst`` volume-to-volume, index
        the new copy, and (``drop_src``) detach + conditionally reclaim
        the source replica — readers keep serving throughout (the copy is
        a landing like any put; the detach is structural and bumps).

        Raced overwrites are detected by write-generation snapshot, same
        rule as auto-repair: the pulled bytes are reclaimed on ``dst``
        instead of indexed, and the source replica is left untouched —
        the engine's decision audit reports the race as abandoned.

        Returns ``{"status": "ok"|"missing"|"present"|"raced",
        "nbytes": int}``."""
        infos = self.index.get(key)
        if infos is None or src not in infos:
            return {"status": "missing", "nbytes": 0}
        if dst in infos:
            return {"status": "present", "nbytes": 0}
        lost = infos[src]
        metas = self._info_metas(key, lost)
        src_gen = lost.write_gen
        src_ref = self.host.volume_refs.get(src)
        dst_ref = self.host.volume_refs.get(dst)
        if src_ref is None or dst_ref is None:
            return {"status": "missing", "nbytes": 0}
        result = await dst_ref.pull_from.call_one(
            src_ref,
            metas,
            src_hostname=self.host.volume_hostnames.get(src, ""),
            src_volume=src,
        )
        infos = self.index.get(key)
        cur = infos.get(src) if infos else None
        if cur is None or cur.write_gen != src_gen:
            # Overwritten/deleted while the copy was in flight: the pulled
            # bytes may be stale — reclaim on the target, keep the source.
            self.schedule_reclaim(dst, {key: -1})
            return {"status": "raced", "nbytes": 0}
        gens = result.get("write_gens", {})
        info = infos.get(dst)
        for m in metas:
            if info is None:
                info = infos[dst] = StorageInfo.from_meta(m)
            else:
                info.merge(m)
        info.write_gen = max(info.write_gen, gens.get(key, 0))
        if drop_src and len(infos) > 1:
            infos.pop(src, None)
            self.schedule_reclaim(src, {key: src_gen})
        await self.host.on_structural()
        await self.bump({key})
        return {"status": "ok", "nbytes": self._info_nbytes(info)}

    def export_entries(self) -> list[tuple[str, Request, int]]:
        """Every (volume_id, meta-only Request, write_gen) this core's
        index holds — the exact ``reindex`` input shape, so a metadata
        reshard can freeze, export, and replay the whole slice onto a new
        shard mesh with zero lost keys. Tier states are NOT exported:
        after a reshard, demoted keys read as resident until the next
        sweep re-folds them (cost: one fault-in-shaped fallback, never
        correctness)."""
        out: list[tuple[str, Request, int]] = []
        for key, infos in self.index.items():
            for vid, info in infos.items():
                for meta in self._info_metas(key, info):
                    out.append((vid, meta, info.write_gen))
        return out

    async def detach_volume(self, volume_id: str) -> dict[str, Any]:
        """Drop every index entry on ``volume_id`` (volume replacement).
        Returns what it held so the repairer can re-replicate: see
        Controller.replace_volume. The caller owns the structural report
        (it also swaps the actor ref in the same step)."""
        recoverable: dict[str, Any] = {}
        lost: list[str] = []
        changed = set()
        for key in list(self.index):
            infos = self.index[key]
            info = infos.pop(volume_id, None)
            if info is None:
                continue
            changed.add(key)
            if infos:
                recoverable[key] = (
                    list(info.tensor_slices.values())
                    if info.object_type == ObjectType.TENSOR_SLICE
                    else None
                )
            else:
                lost.append(key)
                self.index.pop(key, None)
        if changed:
            await self.bump(changed)
        return {"recoverable": recoverable, "lost": lost}

    async def set_tiers(
        self,
        volume_id: str,
        spilled: list[str],
        fault_ins: list[str],
    ) -> None:
        """Fold one volume's reported spill/fault-in transitions into the
        index's tier states. Metadata only — NOT structural: cached plans
        keep serving the resident hot set."""
        for key in spilled:
            infos = self.index.get(key)
            if infos is not None and volume_id in infos:
                infos[volume_id].tier = tiering.TIERED
        for key in fault_ins:
            infos = self.index.get(key)
            if infos is not None and volume_id in infos:
                infos[volume_id].tier = tiering.RESIDENT

    async def reindex(
        self, survivors: list[tuple[str, Request, int]]
    ) -> int:
        """Rebuild this core's slice of the index from resolved volume
        manifests (durable recovery). Seeds every recovered key's update
        generation at a RANDOM epoch offset — a surviving subscriber holds
        a pre-restart gen, and wait_for_change wakes on gen != last_gen,
        so seeding at small integers could collide with exactly the gen it
        last saw and block it through recovered versions."""
        count = 0
        for vid, meta, gen in survivors:
            infos = self.index.get(meta.key)
            if infos is None:
                infos = {}
                self.index[meta.key] = infos
            info = infos.get(vid)
            if info is None:
                info = infos[vid] = StorageInfo.from_meta(meta)
            else:
                info.merge(meta)
            # Live volumes report their in-memory write generation; keep it
            # so conditional reclaims stay sound across controller
            # restarts (a gen-0 entry could never be reclaimed).
            info.write_gen = max(info.write_gen, gen)
            count += 1
        import secrets

        offset = secrets.randbits(46) | (1 << 45)
        cond = self.cond()
        async with cond:
            for key in self.index:
                self._key_gens[key] = offset
            cond.notify_all()
        self.mark_meta_dirty()
        return count

    async def summary(self) -> dict:
        """The index half of ``stats()``: op counters + index rollup.
        Merged across shards by RemoteIndex.summary()."""
        indexed_bytes = 0
        sharded_keys = 0
        for infos in self.index.values():
            key_is_sharded = False
            for info in infos.values():
                if info.object_type == ObjectType.TENSOR_SLICE:
                    key_is_sharded = True
                    itemsize = (
                        info.tensor_meta.np_dtype.itemsize
                        if info.tensor_meta is not None
                        else 4
                    )
                    indexed_bytes += sum(
                        ts.nelements * itemsize
                        for ts in info.tensor_slices.values()
                    )
                elif info.tensor_meta is not None:
                    indexed_bytes += info.tensor_meta.nbytes
            sharded_keys += int(key_is_sharded)
        return {
            **self.counters,
            "num_keys": len(self.index),
            "sharded_keys": sharded_keys,
            "indexed_bytes_approx": indexed_bytes,
            "pending_reclaims": {
                vid: len(keys)
                for vid, keys in self._pending_reclaims.items()
                if keys
            },
        }

    async def catalog(self, channel: Optional[str] = None) -> dict:
        """This core's slice of the per-channel version inventory (see
        Controller.version_catalog — leases are coordinator state and are
        folded in there). ``volumes`` are sets here; the coordinator
        normalizes after the cross-shard merge."""
        out: dict[str, dict[int, dict]] = {}
        for key in self.index:
            group = tiering.version_group(key)
            if group is None:
                continue
            chan, ver = group
            if channel is not None and chan != channel:
                continue
            infos = self.index.get(key)
            if not infos:
                continue
            rec = out.setdefault(chan, {}).setdefault(
                ver,
                {
                    "keys": 0,
                    "bytes": 0,
                    "resident_keys": 0,
                    "spilled_keys": 0,
                    "volumes": set(),
                    "leases": [],
                },
            )
            rec["keys"] += 1
            info = next(iter(infos.values()))
            if info.object_type == ObjectType.TENSOR_SLICE:
                itemsize = (
                    info.tensor_meta.np_dtype.itemsize
                    if info.tensor_meta is not None
                    else 4
                )
                rec["bytes"] += sum(
                    ts.nelements * itemsize
                    for ts in info.tensor_slices.values()
                )
            elif info.tensor_meta is not None:
                rec["bytes"] += info.tensor_meta.nbytes
            if any(i.tier != tiering.TIERED for i in infos.values()):
                rec["resident_keys"] += 1
            else:
                rec["spilled_keys"] += 1
            rec["volumes"].update(infos)
        return out

    # ---- stamped metadata publication ------------------------------------

    def meta_payload(self) -> dict:
        """The one-sided view of this core's COMMITTED index: what a
        same-host client needs to resolve locations with zero RPCs —
        exactly what ``locate`` would answer (committed keys only,
        quarantined replicas filtered under the same coverage rule).
        Staleness is safe by construction: a missing key falls back to the
        RPC locate, and a deleted key's stale entry fails at the volume
        and retries through a fresh RPC locate — the same ladder a warm
        client-side location cache already rides."""
        quarantined = self.host.quarantined_ids()
        out: dict[str, dict[str, StorageInfo]] = {}
        for key in self.index:
            infos = self.index.get(key)
            if not infos or self.committed_state(infos) == "partial":
                continue
            out[key] = self._serving_infos(infos, quarantined)
        return out

    def teardown(self) -> None:
        for task in list(self._reclaim_tasks):
            task.cancel()
        self._reclaim_tasks.clear()
        self._reclaim_running.clear()
        self._pending_reclaims.clear()
        self._key_gens.clear()
        self.index = Trie()
        if self.meta_writer is not None:
            self.meta_writer.mark_dirty()
