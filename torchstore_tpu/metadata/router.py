"""Client-side metadata router: shard fan-out + one-sided stamped reads.

``MetadataRouter`` wraps the coordinator's ``ActorRef`` and presents the
SAME endpoint-attribute surface (``router.locate_volumes.call_one(...)``),
so every existing controller call site routes through it unchanged:

- **Coordinator-scoped ops** (streams, leases, relay, health, epoch,
  prewarm, stats, ...) pass straight through to the coordinator.
- **Index-scoped ops** (``locate_volumes``/``notify_put_batch``/
  ``notify_delete_batch``/``keys``/``contains`` and the blocking waits)
  partition by stable key hash across the controller shards and merge the
  replies. Stream watermarks are recorded on the coordinator strictly
  AFTER every owning shard indexed its slice of the batch, and deletes
  run the coordinator's lease guard first — cross-shard invariants always
  route through the coordinator.
- **Every controller RPC is counted** into the traffic ledger's metadata
  cells (per op, per shard) so ``ts.traffic_matrix()["metadata"]`` makes
  "zero metadata RPCs on the warm path" a measured assertion.

The router also owns the client ends of the stamped metadata segments
(metadata/stamped.py): same-host warm locates, placement-epoch
confirmation, and streamed-publish polling serve from shared memory with
zero controller RPCs, falling back loudly to the RPC path on torn/stale
reads.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Optional

from torchstore_tpu.logging import get_logger
from torchstore_tpu.metadata import INDEX_OPS, shard_of
from torchstore_tpu.metadata import stamped as stamped_mod
from torchstore_tpu.metadata.shards import (
    is_stale_topology,
    partition_keys,
    partition_metas,
    slice_write_gens,
)
from torchstore_tpu.observability import ledger as obs_ledger
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.runtime import ActorRef

logger = get_logger("torchstore_tpu.metadata.router")

_META_RPCS = obs_metrics.counter(
    "ts_meta_rpcs_total",
    "Controller metadata RPCs issued by this client, by op",
)
# Overload signal (ts.slo_report): metadata RPCs this client has issued and
# not yet heard back, per shard ("coord"/"s<i>") — the client-observed
# proxy for each controller actor's service-queue depth. LONG_POLL_OPS are
# excluded: a parked wait occupies a connection, not service capacity.
_META_INFLIGHT = obs_metrics.gauge(
    "ts_meta_rpc_inflight",
    "Metadata RPCs awaiting a reply from this client, by shard",
)

COORD = "coord"

# Ops that PARK on the controller by design (notify-woken long-polls).
# They occupy a connection, not service capacity — counting them as
# inflight would read N idle subscribers as sustained controller backlog
# and trip admission control on a quiet fleet.
LONG_POLL_OPS = frozenset(
    {"wait_for_stream", "wait_for_change", "wait_for_committed"}
)


def _count_rpc(op: str, shard: str = COORD) -> None:
    _META_RPCS.inc(op=op)
    ledger = obs_ledger.ledger()
    if ledger.enabled:
        ledger.record(obs_ledger.METADATA, "rpc", 0, peer_host=op, volume=shard)


def count_stamped(op: str, shard: str = COORD) -> None:
    stamped_mod.STAMPED_READS.inc(op=op)
    ledger = obs_ledger.ledger()
    if ledger.enabled:
        ledger.record(
            obs_ledger.METADATA, "stamped", 0, peer_host=op, volume=shard
        )


class _RoutedOp:
    """One endpoint handle off the router — the ``ActorEndpointRef``
    surface (``call_one``/``call``/``with_timeout``) over routed dispatch."""

    __slots__ = ("_router", "_op", "_timeout")

    def __init__(self, router: "MetadataRouter", op: str, timeout=None):
        self._router = router
        self._op = op
        self._timeout = timeout

    def with_timeout(self, timeout) -> "_RoutedOp":
        return _RoutedOp(self._router, self._op, timeout)

    async def call_one(self, *args, **kwargs) -> Any:
        return await self._router._dispatch(
            self._op, self._timeout, args, kwargs
        )

    async def call(self, *args, **kwargs) -> Any:
        return await self.call_one(*args, **kwargs)


class MetadataRouter:
    """See module docstring. Construct over the coordinator ref; call
    ``load_topology()`` once per volume-map (re)load to discover shards
    and attach same-host stamped segments."""

    def __init__(self, coordinator: ActorRef) -> None:
        self._coordinator = coordinator
        self.shard_refs: list[ActorRef] = []
        self.n_shards = 1
        self._rpc_timeout: Optional[float] = None
        # shard label -> RPCs awaiting replies (single event loop: plain
        # int bookkeeping; mirrored into ts_meta_rpc_inflight).
        self._inflight: dict[str, int] = {}
        # Stamped same-host attachments (None until load_topology finds a
        # co-located publisher): per-index-host readers + the coordinator's
        # stream/epoch segment.
        self._index_readers: list[Optional[stamped_mod.MetaStampReader]] = []
        self._stream_reader: Optional[stamped_mod.MetaStampReader] = None
        # Cross-host: when the stamped publishers live on ANOTHER host,
        # the readers above attach this host's MetadataMirror replica
        # instead (metadata/mirror.py); every stamped read first checks
        # mirror.fresh() and falls back loudly (reason="mirror_lag") when
        # the feed went quiet past its lag bound.
        self._mirror = None

    # -- ActorRef compatibility -------------------------------------------

    @property
    def coordinator(self) -> ActorRef:
        return self._coordinator

    # ActorRef introspection passthroughs (tests/tools read the
    # coordinator's address off the client's controller handle).
    @property
    def host(self) -> str:
        return self._coordinator.host

    @property
    def port(self) -> int:
        return self._coordinator.port

    @property
    def name(self) -> str:
        return self._coordinator.name

    @property
    def rpc_timeout(self) -> Optional[float]:
        return self._rpc_timeout

    @rpc_timeout.setter
    def rpc_timeout(self, value) -> None:
        self._rpc_timeout = value
        self._coordinator.rpc_timeout = value
        for ref in self.shard_refs:
            ref.rpc_timeout = value

    def __getattr__(self, op: str) -> _RoutedOp:
        if op.startswith("_"):
            raise AttributeError(op)
        return _RoutedOp(self, op)

    async def ping(self) -> bool:
        return await self._coordinator.ping()

    # -- topology ----------------------------------------------------------

    async def load_topology(self, meta_stamped: bool = True) -> None:
        """Fetch the metadata-plane topology from the coordinator: shard
        refs for fan-out routing, and stamped-segment descriptors for the
        one-sided path (attached only when the publisher is on THIS
        host). Safe to call repeatedly (volume-map refreshes)."""
        topo = await self._coordinator.metadata_topology.call_one()
        self.shard_refs = list(topo.get("shards") or [])
        self.n_shards = max(1, len(self.shard_refs))
        if self._rpc_timeout is not None:
            for ref in self.shard_refs:
                ref.rpc_timeout = self._rpc_timeout
        for reader in self._index_readers:
            if reader is not None:
                reader.close()
        self._index_readers = []
        if self._stream_reader is not None:
            self._stream_reader.close()
        self._stream_reader = None
        self._mirror = None
        if not (meta_stamped and stamped_mod.enabled()):
            return
        from torchstore_tpu.utils import get_hostname

        local = get_hostname()

        def _attach(desc) -> Optional[stamped_mod.MetaStampReader]:
            if not desc or desc.get("hostname") != local:
                return None
            # Raw segment attachment stays inside stamped/mirror (tslint
            # rule mirror-discipline): the accessor absorbs gone/cross-
            # mount publishers — RPC serves.
            return stamped_mod.attach_reader(desc)

        st = topo.get("stamped") or {}
        self._stream_reader = _attach(st.get("coordinator"))
        self._index_readers = [_attach(d) for d in st.get("index") or []]
        feed = topo.get("meta_feed")
        published = bool(
            st.get("coordinator") or any(st.get("index") or [])
        )
        if (
            feed
            and published
            and stamped_mod.mirror_enabled()
            and self._stream_reader is None
            and not any(self._index_readers)
        ):
            # The publishers are all REMOTE: subscribe this host's mirror
            # and attach its local replica segments through the same path.
            from torchstore_tpu.metadata import mirror as mirror_mod

            mirror = await mirror_mod.ensure_mirror(self._coordinator, feed)
            if mirror is not None:
                self._mirror = mirror
                md = mirror.descriptors()
                self._stream_reader = stamped_mod.attach_reader(
                    md.get("coordinator")
                )
                self._index_readers = [
                    stamped_mod.attach_reader(d)
                    for d in md.get("index") or []
                ]

    def _mirror_stale(self) -> bool:
        """True when stamped reads are mirror-backed and the mirror fell
        past its lag bound — every stamped entrypoint then falls back
        LOUDLY to RPC until the re-subscription catches the replica up."""
        if self._mirror is None:
            return False
        if self._mirror.fresh():
            return False
        stamped_mod.STAMPED_FALLBACKS.inc(reason="mirror_lag")
        return True

    def _index_reader(
        self, key: str
    ) -> Optional[stamped_mod.MetaStampReader]:
        if not self._index_readers:
            return None
        idx = shard_of(key, len(self._index_readers))
        return self._index_readers[idx]

    # -- dispatch ----------------------------------------------------------

    async def _tracked(self, shard: str, coro):
        """Await ``coro`` with the per-shard inflight gauge held up — the
        queue-depth overload signal ``ts.slo_report()`` reads."""
        n = self._inflight.get(shard, 0) + 1
        self._inflight[shard] = n
        _META_INFLIGHT.set(n, shard=shard)
        try:
            return await coro
        finally:
            n = max(0, self._inflight.get(shard, 1) - 1)
            self._inflight[shard] = n
            _META_INFLIGHT.set(n, shard=shard)

    def inflight_snapshot(self) -> dict[str, int]:
        """Current metadata RPCs awaiting replies, per shard label."""
        return {k: v for k, v in self._inflight.items() if v}

    def _coord_ep(self, op: str, timeout):
        ep = getattr(self._coordinator, op)
        if timeout is not None:
            ep = ep.with_timeout(timeout)
        return ep

    def _shard_ep(self, idx: int, op: str, timeout):
        ep = getattr(self.shard_refs[idx], op)
        if timeout is not None:
            ep = ep.with_timeout(timeout)
        return ep

    async def _dispatch(self, op: str, timeout, args, kwargs) -> Any:
        # An op that races a runtime reshard (ts.rebalance(shards=N)) hits a
        # retired shard (STALE_TOPOLOGY_MSG) or a coordinator that went
        # sharded under us: reload the topology from the coordinator and
        # retry ONCE against the new mesh. Safe to replay: both raises fire
        # at endpoint entry, strictly before any index mutation. kwargs is
        # copied per attempt because the sharded paths pop() from it.
        try:
            return await self._dispatch_once(op, timeout, args, dict(kwargs))
        except RuntimeError as exc:
            if not is_stale_topology(exc):
                raise
            logger.info(
                "metadata op %s hit a resharded topology (%s); reloading "
                "and retrying once",
                op,
                exc,
            )
            await self.load_topology()
            return await self._dispatch_once(op, timeout, args, dict(kwargs))

    async def _dispatch_once(self, op: str, timeout, args, kwargs) -> Any:
        if self.shard_refs and op in INDEX_OPS:
            return await self._dispatch_sharded(op, timeout, args, kwargs)
        _count_rpc(op)
        call = self._coord_ep(op, timeout).call_one(*args, **kwargs)
        if op in LONG_POLL_OPS:
            return await call
        return await self._tracked(COORD, call)

    async def _dispatch_sharded(self, op: str, timeout, args, kwargs) -> Any:
        if op == "locate_volumes":
            keys = args[0] if args else kwargs.pop("keys")
            parts = partition_keys(keys, self.n_shards)
            calls = []
            for i, ks in parts.items():
                _count_rpc(op, f"s{i}")
                calls.append(
                    self._tracked(
                        f"s{i}",
                        self._shard_ep(i, "locate_volumes", timeout).call_one(
                            ks, *args[1:], **kwargs
                        ),
                    )
                )
            merged: dict = {}
            for part in await asyncio.gather(*calls):
                merged.update(part)
            return merged
        if op == "contains":
            key = args[0] if args else kwargs["key"]
            i = shard_of(key, self.n_shards)
            _count_rpc(op, f"s{i}")
            return await self._tracked(
                f"s{i}",
                self._shard_ep(i, "contains", timeout).call_one(
                    *args, **kwargs
                ),
            )
        if op == "keys":
            calls = []
            for i in range(self.n_shards):
                _count_rpc(op, f"s{i}")
                calls.append(
                    self._tracked(
                        f"s{i}",
                        self._shard_ep(i, "keys", timeout).call_one(
                            *args, **kwargs
                        ),
                    )
                )
            results = await asyncio.gather(*calls)
            return sorted(k for part in results for k in part)
        if op == "wait_for_committed":
            keys = args[0] if args else kwargs.pop("keys")
            rest = args[1:]
            parts = partition_keys(keys, self.n_shards)
            calls = []
            for i, ks in parts.items():
                _count_rpc(op, f"s{i}")
                # Long-poll: parked, not queued — never inflight-tracked.
                calls.append(
                    self._shard_ep(i, "wait_for_committed", timeout).call_one(
                        ks, *rest, **kwargs
                    )
                )
            await asyncio.gather(*calls)
            return None
        if op == "wait_for_change":
            key = args[0] if args else kwargs["key"]
            i = shard_of(key, self.n_shards)
            _count_rpc(op, f"s{i}")
            # Long-poll: parked, not queued — never inflight-tracked.
            return await self._shard_ep(i, "wait_for_change", timeout).call_one(
                *args, **kwargs
            )
        if op == "notify_put_batch":
            return await self._notify_sharded(timeout, *args, **kwargs)
        if op == "notify_delete_batch":
            return await self._delete_sharded(timeout, *args, **kwargs)
        raise RuntimeError(f"unrouted sharded metadata op {op!r}")

    async def _notify_sharded(
        self,
        timeout,
        metas,
        volume_id,
        detach_volume_ids=None,
        write_gens=None,
        supersede: bool = False,
        watermark=None,
        unchanged=None,
    ) -> Optional[int]:
        """Sharded notify: each owning shard indexes its slice (and runs
        the detach/supersede/reclaim machinery for it); the stream
        watermark is recorded on the coordinator ONLY after every shard
        acked — same bytes-committed-before-watermark-visible ordering as
        the single-actor step, with the indexing now parallel."""
        if unchanged and watermark is None:
            raise ValueError(
                "notify_put_batch(unchanged=...) requires watermark=: "
                "unchanged-key aliases are a streamed-publish protocol"
            )
        parts = partition_metas(metas, self.n_shards)
        calls = []
        for i, ms in parts.items():
            _count_rpc("notify_put_batch", f"s{i}")
            calls.append(
                self._tracked(
                    f"s{i}",
                    self._shard_ep(i, "notify_put_batch", timeout).call_one(
                        ms,
                        volume_id,
                        detach_volume_ids=detach_volume_ids,
                        write_gens=slice_write_gens(
                            write_gens, {m.key for m in ms}
                        ),
                        supersede=supersede,
                    ),
                )
            )
        epochs = [e for e in await asyncio.gather(*calls) if e is not None]
        if watermark is not None:
            stream_key, version = watermark
            volume_ids = (
                [volume_id] if isinstance(volume_id, str) else list(volume_id)
            )
            _count_rpc("stream_watermark")
            await self._tracked(
                COORD,
                self._coord_ep("stream_watermark", timeout).call_one(
                    stream_key,
                    int(version),
                    metas,
                    volume_ids,
                    unchanged,
                ),
            )
        return max(epochs) if epochs else None

    async def _delete_sharded(self, timeout, keys) -> dict[str, list[str]]:
        """Sharded delete: coordinator lease guard FIRST (the never-reaped-
        mid-read guarantee is fleet-scoped), then each owning shard drops
        its slice, then the coordinator retires stream records for what
        actually disappeared."""
        _count_rpc("delete_guard")
        passed = await self._tracked(
            COORD, self._coord_ep("delete_guard", timeout).call_one(keys)
        )
        parts = partition_keys(passed, self.n_shards)
        calls = []
        for i, ks in parts.items():
            _count_rpc("notify_delete_batch", f"s{i}")
            calls.append(
                self._tracked(
                    f"s{i}",
                    self._shard_ep(i, "delete_keys", timeout).call_one(ks),
                )
            )
        merged: dict[str, list[str]] = {}
        for part in await asyncio.gather(*calls):
            for vid, vkeys in part.items():
                merged.setdefault(vid, []).extend(vkeys)
        deleted = sorted({k for vkeys in merged.values() for k in vkeys})
        if deleted:
            _count_rpc("delete_finish")
            await self._tracked(
                COORD,
                self._coord_ep("delete_finish", timeout).call_one(deleted),
            )
        return merged

    # -- one-sided stamped reads ------------------------------------------

    def stamped_locate(
        self, keys: list[str]
    ) -> Optional[dict[str, dict]]:
        """Resolve committed locations for ``keys`` from the stamped index
        segments — zero RPCs. Returns {key: infos} for the subset found
        (missing keys fall back to the RPC locate), or None when no
        stamped index is attached. Staleness rides the exact ladder the
        warm location cache already does: a deleted key's lingering entry
        fails at the volume and the fetch retries with a fresh RPC locate."""
        if not self._index_readers or not any(self._index_readers):
            return None
        if self._mirror_stale():
            return None
        out: dict[str, dict] = {}
        payloads: dict[int, Any] = {}
        n = len(self._index_readers)
        for key in keys:
            idx = shard_of(key, n)
            reader = self._index_readers[idx]
            if reader is None:
                continue
            if idx not in payloads:
                try:
                    _, payload, _ = reader.read()
                except stamped_mod.MetaUnavailable as exc:
                    stamped_mod.STAMPED_FALLBACKS.inc(reason=exc.reason)
                    if exc.reason in ("tombstone", "gone"):
                        self._index_readers[idx] = None
                    payloads[idx] = None
                    continue
                payloads[idx] = payload
            payload = payloads[idx]
            if payload is None:
                continue
            infos = payload.get(key)
            if infos is not None:
                out[key] = infos
                count_stamped(
                    "locate_volumes", f"s{idx}" if self.shard_refs else COORD
                )
        return out or None

    def stamped_epoch(self) -> Optional[int]:
        """The placement epoch from the coordinator's stamped header —
        the zero-RPC half of warm plan validation. None when unattached
        or torn (the caller pays the RPC)."""
        if self._stream_reader is None:
            return None
        if self._mirror_stale():
            return None
        try:
            return self._stream_reader.epoch()
        except stamped_mod.MetaUnavailable as exc:
            stamped_mod.STAMPED_FALLBACKS.inc(reason=exc.reason)
            if exc.reason in ("tombstone", "gone"):
                self._stream_reader = None
            return None

    def stamped_write_gens(
        self, keys: list[str], volume_id: str
    ) -> Optional[dict[str, int]]:
        """Committed write generations of ``keys``' replicas on
        ``volume_id`` from the stamped (possibly mirrored) index — the
        validation primitive for push-on-publish staging: a pushed layer
        serves only once the committed index shows its generation on the
        target volume. Returns None when any key/replica is unresolvable
        or the segment is unattached/stale — the caller falls back to the
        doorbell ring (never a silent serve of unvalidated bytes)."""
        if not self._index_readers or not any(self._index_readers):
            return None
        if self._mirror_stale():
            return None
        out: dict[str, int] = {}
        payloads: dict[int, Any] = {}
        n = len(self._index_readers)
        for key in keys:
            idx = shard_of(key, n)
            reader = self._index_readers[idx]
            if reader is None:
                return None
            if idx not in payloads:
                try:
                    _, payload, _ = reader.read()
                except stamped_mod.MetaUnavailable as exc:
                    stamped_mod.STAMPED_FALLBACKS.inc(reason=exc.reason)
                    if exc.reason in ("tombstone", "gone"):
                        self._index_readers[idx] = None
                    return None
                payloads[idx] = payload
            infos = payloads[idx].get(key)
            info = infos.get(volume_id) if infos else None
            if info is None:
                return None
            out[key] = int(getattr(info, "write_gen", 0) or 0)
        count_stamped("write_gens")
        return out

    async def stamped_wait_stream(
        self,
        key: str,
        version: int,
        known: int = 0,
        timeout: Optional[float] = None,
        volume_id: Optional[str] = None,
    ) -> Optional[dict]:
        """One-sided ``wait_for_stream``: poll the coordinator's stamped
        stream snapshot until progress (same view shape and timeout
        semantics as the RPC long-poll). Returns None when no stamped
        segment is attached — the caller long-polls over RPC. Staleness is
        one-directional (the snapshot can only lag), so ``superseded``/
        ``ready`` are never reported spuriously; a record the caller KNOWS
        exists but the snapshot hasn't caught up with is polled through a
        short grace window before reporting missing."""
        reader = self._stream_reader
        if reader is None:
            return None
        if self._mirror_stale():
            return None
        version = int(version)
        deadline = None if timeout is None else time.monotonic() + timeout
        # Missing-record grace: the caller usually confirmed the record
        # exists via stream_state (RPC) — a missing entry here is almost
        # always publish lag, worth a few intervals before giving up. The
        # writer's debounce is ADAPTIVE (duty-cycle capped), so lag can
        # exceed any fixed window: past the grace, report missing ONLY
        # when the snapshot demonstrably refreshed since entry (its
        # publish generation moved) and STILL lacks the record; a snapshot
        # that never refreshed may simply be stale — stand down to the
        # RPC long-poll for the authoritative answer instead of burning a
        # restart attempt on a healthy stream.
        grace = time.monotonic() + max(
            0.05, 4 * stamped_mod.publish_interval_s()
        )
        entry_gen = reader.generation()
        sleep_s = 0.001
        served_once = False
        while True:
            # Re-checked EVERY poll: a mirror parent dying mid-stream must
            # flip this long-poll to the RPC path at the lag bound, not at
            # the next acquire (the chaos-leg guarantee — a quiet replica
            # can only under-see, and past the bound we stop trusting it).
            if self._mirror_stale():
                return None
            try:
                gen, payload, _ = reader.read()
            except stamped_mod.MetaUnavailable as exc:
                stamped_mod.STAMPED_FALLBACKS.inc(reason=exc.reason)
                if exc.reason in ("tombstone", "gone"):
                    if self._stream_reader is reader:
                        self._stream_reader = None
                return None
            rec = (payload.get("streams") or {}).get(key)
            if rec is None:
                if known < 0 or time.monotonic() < grace:
                    pass  # keep polling: awaited record / publish lag
                elif entry_gen is None or gen == entry_gen:
                    stamped_mod.STAMPED_FALLBACKS.inc(reason="stale_snapshot")
                    return None  # possibly stale: the RPC owns the verdict
                else:
                    count_stamped("wait_for_stream")
                    return {
                        "missing": True,
                        "version": 0,
                        "sealed": False,
                        "superseded": False,
                        "ready": [],
                        "watermarks": {},
                        "aliases": {},
                        "quant": None,
                    }
            else:
                if known < 0:
                    served_once = True
                view = self._stream_view(rec, version, volume_id)
                if (
                    served_once
                    or len(view["ready"]) > known
                    or view["sealed"]
                    or view["superseded"]
                ):
                    count_stamped("wait_for_stream")
                    return view
            if deadline is not None and time.monotonic() >= deadline:
                count_stamped("wait_for_stream")
                raise TimeoutError(
                    f"wait_for_stream({key!r}, v{version}) timed out after "
                    f"{timeout}s with {known} key(s) already served"
                )
            await asyncio.sleep(sleep_s)
            sleep_s = min(0.02, sleep_s * 1.6)

    @staticmethod
    def _stream_view(
        rec: dict, version: int, volume_id: Optional[str] = None
    ) -> dict:
        marks = rec.get("watermarks") or {}
        ready = {k: v for k, v in marks.items() if v >= version}
        sealed = rec["sealed"] >= version
        # Relay gate, the EXACT wait_for_stream formula over the published
        # gate picture: a gate-eligible volume only sees a forwarded key
        # once its relay copy landed (so the acquire reads it locally
        # instead of pulling cross-host from the origin). A volume absent
        # from the snapshot's landed table polls ungated — the controller
        # already applied the membership/quarantine fail-safe when it
        # published the view.
        relay = rec.get("relay")
        if (
            volume_id is not None
            and relay is not None
            and volume_id in relay["landed"]
        ):
            forwarded = set(relay["forwarded"])
            landed = set(relay["landed"][volume_id])
            local = {
                k: v
                for k, v in ready.items()
                if k not in forwarded or k in landed
            }
            sealed = sealed and len(local) == len(ready)
            ready = local
        rec_aliases = rec.get("aliases") or {}
        return {
            "missing": False,
            "version": rec["version"],
            "sealed": sealed,
            "superseded": rec["version"] > version,
            "ready": sorted(ready),
            "watermarks": ready,
            "aliases": {k: rec_aliases[k] for k in ready if k in rec_aliases},
            "quant": rec.get("quant"),
        }
