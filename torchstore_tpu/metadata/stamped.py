"""One-sided stamped metadata segments: PR 7's seqlock idiom, for METADATA.

Each index host (the classic controller, or every ControllerShard)
publishes its COMMITTED index into a shared-memory segment bracketed by a
writer seqlock; the coordinator publishes stream watermark/seal state and
the placement epoch the same way. Same-host clients then resolve
locations, validate cached plans, and poll streamed-publish progress by
READING SHARED MEMORY — zero controller RPCs on the warm path, which is
what removes client count from every controller queue (ROADMAP item 4,
"RPC Considered Harmful").

Layout (all little-endian uint64, 8-byte aligned):

    [0] seq     seqlock word: odd = publish in flight, even = stable
    [1] gen     monotonically increasing publish generation
    [2] len     payload byte length; TOMBSTONE marks a retired segment
    [3] epoch   the writer's placement epoch at publish time
    [4..]       pickled payload

Reader protocol: read seq (must be even), snapshot gen/len/epoch, copy the
payload, re-read seq — any movement is a torn read and falls back LOUDLY
to the RPC path (``ts_meta_stamped_fallbacks_total``). Generations only
increase, so a reader caches the decoded payload per generation and a
header-only re-read (32 bytes) answers "anything new?" — the poll a
streamed acquire runs per layer costs a few loads, not an RPC.

Staleness is one-directional by construction: the writer publishes AFTER
the index/stream change commits, so a reader can only UNDER-see progress
(it falls back or keeps polling), never observe a watermark before its
bytes landed. Deleted keys may linger one debounce interval — exactly the
client-side location-cache staleness the fetch ladder already retries
through.
"""

from __future__ import annotations

import asyncio
import os
import pickle
import time
from typing import Any, Callable, Optional

import numpy as np

from torchstore_tpu.logging import get_logger
from torchstore_tpu.observability import metrics as obs_metrics

logger = get_logger("torchstore_tpu.metadata.stamped")

HEADER_BYTES = 32
# len-word sentinel: the writer retired this segment (payload outgrew it,
# or the host shut down). Readers treat it as a permanent miss for this
# attachment and stand down to the RPC path.
TOMBSTONE = (1 << 63) - 1

ENV_META_STAMPED = "TORCHSTORE_TPU_META_STAMPED"
ENV_META_PUBLISH_MS = "TORCHSTORE_TPU_META_PUBLISH_MS"
ENV_META_SEGMENT_BYTES = "TORCHSTORE_TPU_META_SEGMENT_BYTES"
# Cross-host metadata mirror (metadata/mirror.py): remote clients subscribe
# to the index host's feed and republish received wire images into LOCAL
# shm, so the one-sided warm paths work across the host boundary too.
ENV_META_MIRROR = "TORCHSTORE_TPU_META_MIRROR"
ENV_META_MIRROR_INTERVAL_MS = "TORCHSTORE_TPU_META_MIRROR_INTERVAL_MS"
ENV_META_MIRROR_HEARTBEAT_S = "TORCHSTORE_TPU_META_MIRROR_HEARTBEAT_S"
ENV_META_MIRROR_LAG_S = "TORCHSTORE_TPU_META_MIRROR_LAG_S"

STAMPED_READS = obs_metrics.counter(
    "ts_meta_stamped_total",
    "Warm-path metadata reads served from stamped segments (zero RPCs), "
    "by op",
)
STAMPED_FALLBACKS = obs_metrics.counter(
    "ts_meta_stamped_fallbacks_total",
    "Stamped metadata reads that fell back to the RPC path, by reason",
)
_PUBLISHES = obs_metrics.counter(
    "ts_meta_publishes_total",
    "Stamped metadata segment publishes (debounced; one per dirty window)",
)
_PUBLISH_BYTES = obs_metrics.gauge(
    "ts_meta_publish_bytes",
    "Payload bytes of the newest stamped metadata publish",
)


def enabled() -> bool:
    return os.environ.get(ENV_META_STAMPED, "1").strip().lower() not in (
        "0", "false", "no", "off", "",
    )


def publish_interval_s() -> float:
    try:
        return max(0.001, float(os.environ.get(ENV_META_PUBLISH_MS, "10")) / 1e3)
    except ValueError:
        return 0.01


def segment_bytes() -> int:
    try:
        return max(64 << 10, int(os.environ.get(ENV_META_SEGMENT_BYTES, 8 << 20)))
    except ValueError:
        return 8 << 20


def mirror_enabled() -> bool:
    return os.environ.get(ENV_META_MIRROR, "1").strip().lower() not in (
        "0", "false", "no", "off", "",
    )


def mirror_interval_s() -> float:
    try:
        return max(
            0.001,
            float(os.environ.get(ENV_META_MIRROR_INTERVAL_MS, "20")) / 1e3,
        )
    except ValueError:
        return 0.02


def mirror_heartbeat_s() -> float:
    try:
        return max(
            0.02, float(os.environ.get(ENV_META_MIRROR_HEARTBEAT_S, "0.2"))
        )
    except ValueError:
        return 0.2


def mirror_lag_s() -> float:
    """Staleness bound on a mirror replica: reads older than this fall back
    to the RPC path with ``reason="mirror_lag"`` (loud, never silent)."""
    try:
        return max(
            0.1, float(os.environ.get(ENV_META_MIRROR_LAG_S, "1.5"))
        )
    except ValueError:
        return 1.5


class MetaUnavailable(Exception):
    """This attachment can no longer serve (tombstoned / unmapped /
    persistent tears): the caller stands down to the RPC path."""

    def __init__(self, reason: str) -> None:
        super().__init__(reason)
        self.reason = reason


class MetaStampWriter:
    """Debounced seqlock publisher for one metadata view.

    ``payload_fn`` builds the current view (must run on the host's event
    loop — index state is single-writer there); ``epoch_fn`` supplies the
    placement epoch stamped into the header. ``mark_dirty()`` is cheap and
    idempotent: publishes coalesce to at most one per interval."""

    def __init__(
        self,
        payload_fn: Callable[[], Any],
        epoch_fn: Optional[Callable[[], int]] = None,
        size: Optional[int] = None,
        interval_s: Optional[float] = None,
    ) -> None:
        from torchstore_tpu.transport.shared_memory import ShmSegment

        self.payload_fn = payload_fn
        self.epoch_fn = epoch_fn or (lambda: 0)
        self.size = size or segment_bytes()
        self.interval_s = (
            publish_interval_s() if interval_s is None else interval_s
        )
        # count=False: protocol metadata, not pool economics (same rule as
        # the data plane's stamp tables).
        self.seg = ShmSegment.create(self.size, count=False)
        self.words = np.frombuffer(
            self.seg.mmap, dtype=np.uint64, count=4
        )
        self._gen = 0
        self._dirty = False
        self._scheduled = False
        self._last_pub = 0.0
        # Adaptive debounce: building + pickling the view runs ON the
        # host's event loop, so the effective interval grows with the
        # measured publish cost to cap the duty cycle at ~DUTY_CYCLE of
        # loop time (a huge index publishes less often; a small stream
        # snapshot keeps the configured cadence). Staleness stays safe —
        # readers only ever UNDER-see progress and fall back to RPCs.
        self._effective_interval = self.interval_s
        self._dead = False

    DUTY_CYCLE = 0.05

    def describe(self) -> dict:
        from torchstore_tpu.utils import get_hostname

        return {
            "segment": self.seg.name,
            "size": self.size,
            "hostname": get_hostname(),
        }

    def mark_dirty(self) -> None:
        if self._dead:
            return
        self._dirty = True
        if self._scheduled:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            # No loop (direct unit-test construction): publish inline.
            self.publish_now()
            return
        self._scheduled = True
        delay = max(
            0.0, self._last_pub + self._effective_interval - time.monotonic()
        )
        loop.call_later(delay, self._scheduled_publish)

    def _scheduled_publish(self) -> None:
        self._scheduled = False
        if self._dirty:
            self.publish_now()

    def publish_now(self) -> None:
        """One seqlock-bracketed publish of the current payload. Payloads
        that outgrow the segment tombstone it permanently (readers fall
        back to RPC; loud log once) — growing in place would orphan every
        attached reader silently."""
        if self._dead:
            return
        self._dirty = False
        t0 = time.monotonic()
        self._last_pub = t0
        try:
            blob = pickle.dumps(self.payload_fn(), protocol=4)
        except Exception:  # noqa: BLE001 - a publish must never kill the
            # host endpoint that marked it dirty; RPC path still serves
            logger.exception("stamped metadata publish failed; RPC serves")
            return
        if len(blob) > self.size - HEADER_BYTES:
            logger.warning(
                "stamped metadata payload (%d bytes) outgrew its segment "
                "(%d); tombstoning — same-host readers fall back to RPCs "
                "(raise TORCHSTORE_TPU_META_SEGMENT_BYTES to restore "
                "one-sided metadata at this scale)",
                len(blob),
                self.size,
            )
            self._tombstone()
            return
        seq = self._publish_open()
        try:
            self._gen += 1
            self.seg.mmap[HEADER_BYTES : HEADER_BYTES + len(blob)] = blob
            self.words[1] = self._gen
            self.words[2] = len(blob)
            self.words[3] = int(self.epoch_fn())
        except BaseException:
            # A raise mid-bracket (epoch_fn blowing up, a torn mmap after
            # the segment shrank underneath us) must not leave the seq
            # word odd forever — every reader would spin its torn-read
            # retries out on a bracket nobody will ever close. The header
            # is half-written and can't be trusted, so tombstone it (the
            # handler runs BEFORE the finally: the marker lands while the
            # bracket is still odd, never visible as a stable half-header)
            # and serve via RPC permanently.
            self.words[2] = TOMBSTONE
            self._dead = True
            raise
        finally:
            self._publish_close(seq)
        _PUBLISHES.inc()
        _PUBLISH_BYTES.set(len(blob))
        # Duty-cycle cap: the next publish waits at least cost/DUTY_CYCLE,
        # so view building can never consume more than ~5% of the loop.
        cost = time.monotonic() - t0
        self._effective_interval = max(
            self.interval_s, cost / self.DUTY_CYCLE
        )

    def _publish_open(self) -> int:
        """Open the seqlock bracket: seq word goes odd, readers retry.
        Returns the odd seq to hand back to :meth:`_publish_close`."""
        seq = int(self.words[0]) + 1
        self.words[0] = seq
        return seq

    def _publish_close(self, seq: int) -> None:
        """Close the bracket: seq settles even, the publish is stable."""
        self.words[0] = seq + 1

    def _tombstone(self) -> None:
        seq = self._publish_open()
        try:
            self.words[2] = TOMBSTONE
        finally:
            self._publish_close(seq)
        self._dead = True

    def close(self) -> None:
        if not self._dead:
            self._tombstone()
        self.seg.unlink()


class MetaStampReader:
    """Same-host attachment to one writer's segment, with per-generation
    decode caching: a header-only read answers "unchanged?", a changed
    generation pays one payload copy + unpickle."""

    MAX_TORN_RETRIES = 16

    def __init__(self, name: str, size: int) -> None:
        from torchstore_tpu.transport.shared_memory import ShmSegment

        self.seg = ShmSegment.attach(name, size)
        self.words = np.frombuffer(self.seg.mmap, dtype=np.uint64, count=4)
        self._cached_gen: Optional[int] = None
        self._cached: Any = None
        self._dead = False

    def read(self) -> tuple[int, Any, int]:
        """(generation, payload, epoch) of the newest stable publish.
        Raises MetaUnavailable on tombstones / never-published segments /
        persistent tears — the caller falls back to the RPC path."""
        if self._dead:
            raise MetaUnavailable("gone")
        words = self.words
        for _ in range(self.MAX_TORN_RETRIES):
            s1 = int(words[0])
            if s1 & 1:
                continue  # publish in flight: the writer is fast; spin
            gen = int(words[1])
            ln = int(words[2])
            epoch = int(words[3])
            if ln == TOMBSTONE:
                self._dead = True
                raise MetaUnavailable("tombstone")
            if gen == 0:
                raise MetaUnavailable("never_published")
            if gen == self._cached_gen and int(words[0]) == s1:
                return gen, self._cached, epoch
            blob = bytes(self.seg.mmap[HEADER_BYTES : HEADER_BYTES + ln])
            if int(words[0]) != s1:
                continue  # torn: a publish raced the copy
            try:
                obj = pickle.loads(blob)
            except Exception as exc:  # noqa: BLE001 - torn beyond the
                # seqlock's detection window (should not happen; be loud)
                raise MetaUnavailable(f"undecodable: {exc}") from exc
            self._cached_gen = gen
            self._cached = obj
            return gen, obj, epoch
        raise MetaUnavailable("torn")

    def epoch(self) -> int:
        """Header-only read of the stamped placement epoch (the zero-RPC
        plan-validation primitive). Raises MetaUnavailable like read()."""
        if self._dead:
            raise MetaUnavailable("gone")
        words = self.words
        for _ in range(self.MAX_TORN_RETRIES):
            s1 = int(words[0])
            if s1 & 1:
                continue
            gen = int(words[1])
            ln = int(words[2])
            epoch = int(words[3])
            if ln == TOMBSTONE:
                self._dead = True
                raise MetaUnavailable("tombstone")
            if gen == 0:
                raise MetaUnavailable("never_published")
            if int(words[0]) == s1:
                return epoch
        raise MetaUnavailable("torn")

    def read_image(self) -> tuple[int, int, bytes]:
        """Seqlock-consistent RAW snapshot ``(generation, epoch, payload
        bytes)`` of the newest stable publish — NO unpickle. This is the
        wire image the cross-host metadata feed ships: the mirror republishes
        the exact bytes under its own local seqlock, preserving gen/epoch, so
        a remote reader's decode path is byte-identical to a same-host one.
        Raises MetaUnavailable exactly like :meth:`read`."""
        if self._dead:
            raise MetaUnavailable("gone")
        words = self.words
        for _ in range(self.MAX_TORN_RETRIES):
            s1 = int(words[0])
            if s1 & 1:
                continue
            gen = int(words[1])
            ln = int(words[2])
            epoch = int(words[3])
            if ln == TOMBSTONE:
                self._dead = True
                raise MetaUnavailable("tombstone")
            if gen == 0:
                raise MetaUnavailable("never_published")
            blob = bytes(self.seg.mmap[HEADER_BYTES : HEADER_BYTES + ln])
            if int(words[0]) != s1:
                continue  # torn: a publish raced the copy
            return gen, epoch, blob
        raise MetaUnavailable("torn")

    def generation(self) -> Optional[int]:
        """Header-only publish generation (None while torn/unpublished) —
        the cheap "anything new?" probe the stream poll loop spins on."""
        if self._dead:
            return None
        try:
            words = self.words
            s1 = int(words[0])
            if s1 & 1:
                return None
            gen = int(words[1])
            if int(words[2]) == TOMBSTONE:
                self._dead = True
                return None
            return gen if int(words[0]) == s1 and gen else None
        except (ValueError, OSError):
            return None

    def close(self) -> None:
        """Detach: further reads raise MetaUnavailable("gone") and the
        cached decode + header view are dropped so the mapping's pages
        release as soon as the last borrower lets go (a long-lived client
        re-attaches on every topology reload — dropped readers must not
        pin retired 8MB segments until a lucky GC)."""
        self._dead = True
        self._cached = None
        self._cached_gen = None
        self.words = None


def attach_reader(desc: Optional[dict]) -> Optional[MetaStampReader]:
    """THE sanctioned way to attach a reader to a METADATA segment outside
    this module (tslint rule ``mirror-discipline``: raw ``MetaStampReader``
    construction is confined to ``stamped.py``/``mirror.py`` so every
    consumer inherits the same descriptor validation and the mirror's
    accessors stay the single remote-read surface). Returns None for an
    empty descriptor or an unmappable segment (publisher gone / cross-mount
    attach): the caller stands down to the RPC path."""
    if not desc or not desc.get("segment"):
        return None
    try:
        return MetaStampReader(desc["segment"], desc["size"])
    except (OSError, KeyError):
        return None


class ImageStampWriter:
    """Seqlock republisher of received WIRE IMAGES (metadata/mirror.py's
    local replica segments): writes the exact payload bytes the origin
    published, preserving its generation and epoch words, under a local
    seqlock bracket — readers attached to the mirror segment run the
    identical torn/stale ladder they run against the origin. Monotonicity
    is inherited: the feed delivers images in publish order per source, and
    ``publish_image`` drops regressions defensively."""

    def __init__(self, size: Optional[int] = None) -> None:
        from torchstore_tpu.transport.shared_memory import ShmSegment

        self.size = size or segment_bytes()
        self.seg = ShmSegment.create(self.size, count=False)
        self.words = np.frombuffer(self.seg.mmap, dtype=np.uint64, count=4)
        self._gen = 0
        self._dead = False

    def describe(self) -> dict:
        from torchstore_tpu.utils import get_hostname

        return {
            "segment": self.seg.name,
            "size": self.size,
            "hostname": get_hostname(),
        }

    def publish_image(self, gen: int, epoch: int, blob: bytes) -> bool:
        """One bracketed republish of a received image; returns False when
        the image was dropped (stale generation / outgrown segment)."""
        if self._dead:
            return False
        if gen <= self._gen:
            return False  # reordered/duplicate image: keep the newer view
        if len(blob) > self.size - HEADER_BYTES:
            # The origin's segment grew past ours (operator raised
            # TORCHSTORE_TPU_META_SEGMENT_BYTES mid-fleet): tombstone so
            # readers fall back loudly instead of serving a truncated view.
            self._tombstone()
            return False
        seq = int(self.words[0]) + 1
        self.words[0] = seq
        try:
            self.seg.mmap[HEADER_BYTES : HEADER_BYTES + len(blob)] = blob
            self.words[1] = gen
            self.words[2] = len(blob)
            self.words[3] = int(epoch)
            self._gen = gen
        except BaseException:
            self.words[2] = TOMBSTONE
            self._dead = True
            raise
        finally:
            self.words[0] = seq + 1
        return True

    def _tombstone(self) -> None:
        seq = int(self.words[0]) + 1
        self.words[0] = seq
        try:
            self.words[2] = TOMBSTONE
        finally:
            self.words[0] = seq + 1
        self._dead = True

    def close(self) -> None:
        if not self._dead:
            self._tombstone()
        self.seg.unlink()
