"""Cross-host metadata relay: stamped wire images pushed over DCN.

PR 14's stamped metadata plane stops at the host boundary — a segment in
/dev/shm is only attachable same-host, so DCN clients still pay a
controller RPC for every locate/plan-validate/stream-poll. This module
extends the one-sided tier across hosts:

- The index host runs a **MetaFeedServer**: a persistent bulk-style TCP
  feed that pushes every stamped segment's RAW wire image (the exact
  seqlock payload ``metadata/stamped.py`` publishes — index snapshot,
  stream watermarks, placement epoch) to its direct subscribers the
  moment the origin generation moves, plus liveness heartbeats.
- Subscribing hosts run a **MetadataMirror**: it republishes received
  images into LOCAL shm under a fresh seqlock (generation and epoch
  preserved — ``stamped.ImageStampWriter``), so every reader on that host
  resolves locations, confirms plan epochs, and polls streamed publishes
  against a LOCAL replica with zero controller round-trips. The mirror
  also re-serves the feed to child subscribers: the controller assigns
  parents over the PR 11 relay-tree shape (root out-degree
  ``relay.ROOT_FANOUT``), so the index host's metadata egress stays O(1)
  in subscriber count.
- Staleness stays LOUD and one-directional: a mirror whose feed went
  quiet past ``TORCHSTORE_TPU_META_MIRROR_LAG_S`` reports unfresh, and
  every stamped read on that host falls back to the RPC path with
  ``reason="mirror_lag"`` until the re-subscription (down-set re-parent
  through the controller) catches the replica up. A lagging mirror can
  only UNDER-see progress — never a watermark before its bytes.

tslint rule ``mirror-discipline``: remote code reads mirrored metadata
ONLY through this module's accessors (``attach_reader``); raw attachment
of METADATA segments outside ``stamped.py``/``mirror.py`` is forbidden.
"""

from __future__ import annotations

import asyncio
import pickle
import socket
import struct
import time
from typing import Any, Callable, Optional

from torchstore_tpu.logging import get_logger
from torchstore_tpu.metadata import stamped as stamped_mod
from torchstore_tpu.metadata.stamped import (  # noqa: F401 - re-exported
    attach_reader,
)
from torchstore_tpu.observability import ledger as obs_ledger
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.utils import get_hostname, spawn_logged

logger = get_logger("torchstore_tpu.metadata.mirror")

# Wire frame: kind u8, source u32, gen u64, epoch u64, len u64 + payload.
# Source identity is positional and stable per hello: 0 = coordinator
# (streams + placement epoch), 1+i = index segment i (shard i, or the
# unsharded core at i=0).
_MFRAME = struct.Struct("<BIQQQ")
KIND_HELLO = 0      # payload: pickled {"sources": [size_or_None, ...]}
KIND_IMAGE = 1      # payload: the raw stamped wire image
KIND_HEARTBEAT = 2  # no payload; liveness + lag bound

MIRROR_TRANSPORT = "meta_mirror"

_IMAGES = obs_metrics.counter(
    "ts_meta_mirror_images_total",
    "Stamped metadata wire images applied by this host's mirror, by source",
)
_IMAGE_BYTES = obs_metrics.counter(
    "ts_meta_mirror_bytes_total",
    "Payload bytes of stamped metadata images received by this mirror",
)
_RESUBSCRIBES = obs_metrics.counter(
    "ts_meta_mirror_resubscribes_total",
    "Mirror feed re-subscriptions (parent death / feed loss), by reason",
)
_FRESH = obs_metrics.gauge(
    "ts_meta_mirror_fresh",
    "1 while this host's metadata mirror is within its lag bound",
)
_SUBSCRIBERS = obs_metrics.gauge(
    "ts_meta_feed_subscribers",
    "Direct subscribers currently connected to this process's metadata feed",
)


async def _recv_exact(sock: socket.socket, view: memoryview) -> None:
    loop = asyncio.get_running_loop()
    pos = 0
    total = view.nbytes
    while pos < total:
        n = await loop.sock_recv_into(sock, view[pos:])
        if n == 0:
            raise ConnectionError("meta feed peer closed mid-frame")
        pos += n


def _close_sock(sock: Optional[socket.socket]) -> None:
    if sock is None:
        return
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class _Subscriber:
    """One connected feed subscriber: a bounded frame queue + sender task.
    A consumer that stops draining (wedged child) overflows the queue and
    is DROPPED — it re-subscribes through the controller rather than
    back-pressuring the pump into stalling every other subscriber."""

    QUEUE_MAX = 256

    def __init__(self, server: "MetaFeedServer", sock: socket.socket) -> None:
        self.server = server
        self.sock = sock
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=self.QUEUE_MAX)
        self.task: Optional[asyncio.Task] = None

    def offer(self, frame: bytes) -> None:
        try:
            self.queue.put_nowait(frame)
        except asyncio.QueueFull:
            logger.warning(
                "meta feed subscriber wedged (queue full); dropping it"
            )
            _close_sock(self.sock)

    async def run(self) -> None:
        loop = asyncio.get_running_loop()
        try:
            while True:
                frame = await self.queue.get()
                await loop.sock_sendall(self.sock, frame)
        except (ConnectionError, OSError):
            pass
        finally:
            self.server._drop_subscriber(self)
            _close_sock(self.sock)


class MetaFeedServer:
    """Persistent metadata-image feed (root AND mirror re-serve roles).

    Holds the latest wire image per source plus the source-size table; on
    subscriber connect it replays hello + every current image, then pushes
    updates/heartbeats as :meth:`update_image`/:meth:`heartbeat` land. The
    ROOT's pump (``run_pump``) fills it by polling the local stamped
    segments; a MIRROR fills it by forwarding frames from its parent."""

    def __init__(
        self,
        sources_fn: Optional[Callable[[], list]] = None,
    ) -> None:
        self._sources_fn = sources_fn
        self._listen_sock: Optional[socket.socket] = None
        self._accept_task: Optional[asyncio.Task] = None
        self._pump_task: Optional[asyncio.Task] = None
        self._tasks: set = set()
        self.host: str = "127.0.0.1"
        self.port: Optional[int] = None
        self.sizes: list = []
        self.latest: dict[int, tuple[int, int, bytes]] = {}
        self._subs: list[_Subscriber] = []
        # Root-pump attachments: source idx -> (segment name, reader).
        self._readers: dict[int, tuple[str, Any]] = {}

    # ---- lifecycle -------------------------------------------------------

    async def ensure_started(self, bind_host: Optional[str] = None) -> tuple:
        if self._listen_sock is None:
            import os

            bind_host = bind_host or os.environ.get(
                "TORCHSTORE_TPU_BIND_HOST", "127.0.0.1"
            )
            family = (
                socket.AF_INET6 if ":" in bind_host else socket.AF_INET
            )
            sock = socket.socket(family, socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((bind_host, 0))
            sock.listen(32)
            sock.setblocking(False)
            self._listen_sock = sock
            self.port = sock.getsockname()[1]
            advertise = os.environ.get("TORCHSTORE_TPU_ADVERTISE_HOST")
            if advertise is None:
                advertise = (
                    socket.gethostname()
                    if bind_host in ("0.0.0.0", "::")
                    else bind_host
                )
            self.host = advertise
            self._accept_task = asyncio.ensure_future(self._accept_loop())
            if self._sources_fn is not None:
                self._pump_task = asyncio.ensure_future(self.run_pump())
            logger.info(
                "meta feed bound %s:%s (advertised as %s)",
                bind_host,
                self.port,
                self.host,
            )
        return self.host, self.port

    async def _accept_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            try:
                conn, _ = await loop.sock_accept(self._listen_sock)
            except asyncio.CancelledError:
                raise
            except OSError as exc:
                if self._listen_sock is None or self._listen_sock.fileno() < 0:
                    return
                logger.warning("meta feed accept failed (%s); retrying", exc)
                # Same forever-accept contract as the bulk listener: dying
                # here would strand every future subscriber.
                await asyncio.sleep(1.0)  # tslint: disable=retry-discipline
                continue
            conn.setblocking(False)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            spawn_logged(
                self._adopt(conn),
                name="meta_feed.adopt",
                tasks=self._tasks,
                log=logger,
            )

    async def _adopt(self, sock: socket.socket) -> None:
        from torchstore_tpu.runtime.auth import server_authenticate_sock

        if not await server_authenticate_sock(sock):
            _close_sock(sock)
            return
        sub = _Subscriber(self, sock)
        # Snapshot replay BEFORE joining the broadcast list: hello + every
        # current image enqueue first, so the subscriber's view is ordered
        # (snapshot, then updates) without a pump lock.
        sub.offer(self._hello_frame())
        for source in sorted(self.latest):
            gen, epoch, blob = self.latest[source]
            sub.offer(_MFRAME.pack(KIND_IMAGE, source, gen, epoch, len(blob)) + blob)
        self._subs.append(sub)
        _SUBSCRIBERS.set(len(self._subs))
        sub.task = asyncio.ensure_future(sub.run())
        self._tasks.add(sub.task)
        sub.task.add_done_callback(self._tasks.discard)

    def _drop_subscriber(self, sub: _Subscriber) -> None:
        if sub in self._subs:
            self._subs.remove(sub)
            _SUBSCRIBERS.set(len(self._subs))

    def _hello_frame(self) -> bytes:
        payload = pickle.dumps({"sources": list(self.sizes)}, protocol=4)
        return _MFRAME.pack(KIND_HELLO, 0, 0, 0, len(payload)) + payload

    def _broadcast(self, frame: bytes) -> None:
        for sub in list(self._subs):
            sub.offer(frame)

    # ---- feed input (pump or parent-forward) -----------------------------

    def set_sizes(self, sizes: list) -> None:
        """Adopt a new source table (reshard / first hello) and re-hello
        every subscriber; stale per-source images beyond the new table are
        dropped."""
        if sizes == self.sizes:
            return
        self.sizes = list(sizes)
        self.latest = {
            s: img for s, img in self.latest.items() if s < len(sizes)
        }
        self._broadcast(self._hello_frame())

    def update_image(
        self, source: int, gen: int, epoch: int, blob: bytes
    ) -> None:
        prev = self.latest.get(source)
        if prev is not None and prev[0] >= gen:
            return
        self.latest[source] = (gen, epoch, blob)
        self._broadcast(
            _MFRAME.pack(KIND_IMAGE, source, gen, epoch, len(blob)) + blob
        )

    def heartbeat(self) -> None:
        self._broadcast(_MFRAME.pack(KIND_HEARTBEAT, 0, 0, 0, 0))

    # ---- root pump -------------------------------------------------------

    async def run_pump(self) -> None:
        """Poll the local stamped segments (header-only when unchanged) and
        push changed wire images + heartbeats to direct subscribers. Runs
        in the index host's process; cancellation is shutdown."""
        interval = stamped_mod.mirror_interval_s()
        heartbeat_s = stamped_mod.mirror_heartbeat_s()
        last_beat = 0.0
        while True:
            try:
                self._pump_once()
            except Exception:  # noqa: BLE001 - the feed is advisory; a bad
                # tick must never kill the host serving RPCs
                logger.exception("meta feed pump tick failed")
            now = time.monotonic()
            if now - last_beat >= heartbeat_s:
                self.heartbeat()
                last_beat = now
            await asyncio.sleep(interval)

    def _pump_once(self) -> None:
        descs = list(self._sources_fn() or [])
        sizes = [d.get("size") if d else None for d in descs]
        # (Re)attach readers on segment change; detach removed sources.
        for idx, desc in enumerate(descs):
            name = desc.get("segment") if desc else None
            cur = self._readers.get(idx)
            if cur is not None and cur[0] != name:
                cur[1].close()
                self._readers.pop(idx, None)
                cur = None
            if cur is None and desc:
                reader = stamped_mod.attach_reader(desc)
                if reader is not None:
                    self._readers[idx] = (name, reader)
        for idx in [i for i in self._readers if i >= len(descs)]:
            self._readers.pop(idx)[1].close()
        self.set_sizes(sizes)
        for idx, (_, reader) in list(self._readers.items()):
            gen = reader.generation()
            if gen is None:
                continue
            prev = self.latest.get(idx)
            if prev is not None and prev[0] >= gen:
                continue
            try:
                gen, epoch, blob = reader.read_image()
            except stamped_mod.MetaUnavailable:
                continue  # torn/tombstoned this tick: next tick re-checks
            self.update_image(idx, gen, epoch, blob)

    def close(self) -> None:
        for task in (self._accept_task, self._pump_task):
            if task is not None:
                task.cancel()
        for task in list(self._tasks):
            task.cancel()
        self._tasks.clear()
        for sub in list(self._subs):
            _close_sock(sub.sock)
        self._subs.clear()
        _SUBSCRIBERS.set(0)
        for _, reader in self._readers.values():
            reader.close()
        self._readers.clear()
        _close_sock(self._listen_sock)
        self._listen_sock = None
        self.port = None


class MetadataMirror:
    """This host's local replica of the fleet's stamped metadata plane.

    Subscribes through the controller (``meta_subscribe`` assigns a relay
    parent: the root feed or another host's mirror), republishes received
    wire images into local shm segments, re-serves the feed to child
    subscribers, and answers :meth:`fresh` for the router's mirror_lag
    ladder. One instance per (process, feed root); see :func:`ensure_mirror`.
    """

    def __init__(self, coordinator: Any, root: tuple[str, int]) -> None:
        self._coordinator = coordinator
        self._root = root
        self._server = MetaFeedServer()  # child re-serve; fed by _receiver
        self._writers: list[Optional[stamped_mod.ImageStampWriter]] = []
        self._sizes: list = []
        self._last_rx = 0.0
        self._ready = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._tasks: set = set()
        self._parent_host = ""
        self._parent_hostname = ""
        self._closed = False

    # ---- public surface (the sanctioned remote-read accessors) -----------

    def fresh(self) -> bool:
        """True while the mirrored replica is within its lag bound — the
        gate every stamped read on this host checks before serving from
        the mirror (stale -> loud ``mirror_lag`` fallback to RPC)."""
        ok = (
            self._ready.is_set()
            and time.monotonic() - self._last_rx
            <= stamped_mod.mirror_lag_s()
        )
        _FRESH.set(1 if ok else 0)
        return ok

    def descriptors(self) -> dict:
        """Stamped-segment descriptors of the LOCAL replica, topology-
        shaped exactly like ``metadata_topology()["stamped"]`` so the
        router attaches through the identical path."""
        descs = [
            w.describe() if w is not None else None for w in self._writers
        ]
        return {
            "coordinator": descs[0] if descs else None,
            "index": descs[1:],
        }

    async def wait_ready(self, timeout: float) -> bool:
        try:
            await asyncio.wait_for(self._ready.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    # ---- lifecycle -------------------------------------------------------

    async def start(self) -> None:
        await self._server.ensure_started()
        self._task = asyncio.ensure_future(self._receiver())

    async def _subscribe(self, down: Optional[list] = None) -> tuple[str, int]:
        res = await self._coordinator.meta_subscribe.call_one(
            get_hostname(),
            self._server.host,
            self._server.port,
            down=down or [],
        )
        self._parent_hostname = res.get("parent_hostname", "")
        return res["host"], res["port"]

    async def _receiver(self) -> None:
        """The subscription loop: connect to the assigned parent, apply
        frames, and on loss/lag re-subscribe AROUND the dead parent (the
        controller re-parents using the down set). Runs until close();
        while disconnected the mirror simply reports unfresh and the RPC
        path serves — so the loop retries forever, paced by the unified
        backoff curve."""
        from torchstore_tpu.config import RetryPolicy

        policy = RetryPolicy.from_env()
        streak = 0
        down: list = []
        while not self._closed:
            sock = None
            try:
                host, port = await self._subscribe(down)
                sock = await self._connect(host, port)
                streak = 0
                down = []
                await self._consume(sock)
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 - feed loss heals by
                # re-parenting; meanwhile RPC serves loudly
                if self._closed:
                    return
                reason = (
                    "lag" if isinstance(exc, asyncio.TimeoutError) else "conn"
                )
                _RESUBSCRIBES.inc(reason=reason)
                _FRESH.set(0)
                if self._parent_hostname:
                    down = [self._parent_hostname]
                logger.info(
                    "meta mirror feed lost (%s: %s); re-subscribing around "
                    "parent %r",
                    reason,
                    exc,
                    self._parent_hostname,
                )
                # Forever-retry by design (see docstring): the policy
                # supplies pacing only, never a deadline.
                await asyncio.sleep(  # tslint: disable=retry-discipline
                    policy.backoff(streak)
                )
                streak += 1
            finally:
                _close_sock(sock)

    async def _connect(self, host: str, port: int) -> socket.socket:
        from torchstore_tpu.runtime.auth import client_authenticate_sock

        loop = asyncio.get_running_loop()
        infos = await loop.getaddrinfo(host, port, type=socket.SOCK_STREAM)
        family, _, _, _, sockaddr = infos[0]
        sock = socket.socket(family, socket.SOCK_STREAM)
        sock.setblocking(False)
        try:
            await asyncio.wait_for(loop.sock_connect(sock, sockaddr), 5.0)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            await client_authenticate_sock(sock)
        except BaseException:
            _close_sock(sock)
            raise
        self._parent_host = host
        return sock

    async def _consume(self, sock: socket.socket) -> None:
        header = bytearray(_MFRAME.size)
        hview = memoryview(header)
        lag = stamped_mod.mirror_lag_s()
        while not self._closed:
            # The parent heartbeats well inside the lag bound: a frame gap
            # past it IS the parent-death signal (the chaos leg's trigger).
            await asyncio.wait_for(_recv_exact(sock, hview), timeout=lag)
            kind, source, gen, epoch, nbytes = _MFRAME.unpack(header)
            blob = b""
            if nbytes:
                buf = bytearray(nbytes)
                await asyncio.wait_for(
                    _recv_exact(sock, memoryview(buf)), timeout=lag
                )
                blob = bytes(buf)
            self._last_rx = time.monotonic()
            if kind == KIND_HEARTBEAT:
                self._server.heartbeat()
                continue
            if kind == KIND_HELLO:
                cfg = pickle.loads(blob)
                self._adopt_sizes(cfg.get("sources") or [])
                # Ready on hello: the image replay follows immediately in
                # the same snapshot burst, and a reader that races it just
                # sees never_published -> loud RPC fallback.
                self._ready.set()
                continue
            if kind != KIND_IMAGE or source >= len(self._writers):
                continue
            writer = self._writers[source]
            if writer is None:
                continue
            if writer.publish_image(gen, epoch, blob):
                _IMAGES.inc(source=str(source))
                _IMAGE_BYTES.inc(len(blob))
                # Mirror/push cells are REAL host->host edges: the receiver
                # knows both endpoints, so this single ingress cell carries
                # the attributable edge (the sender records nothing peer-
                # aware — count-once, same rule as the data plane).
                obs_ledger.record(
                    MIRROR_TRANSPORT,
                    obs_ledger.INGRESS,
                    len(blob),
                    peer_host=self._parent_hostname or self._parent_host,
                    volume="meta",
                )
                self._server.update_image(source, gen, epoch, blob)
            _FRESH.set(1)

    def _adopt_sizes(self, sizes: list) -> None:
        """(Re)build the local replica segments for a new source table. A
        reshaped table (reshard) tombstones the old segments — attached
        readers fall back loudly and the next topology reload re-attaches."""
        if sizes == self._sizes and self._writers:
            return
        for writer in self._writers:
            if writer is not None:
                writer.close()
        self._writers = [
            stamped_mod.ImageStampWriter(size) if size else None
            for size in sizes
        ]
        self._sizes = list(sizes)
        self._server.set_sizes(sizes)
        self._ready.clear()

    def close(self) -> None:
        self._closed = True
        if self._task is not None:
            self._task.cancel()
        for task in list(self._tasks):
            task.cancel()
        self._server.close()
        for writer in self._writers:
            if writer is not None:
                writer.close()
        self._writers = []
        self._ready.clear()
        _FRESH.set(0)


# Per-process mirror registry, keyed by the root feed endpoint: every store
# handle in a process pointing at the same fleet shares ONE subscription
# (and one local replica) regardless of how many clients re-load topology.
_MIRRORS: dict[tuple, MetadataMirror] = {}


async def ensure_mirror(
    coordinator: Any, feed: dict, timeout: float = 2.0
) -> Optional[MetadataMirror]:
    """Subscribe this process to the fleet's metadata feed (idempotent) and
    return the mirror once its first full snapshot landed. Returns None
    when the snapshot does not arrive within ``timeout`` — the caller
    stays on the RPC path and the subscription keeps warming in the
    background for the next topology load."""
    key = (feed.get("host"), feed.get("port"))
    if key[0] is None or key[1] is None:
        return None
    mirror = _MIRRORS.get(key)
    if mirror is None or mirror._closed:
        mirror = MetadataMirror(coordinator, key)
        _MIRRORS[key] = mirror
        await mirror.start()
    if await mirror.wait_ready(timeout):
        return mirror
    return None


def close_mirrors() -> None:
    """Tear down every mirror in this process (tests / store shutdown)."""
    for mirror in list(_MIRRORS.values()):
        mirror.close()
    _MIRRORS.clear()
