"""Composable arrival processes for the fleet-scale load harness.

Real serving fleets (TensorHub, PAPERS.md) never see uniform load: they
see Poisson steady-state with bursts riding on diurnal swings, readers of
wildly different speeds, and membership churn. Each pattern here is a
time-varying rate function ``rate_at(t)`` plus an inter-arrival sampler —
everything is driven off a caller-owned ``random.Random`` so a (seed,
pattern) pair replays the exact same schedule in every driver process.

Patterns (``make_pattern`` accepts the name or a ``{"kind": ...}`` dict
overriding the defaults):

    steady    fixed gaps at ``rate_hz`` (a metronome, the control case)
    poisson   exponential gaps at ``rate_hz`` (memoryless steady state)
    burst     square wave: ``rate_hz`` baseline, ``peak_rate_hz`` during
              the first ``burst_frac`` of every ``period_s`` window
    diurnal   sinusoid between ``rate_hz`` and ``peak_rate_hz`` over
              ``period_s`` (a day, time-compressed to the run length)
    skewed    the placement-bench profile: Poisson gaps at ``rate_hz``,
              but the harness reading this kind ALSO draws shared-key
              gets Zipf-weighted (``zipf_alpha``; see
              :func:`zipf_weights` — a few keys take most of the reads)
              and gives ONE tenant cohort a burst schedule
              (``peak_rate_hz``/``period_s``/``burst_frac``) while the
              rest stay at baseline — the skewed-traffic shape the
              control plane's hot-key splits and admission control are
              measured against

Churn (:func:`churn_sessions`) turns one logical client into alternating
live/offline sessions: live spans are exponential around
``1 / churn_rate_hz``, offline gaps a quarter of that — so at any instant
~80% of clients are up, and joins/leaves land all through the run instead
of at its edges.
"""

from __future__ import annotations

import math
import random
from typing import Union

PATTERNS = ("steady", "poisson", "burst", "diurnal", "skewed")

# A pattern's instantaneous rate never falls below this (a zero-rate
# trough would make next_gap infinite and wedge the client loop).
_MIN_RATE_HZ = 0.01


class ArrivalPattern:
    """One arrival process: ``rate_at(t)`` in ops/s and ``next_gap(t,
    rng)`` in seconds. ``t`` is seconds since the run's start."""

    def __init__(
        self,
        kind: str = "poisson",
        rate_hz: float = 20.0,
        peak_rate_hz: float = 0.0,
        period_s: float = 1.0,
        burst_frac: float = 0.25,
        zipf_alpha: float = 1.1,
    ) -> None:
        if kind not in PATTERNS:
            raise ValueError(
                f"unknown arrival pattern {kind!r}; choose from {PATTERNS}"
            )
        self.kind = kind
        self.rate_hz = max(_MIN_RATE_HZ, float(rate_hz))
        self.peak_rate_hz = max(float(peak_rate_hz), self.rate_hz)
        self.period_s = max(1e-3, float(period_s))
        self.burst_frac = min(1.0, max(0.0, float(burst_frac)))
        self.zipf_alpha = max(0.0, float(zipf_alpha))

    def rate_at(self, t: float) -> float:
        if self.kind in ("steady", "poisson", "skewed"):
            return self.rate_hz
        phase = (t % self.period_s) / self.period_s
        if self.kind == "burst":
            return (
                self.peak_rate_hz
                if phase < self.burst_frac
                else self.rate_hz
            )
        # diurnal: sinusoid between base and peak, trough at t=3/4 period.
        mid = (self.rate_hz + self.peak_rate_hz) / 2.0
        amp = (self.peak_rate_hz - self.rate_hz) / 2.0
        return max(
            _MIN_RATE_HZ, mid + amp * math.sin(2.0 * math.pi * phase)
        )

    def next_gap(self, t: float, rng: random.Random) -> float:
        """Seconds until this client's next op, sampled at the CURRENT
        rate (piecewise-stationary approximation of the non-homogeneous
        process — exact for steady/poisson, faithful at harness scale for
        the modulated shapes)."""
        rate = self.rate_at(t)
        if self.kind == "steady":
            return 1.0 / rate
        return rng.expovariate(rate)

    def spec(self) -> dict:
        return {
            "kind": self.kind,
            "rate_hz": self.rate_hz,
            "peak_rate_hz": self.peak_rate_hz,
            "period_s": self.period_s,
            "burst_frac": self.burst_frac,
            "zipf_alpha": self.zipf_alpha,
        }


def make_pattern(spec: Union[str, dict, ArrivalPattern]) -> ArrivalPattern:
    """``"poisson"`` | ``{"kind": "burst", "peak_rate_hz": 200, ...}`` |
    an already-built pattern (passed through)."""
    if isinstance(spec, ArrivalPattern):
        return spec
    if isinstance(spec, str):
        return ArrivalPattern(kind=spec)
    return ArrivalPattern(**spec)


def zipf_weights(n: int, alpha: float = 1.1) -> list[float]:
    """Normalized Zipf popularity weights for ranks ``0..n-1``.

    Rank ``i`` gets weight ``1/(i+1)**alpha``; with the default alpha the
    top handful of keys soak up most of the draws, which is exactly the
    hot-key shape the control plane's split/co-locate policies target.
    ``alpha == 0`` degrades to uniform."""
    if n <= 0:
        return []
    raw = [1.0 / float(i + 1) ** alpha for i in range(n)]
    total = sum(raw)
    return [w / total for w in raw]


def churn_sessions(
    duration_s: float, churn_rate_hz: float, rng: random.Random
) -> list[tuple[float, float]]:
    """One client's ``[(join_t, leave_t), ...]`` schedule over the run.

    ``churn_rate_hz <= 0`` means no churn: one session spanning the whole
    run. Otherwise live spans draw from an exponential with mean
    ``1 / churn_rate_hz`` and offline gaps from one a quarter as long
    (~80% duty cycle), with the first join jittered into the first live
    span so a thousand churning clients don't all (re)join at t=0."""
    if churn_rate_hz <= 0:
        return [(0.0, duration_s)]
    mean_up = 1.0 / churn_rate_hz
    mean_down = mean_up / 4.0
    sessions: list[tuple[float, float]] = []
    t = rng.uniform(0.0, mean_up / 2.0)
    while t < duration_s:
        up = rng.expovariate(1.0 / mean_up)
        leave = min(duration_s, t + up)
        if leave - t > 1e-3:
            sessions.append((t, leave))
        t = leave + rng.expovariate(1.0 / mean_down)
    return sessions or [(0.0, duration_s)]
