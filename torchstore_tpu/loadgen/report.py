"""Fold per-driver loadgen reports into the fleet view the bench gates on.

Each driver process ships home (harness._drive): per-op latency sample
lists (bounded, decimated past the cap), op/error counts, its own measured
op window, and its process-local ``timeline.slo_report()``. The merges
here are exact where it matters:

- latency quantiles are computed over the CONCATENATED samples (never an
  average of per-driver quantiles — that underestimates the tail the SLO
  gate is about);
- ops/s divides by the MAX driver window (drivers run concurrently; boot
  and spawn time never deflate the sustained rate — the metadata_scale
  lesson);
- scoreboard violation counts SUM across drivers, and the dominant stage
  per violated SLO is recomputed from the SUMMED per-stage wall time, so
  one driver's noisy attribution can't outvote the fleet's.
"""

from __future__ import annotations

from typing import Optional


def quantile_ms(samples: list[float], q: float) -> Optional[float]:
    """Exact q-quantile of a seconds-sample list, in milliseconds."""
    if not samples:
        return None
    ordered = sorted(samples)
    idx = min(len(ordered) - 1, int(len(ordered) * q))
    return ordered[idx] * 1e3


def merge_driver_reports(reports: list[dict]) -> dict:
    """Fleet fold of ``harness._drive`` reports (drivers that died or
    timed out are simply absent — the caller tracks ``failed_drivers``).

    Returns ``{"ops", "ops_per_s", "window_s", "by_op": {op: {"count",
    "errors", "p50_ms", "p99_ms"}}, "errors", "by_tenant": {tenant:
    {"count", "errors", "ops_per_s", "by_op"}}, "slo": merged scoreboard,
    "drivers"}``. ``by_tenant`` is present only when at least one driver
    labeled its clients (``LoadSpec.tenants > 1`` or the skewed profile)
    — each tenant's quantiles fold over that tenant's concatenated
    samples, same discipline as the fleet-wide ones."""
    by_op: dict[str, dict] = {}
    samples: dict[str, list[float]] = {}
    tenant_ops: dict[str, dict] = {}
    tenant_samples: dict[str, dict] = {}
    windows: list[float] = []
    total_ops = 0
    total_errors = 0
    for rep in reports:
        windows.append(float(rep.get("window_s") or 0.0))
        for op, count in (rep.get("counts") or {}).items():
            row = by_op.setdefault(op, {"count": 0, "errors": 0})
            row["count"] += int(count)
            total_ops += int(count)
        for op, errs in (rep.get("errors") or {}).items():
            row = by_op.setdefault(op, {"count": 0, "errors": 0})
            row["errors"] += int(errs)
            total_errors += int(errs)
        for op, vals in (rep.get("samples") or {}).items():
            samples.setdefault(op, []).extend(vals)
        for tenant, bucket in (rep.get("by_tenant") or {}).items():
            t_ops = tenant_ops.setdefault(tenant, {})
            t_samples = tenant_samples.setdefault(tenant, {})
            for op, count in (bucket.get("counts") or {}).items():
                row = t_ops.setdefault(op, {"count": 0, "errors": 0})
                row["count"] += int(count)
            for op, errs in (bucket.get("errors") or {}).items():
                row = t_ops.setdefault(op, {"count": 0, "errors": 0})
                row["errors"] += int(errs)
            for op, vals in (bucket.get("samples") or {}).items():
                t_samples.setdefault(op, []).extend(vals)
    for op, row in by_op.items():
        row["p50_ms"] = quantile_ms(samples.get(op, []), 0.5)
        row["p99_ms"] = quantile_ms(samples.get(op, []), 0.99)
        vals = samples.get(op)
        row["max_ms"] = round(max(vals) * 1e3, 3) if vals else None
    window = max(windows) if windows else 0.0
    merged = {
        "ops": total_ops,
        "errors": total_errors,
        "ops_per_s": round(total_ops / window, 1) if window > 0 else 0.0,
        "window_s": round(window, 3),
        "by_op": by_op,
        "slo": merge_slo_reports(
            [rep["slo"] for rep in reports if rep.get("slo")]
        ),
        "drivers": len(reports),
    }
    hist = merge_history(
        [rep["history"] for rep in reports if rep.get("history")]
    )
    if hist:
        merged["history"] = hist
    if tenant_ops:
        by_tenant: dict[str, dict] = {}
        for tenant in sorted(tenant_ops):
            t_ops = tenant_ops[tenant]
            t_samples = tenant_samples.get(tenant, {})
            for op, row in t_ops.items():
                row["p50_ms"] = quantile_ms(t_samples.get(op, []), 0.5)
                row["p99_ms"] = quantile_ms(t_samples.get(op, []), 0.99)
            count = sum(row["count"] for row in t_ops.values())
            errs = sum(row["errors"] for row in t_ops.values())
            by_tenant[tenant] = {
                "count": count,
                "errors": errs,
                "ops_per_s": (
                    round(count / window, 1) if window > 0 else 0.0
                ),
                "by_op": t_ops,
            }
        merged["by_tenant"] = by_tenant
    return merged


def merge_history(histories: list[dict]) -> dict:
    """Fold per-driver history docs (``observability.history()`` views of
    each driver's ``ts_client_ops_total`` / ``ts_op_p99_seconds`` rings)
    into the run's time-series shape:

    - ``ops_per_s``: EXACT per-bucket fleet rate — successive diffs of
      each driver's cumulative op counters (restart-safe), summed across
      op labels and drivers per timestamp bucket. Exact because the
      counters are cumulative: whatever the sampler's phase, the diff over
      a bucket boundary is precisely the ops that landed between them.
    - ``get_p99_ms``: worst per-bucket get p99 across drivers (a gauge —
      max is the only honest fleet fold without the underlying samples).

    Returns ``{"ops_per_s": [[ts, rate], ...], "get_p99_ms": [[ts, ms],
    ...], "step_s"}`` (lists oldest-first), or ``{}`` when no driver
    shipped history (TORCHSTORE_TPU_HISTORY=0)."""
    from torchstore_tpu.observability import history as obs_history

    ops_rates: list[list] = []
    p99_points: list[list] = []
    step = None
    for doc in histories:
        local = (doc or {}).get("processes", {}).get("client") or doc or {}
        series = local.get("series") or {}
        if step is None and local.get("step_s"):
            step = local["step_s"]
        for sid, entry in series.items():
            if sid.startswith("ts_client_ops_total{") or sid == "ts_client_ops_total":
                ops_rates.append(
                    obs_history.counter_rate_points(entry["points"])
                )
            elif sid == 'ts_op_p99_seconds{op="get"}':
                p99_points.append(entry["points"])
    out: dict = {}
    if ops_rates:
        merged: dict[float, float] = {}
        for rows in ops_rates:
            for ts, rate in rows:
                merged[ts] = merged.get(ts, 0.0) + rate
        out["ops_per_s"] = [
            [ts, round(merged[ts], 3)] for ts in sorted(merged)
        ]
    if p99_points:
        folded = obs_history.merge_points(p99_points, how="max")
        out["get_p99_ms"] = [
            [row[0], round(row[2] * 1e3, 3)] for row in folded
        ]
    if out and step is not None:
        out["step_s"] = step
    return out


def _merge_stage_tables(tables: list[dict]) -> dict:
    """Sum per-(op, stage) totals/samples across processes; p99 is the max
    (a conservative fleet tail — exact merging would need the rings)."""
    merged: dict[str, dict] = {}
    for table in tables:
        for op, stages in (table or {}).items():
            dst_op = merged.setdefault(op, {})
            for stage, row in stages.items():
                dst = dst_op.setdefault(
                    stage, {"samples": 0, "total_s": 0.0, "p99_s": None}
                )
                dst["samples"] += int(row.get("samples") or 0)
                dst["total_s"] = round(
                    dst["total_s"] + float(row.get("total_s") or 0.0), 6
                )
                p99 = row.get("p99_s")
                if p99 is not None and (
                    dst["p99_s"] is None or p99 > dst["p99_s"]
                ):
                    dst["p99_s"] = p99
    for stages in merged.values():
        grand = sum(row["total_s"] for row in stages.values()) or 0.0
        for row in stages.values():
            row["share"] = (
                round(row["total_s"] / grand, 4) if grand > 0 else 0.0
            )
    return merged


def merge_slo_reports(reports: list[dict]) -> dict:
    """Fold per-process ``timeline.slo_report()`` scoreboards into one:
    violations sum, ``current`` is the worst across processes, and each
    SLO's dominant stage is recomputed from the SUMMED stage time of its
    op."""
    stages = _merge_stage_tables([rep.get("stages") or {} for rep in reports])
    slos: dict[str, dict] = {}
    for rep in reports:
        for name, row in (rep.get("slos") or {}).items():
            dst = slos.get(name)
            if dst is None:
                dst = slos[name] = {
                    "env": row.get("env"),
                    "threshold": row.get("threshold"),
                    "worse": row.get("worse", "above"),
                    "op": row.get("op"),
                    "current": None,
                    "violations": 0,
                    "violated": False,
                }
            dst["violations"] += int(row.get("violations") or 0)
            dst["violated"] = dst["violated"] or bool(row.get("violated"))
            current = row.get("current")
            if current is not None:
                worst = dst["current"]
                worse_dir = dst["worse"]
                if worst is None or (
                    current > worst
                    if worse_dir == "above"
                    else current < worst
                ):
                    dst["current"] = current
    for name, row in slos.items():
        op = row.get("op")
        if op and op in stages and (row["violated"] or row["violations"]):
            op_stages = stages[op]
            row["stages"] = op_stages
            row["dominant_stage"] = max(
                op_stages.items(), key=lambda kv: kv[1]["total_s"]
            )[0] if op_stages else None
    return {"slos": slos, "stages": stages, "processes": len(reports)}
