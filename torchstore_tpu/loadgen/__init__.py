"""Fleet-scale load harness: prove "millions of users" arithmetic on one box.

A scale-model load generator (ROADMAP item 6): ``processes`` OS driver
processes x ``clients_per_process`` logical asyncio clients — hundreds of
simulated generator processes, thousands of logical clients — driving
puts, warm one-sided gets, streamed acquires, and pinned-version reads
against a live multi-volume fleet under composable arrival patterns:

- **arrivals** (:mod:`torchstore_tpu.loadgen.arrivals`): Poisson
  steady-state, square-wave bursts, diurnal (time-compressed sinusoid)
  skew — all deterministic per seed — plus per-client churn schedules
  (sessions that join/leave mid-run, riding relay membership when a
  relay channel is configured) and slow-reader pacing.
- **harness** (:mod:`torchstore_tpu.loadgen.harness`): :class:`LoadSpec`
  describes one run; :func:`run_fleet_load` spawns the driver processes
  (the ``metadata_scale`` bench's multi-process pattern), each driver
  runs its logical clients to the spec and ships home per-op latency
  samples, error counts, and its process-local ``slo_report()``.
- **report** (:mod:`torchstore_tpu.loadgen.report`): folds driver reports
  into the fleet view — sustained ops/s over the drivers' own measured
  windows, exact merged p50/p99 per op, and the merged SLO scoreboard
  (violation counts summed, dominant stage recomputed from summed
  per-stage wall time) the ``fleet_scale`` bench gates on.

The harness is also the chaos vehicle: pair a spec with armed faultpoints
(``ts.inject_fault`` / ``TORCHSTORE_TPU_FAULTPOINTS`` in ``spec.env``) or
kill a volume mid-run, and the merged scoreboard shows the blast radius —
which SLO blew, how often, and which stage ate the budget.
"""

from torchstore_tpu.loadgen.arrivals import (
    PATTERNS,
    ArrivalPattern,
    churn_sessions,
    make_pattern,
)
from torchstore_tpu.loadgen.harness import LoadSpec, run_fleet_load
from torchstore_tpu.loadgen.report import (
    merge_driver_reports,
    merge_slo_reports,
    quantile_ms,
)

__all__ = [
    "ArrivalPattern",
    "LoadSpec",
    "PATTERNS",
    "churn_sessions",
    "make_pattern",
    "merge_driver_reports",
    "merge_slo_reports",
    "quantile_ms",
    "run_fleet_load",
]
