"""The loadgen driver: LoadSpec in, merged fleet report out.

One :func:`run_fleet_load` call spawns ``spec.processes`` OS driver
processes (the ``metadata_scale`` bench's multi-process pattern: complete
env snapshot, per-driver pipe, measured windows that exclude boot), each
running ``spec.clients_per_process`` logical asyncio clients. Every
logical client replays a deterministic schedule derived from
``spec.seed``: its arrival pattern gaps, its op draws from ``spec.mix``,
its churn sessions, and whether it is a slow reader.

Op kinds (weights in ``spec.mix``):

    get     warm get of a pre-seeded shared key into a per-client
            destination array — the one-sided zero-RPC path once plans
            record (the fleet's dominant op, as in production serving)
    put     put_batch of the client's OWN key (no cross-client stamp
            churn on the shared working set)
    stream  streamed state-dict acquire of ``spec.stream_key`` (the
            harness seeds + seals it before drivers launch) — exercises
            watermark waits and the final consistency re-check
    pinned  barrier get_state_dict of ``spec.pinned_key`` (a historical
            channel version the harness holds a retention lease on)

Churn sessions re-enter through a FRESH ``reset_client`` boundary only at
the process level (clients share the process's LocalClient — per-session
actor re-dials at thousand-client scale would measure connection setup,
not the store); joining/leaving rides relay membership instead when
``spec.relay_channel`` is set, which is the membership signal the relay
trees actually consume.

Each driver ships home: per-op counts/errors, bounded latency samples
(decimated past ``spec.max_samples`` — quantiles stay exact to sampling),
its own measured window, and its process-local ``timeline.slo_report()``
(merged fleet-side by :mod:`torchstore_tpu.loadgen.report`).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import sys
from dataclasses import dataclass, field
from typing import Any, Optional

from torchstore_tpu.loadgen import report as report_mod
from torchstore_tpu.loadgen.arrivals import (
    churn_sessions,
    make_pattern,
    zipf_weights,
)

_OPS = ("get", "put", "stream", "pinned")


@dataclass
class LoadSpec:
    """One fleet-scale load run. Everything is plain data (JSON round-trip
    via ``to_json``/``from_json``): the spec crosses the process boundary
    as a string, never a pickle."""

    store_name: str = "loadgen"
    duration_s: float = 3.0
    processes: int = 8
    clients_per_process: int = 128
    # Arrival pattern: a PATTERNS name or a full spec dict
    # ({"kind", "rate_hz", "peak_rate_hz", "period_s", "burst_frac"}).
    pattern: Any = "poisson"
    rate_hz: float = 10.0  # per logical client, baseline
    # Op mix weights; ops absent (or zero) are never drawn. stream/pinned
    # require stream_key/pinned_key (seeded by the caller).
    mix: dict = field(default_factory=lambda: {"get": 0.8, "put": 0.2})
    value_kb: float = 4.0
    shared_keys: int = 64
    # Tenant cohorts: every logical client gets a stable tenant label
    # ("t0".."t{n-1}", round-robin over the global client index) carried
    # through its op records into the merged scoreboard's by_tenant
    # block. Under the "skewed" pattern, tenant t0 is the BURSTING
    # tenant: its clients run a burst schedule (peak_rate_hz, or 5x
    # baseline when unset) while every other tenant stays at baseline —
    # the isolation shape admission control is judged on.
    tenants: int = 1
    # Churn: per-client session turnover rate (0 = stable membership);
    # joins/leaves ride relay membership when relay_channel is set.
    churn_rate_hz: float = 0.0
    relay_channel: Optional[str] = None
    # Slow readers: this fraction of clients pauses slow_reader_ms after
    # every get (and per streamed layer) — consumption pacing, the
    # "straggler subscriber" shape.
    slow_reader_frac: float = 0.0
    slow_reader_ms: float = 5.0
    stream_key: Optional[str] = None
    pinned_key: Optional[str] = None
    seed: int = 0
    max_samples: int = 20000
    # Extra TORCHSTORE_TPU_* env for the DRIVER processes (SLO thresholds,
    # faultpoints, ledger toggles): overlaid on the parent's snapshot.
    # NOTE: StoreConfig-derived flags (one_sided, transports, retry) ride
    # the store handle's PICKLED config from the initializing process —
    # env overrides here cannot reach them; use config_overrides.
    env: dict = field(default_factory=dict)
    # Emulated multi-host topology: driver d runs under
    # TORCHSTORE_TPU_HOSTNAME=hostnames[d % len(hostnames)], so a
    # single-machine fleet exercises every cross-host path (metadata
    # mirrors, push sessions, relay parenting) exactly as a real
    # multi-host deployment would — get_hostname() is the only identity
    # the planes ever consult. Empty/None = inherit the real hostname.
    hostnames: list = field(default_factory=list)
    # DCN emulation: >0 sets TORCHSTORE_TPU_BULK_EMULATE_GBPS in every
    # driver, pacing bulk/push/mirror frames to the given line rate so
    # cross-host latency comparisons aren't loopback-flattered.
    emulate_gbps: float = 0.0
    # StoreConfig field overrides applied to each driver's client config
    # (dataclasses.replace) — e.g. {"one_sided": False} to force every
    # get onto the RPC plane (chaos legs measuring failover, which the
    # kill-resilient one-sided path deliberately hides).
    config_overrides: dict = field(default_factory=dict)

    def to_json(self) -> str:
        spec = dataclasses.asdict(self)
        if not isinstance(spec["pattern"], (str, dict)):
            spec["pattern"] = self.pattern.spec()
        return json.dumps(spec)

    @classmethod
    def from_json(cls, text: str) -> "LoadSpec":
        return cls(**json.loads(text))


def _client_rng(spec: LoadSpec, driver_idx: int, client_idx: int):
    import random

    return random.Random(
        (spec.seed * 1000003 + driver_idx * 1009 + client_idx) & 0x7FFFFFFF
    )


def _driver_main(env: dict, spec_json: str, driver_idx: int, conn) -> None:
    """Driver PROCESS entry (multiprocessing target — must stay
    module-level importable). Scrubs the forkserver's stale
    TORCHSTORE_TPU_* snapshot exactly like runtime.actors._child_main,
    overlays the spec's env, then runs the async drive."""
    import asyncio as _asyncio
    import os as _os

    for key in list(_os.environ):
        if key.startswith("TORCHSTORE_TPU_") and key not in env:
            del _os.environ[key]
    _os.environ.update(env)
    _os.environ.setdefault("TORCHSTORE_TPU_LOG_LEVEL", "ERROR")
    from torchstore_tpu import config as _config_mod
    from torchstore_tpu import faults as _faults
    from torchstore_tpu import observability as _obs

    _config_mod._default_config = None
    _faults.reinit_after_fork()
    # Same story as runtime.actors._child_main: the forkserver's history
    # sampler thread died in the fork and its rings are another process's
    # — without this the driver ships an EMPTY history doc home and the
    # diurnal-shape artifact silently vanishes.
    _obs.reinit_after_fork()
    spec = LoadSpec.from_json(spec_json)
    try:
        out = _asyncio.run(_drive(spec, driver_idx))
    except BaseException as exc:  # noqa: BLE001 - ship the failure home
        out = {"driver_error": f"{type(exc).__name__}: {exc}"[:500]}
    try:
        conn.send(out)
    finally:
        conn.close()


async def _drive(spec: LoadSpec, driver_idx: int) -> dict:
    import asyncio
    import time

    import numpy as np

    import torchstore_tpu as ts
    from torchstore_tpu.observability import timeline as obs_timeline
    from torchstore_tpu.utils import get_hostname

    client = ts.client(spec.store_name)
    await client._ensure_setup()
    if spec.config_overrides:
        client._config = dataclasses.replace(
            client._config, **spec.config_overrides
        )
    pattern = make_pattern(spec.pattern)
    if pattern.rate_hz != spec.rate_hz and isinstance(spec.pattern, str):
        # Bare pattern names take the spec's baseline rate; dict specs own
        # their rates explicitly.
        pattern = make_pattern({**pattern.spec(), "rate_hz": spec.rate_hz})
    ops = [op for op in _OPS if spec.mix.get(op)]
    weights = [float(spec.mix[op]) for op in ops]
    if not ops:
        raise ValueError(f"LoadSpec.mix selects no ops: {spec.mix!r}")
    shared = [f"{spec.store_name}/shared/{i}" for i in range(spec.shared_keys)]
    n_elem = max(1, int(spec.value_kb * 1024 // 4))

    # Warmup BEFORE the measured window: create every client's own key
    # now (a first put of a NEW key is a structural placement-epoch bump
    # that invalidates plans fleet-wide — 1k clients doing that inside
    # the window would measure epoch churn, not steady state) and touch
    # the shared working set once so locates/one-sided plans are warm.
    # Real fleets run for hours; the measured window models their steady
    # state, and the cold start is visible in the window_s vs duration_s
    # gap, not buried in the p99.
    own_keys = {
        i: f"{spec.store_name}/own/{driver_idx}/{i}"
        for i in range(spec.clients_per_process)
    }
    if "put" in ops:
        warm_val = np.zeros(n_elem, np.float32)
        for start in range(0, spec.clients_per_process, 64):
            await client.put_batch(
                {
                    own_keys[i]: warm_val
                    for i in range(
                        start, min(start + 64, spec.clients_per_process)
                    )
                }
            )
    if "get" in ops:
        warm_dests = {key: np.zeros(n_elem, np.float32) for key in shared}
        await client.get_batch(warm_dests)  # locate + record plans
        await client.get_batch(warm_dests)  # warm one-sided pass

    # Skewed profile: Zipf-weighted shared-key draws (hot keys emerge)
    # plus one bursting tenant cohort; every other pattern keeps the
    # uniform pick and a single flat cohort.
    zipf_cum = None
    if pattern.kind == "skewed" and shared:
        import itertools

        zipf_cum = list(
            itertools.accumulate(zipf_weights(len(shared), pattern.zipf_alpha))
        )
    n_tenants = max(1, int(spec.tenants))
    burst_pattern = None
    if pattern.kind == "skewed" and n_tenants > 1:
        peak = pattern.peak_rate_hz
        if peak <= pattern.rate_hz:
            peak = pattern.rate_hz * 5.0
        burst_pattern = make_pattern(
            {
                "kind": "burst",
                "rate_hz": pattern.rate_hz,
                "peak_rate_hz": peak,
                "period_s": pattern.period_s,
                "burst_frac": pattern.burst_frac,
            }
        )

    counts = {op: 0 for op in ops}
    errors: dict[str, int] = {}
    samples: dict[str, list[float]] = {op: [] for op in ops}
    by_tenant: dict[str, dict] = {}

    def _tenant_bucket(tenant: str) -> dict:
        bucket = by_tenant.get(tenant)
        if bucket is None:
            bucket = by_tenant[tenant] = {
                "counts": {op: 0 for op in ops},
                "errors": {},
                "samples": {op: [] for op in ops},
            }
        return bucket

    def _decimated_append(bucket: list, dur_s: float) -> None:
        if len(bucket) >= spec.max_samples:
            # Decimate in place (drop every other sample) — a uniform
            # thinning that keeps quantiles representative while bounding
            # what crosses the pipe home.
            del bucket[::2]
        bucket.append(dur_s)

    def observe(op: str, dur_s: float, tenant: str) -> None:
        counts[op] += 1
        _decimated_append(samples[op], dur_s)
        t = _tenant_bucket(tenant)
        t["counts"][op] += 1
        _decimated_append(t["samples"][op], dur_s)

    async def one_client(client_idx: int, stop_at: float) -> None:
        rng = _client_rng(spec, driver_idx, client_idx)
        slow = rng.random() < spec.slow_reader_frac
        tenant = (
            f"t{(driver_idx * spec.clients_per_process + client_idx) % n_tenants}"
        )
        client_pattern = (
            burst_pattern
            if burst_pattern is not None and tenant == "t0"
            else pattern
        )
        own_key = own_keys[client_idx]
        own_val = np.random.default_rng(client_idx).standard_normal(
            n_elem, dtype=np.float32
        )
        dests = {}
        t0 = time.monotonic()
        sessions = churn_sessions(
            spec.duration_s, spec.churn_rate_hz, rng
        )

        async def run_session(leave_t: float) -> None:
            subscribed = None
            if spec.relay_channel:
                try:
                    sub = await client.controller.relay_subscribe.call_one(
                        spec.relay_channel, get_hostname()
                    )
                    subscribed = sub.get("volume_id")
                except Exception:  # noqa: BLE001 - membership is advisory
                    subscribed = None
            try:
                while True:
                    now = time.monotonic() - t0
                    if now >= leave_t or time.monotonic() >= stop_at:
                        return
                    gap = client_pattern.next_gap(now, rng)
                    await asyncio.sleep(
                        min(gap, max(0.0, leave_t - now))
                    )
                    if time.monotonic() >= stop_at:
                        return
                    if time.monotonic() - t0 >= leave_t:
                        # The session ended before this gap elapsed: the
                        # arrival pattern never scheduled an op here —
                        # firing one anyway would cluster unscheduled ops
                        # at every session boundary (at high churn, far
                        # MORE load than the configured rate).
                        return
                    op = rng.choices(ops, weights=weights)[0]
                    t_op = time.perf_counter()
                    try:
                        if op == "get":
                            if zipf_cum is None:
                                key = shared[rng.randrange(len(shared))]
                            else:
                                key = rng.choices(
                                    shared, cum_weights=zipf_cum
                                )[0]
                            dest = dests.get(key)
                            if dest is None:
                                dest = dests[key] = np.zeros(
                                    n_elem, np.float32
                                )
                            await client.get_batch({key: dest})
                        elif op == "put":
                            own_val[0] = counts["put"]
                            await client.put_batch({own_key: own_val})
                        elif op == "stream":
                            on_layer = None
                            if slow:
                                async def on_layer(fk, value):  # noqa: ARG001
                                    await asyncio.sleep(
                                        spec.slow_reader_ms / 1e3
                                    )
                            await ts.get_state_dict(
                                spec.stream_key,
                                stream=True,
                                on_layer=on_layer,
                                store_name=spec.store_name,
                            )
                        elif op == "pinned":
                            await ts.get_state_dict(
                                spec.pinned_key,
                                store_name=spec.store_name,
                            )
                    except Exception:  # noqa: BLE001 - counted, run goes on
                        errors[op] = errors.get(op, 0) + 1
                        t_err = _tenant_bucket(tenant)["errors"]
                        t_err[op] = t_err.get(op, 0) + 1
                    else:
                        observe(op, time.perf_counter() - t_op, tenant)
                        if slow and op == "get":
                            await asyncio.sleep(spec.slow_reader_ms / 1e3)
            finally:
                if subscribed is not None:
                    try:
                        await client.controller.relay_unsubscribe.call_one(
                            spec.relay_channel, subscribed
                        )
                    except Exception:  # noqa: BLE001 - leaving is advisory
                        pass

        for join_t, leave_t in sessions:
            now = time.monotonic() - t0
            if now < join_t:
                await asyncio.sleep(join_t - now)
            if time.monotonic() >= stop_at:
                return
            await run_session(leave_t)

    # Ready marker: chaos harnesses (kill-mid-run tests) need to know the
    # measured window is OPEN before they strike — wall-clock sleeps race
    # the seconds of driver boot/import and land their chaos on an idle
    # fleet. One put per driver, BEFORE the window opens so its
    # structural epoch bump never pollutes the first samples.
    await client.put_batch(
        {
            f"{spec.store_name}/ctl/ready/{driver_idx}": np.zeros(
                1, np.float32
            )
        }
    )
    # The measured window opens AFTER boot/attach: sustained ops/s divides
    # by what the drivers actually drove, never spawn/import time.
    t_start = time.monotonic()
    stop_at = t_start + spec.duration_s
    await asyncio.gather(
        *(one_client(i, stop_at) for i in range(spec.clients_per_process))
    )
    from torchstore_tpu.observability import history as obs_history

    return {
        "driver": driver_idx,
        "counts": counts,
        "errors": errors,
        "samples": samples,
        "by_tenant": by_tenant,
        "window_s": time.monotonic() - t_start,
        "slo": obs_timeline.slo_report(),
        # This driver's retained op-rate + tail series over the run window
        # (merge_history folds the fleet's by timestamp bucket, so a
        # diurnal arrival shape is reconstructable from the artifact).
        "history": obs_history.history(
            series=("ts_client_ops_total*", "ts_op_p99_seconds*"),
            since=spec.duration_s + 60.0,
        ),
    }


async def run_fleet_load(spec: LoadSpec) -> dict:
    """Run one loadgen spec against an ALREADY-INITIALIZED store fleet
    (the caller owns initialize/seed/shutdown — the bench and the chaos
    tests both reuse fleets across legs). Seeds the shared get working
    set, spawns the driver processes, and folds their reports.

    Returns the merged report (see ``report.merge_driver_reports``) plus
    ``{"logical_clients", "failed_drivers", "driver_errors"}``."""
    import os

    import numpy as np

    import torchstore_tpu as ts
    from torchstore_tpu.runtime.actors import _mp_context

    client = ts.client(spec.store_name)
    await client._ensure_setup()
    n_elem = max(1, int(spec.value_kb * 1024 // 4))
    seed_rng = np.random.default_rng(spec.seed)
    await client.put_batch(
        {
            f"{spec.store_name}/shared/{i}": seed_rng.standard_normal(
                n_elem, dtype=np.float32
            )
            for i in range(spec.shared_keys)
        }
    )
    env = {
        k: v for k, v in os.environ.items() if k.startswith("TORCHSTORE_TPU_")
    }
    env.update({k: str(v) for k, v in (spec.env or {}).items()})
    if spec.emulate_gbps and spec.emulate_gbps > 0:
        env["TORCHSTORE_TPU_BULK_EMULATE_GBPS"] = str(spec.emulate_gbps)
    ctx = _mp_context()
    procs = []
    spec_json = spec.to_json()
    for d in range(spec.processes):
        denv = env
        if spec.hostnames:
            # Per-driver host identity: the overlay is what makes the
            # driver REMOTE to every volume/index host, arming the
            # mirror + push-session planes instead of same-host shm.
            denv = dict(env)
            denv["TORCHSTORE_TPU_HOSTNAME"] = spec.hostnames[
                d % len(spec.hostnames)
            ]
        parent, child = ctx.Pipe()
        proc = ctx.Process(
            target=_driver_main,
            args=(denv, spec_json, d, child),
            daemon=True,
            name=f"ts-loadgen-{d}",
        )
        proc.start()
        child.close()
        procs.append((proc, parent))
    reports: list[dict] = []
    failed = 0
    driver_errors: list[str] = []
    loop = asyncio.get_running_loop()

    def _recv(parent) -> Optional[dict]:
        # Blocking pipe wait — MUST run on an executor thread: a bare
        # parent.poll() here would freeze the caller's whole event loop
        # for the run's duration, silently serializing "concurrent" work
        # (the bench's under-load measurement, a chaos harness's
        # kill-timing) until the drivers finish.
        if parent.poll(spec.duration_s + 120):
            return parent.recv()
        return None

    async def _collect(parent) -> None:
        nonlocal failed
        try:
            rep = await loop.run_in_executor(None, _recv, parent)
        except (EOFError, OSError):
            failed += 1
            driver_errors.append("driver pipe broke (process died?)")
            return
        if rep is None:
            failed += 1
            driver_errors.append("driver timed out")
        elif "driver_error" in rep:
            failed += 1
            driver_errors.append(rep["driver_error"])
        else:
            reports.append(rep)

    await asyncio.gather(*(_collect(parent) for _, parent in procs))
    for proc, _ in procs:
        proc.join(10)
        if proc.is_alive():
            proc.terminate()
    merged = report_mod.merge_driver_reports(reports)
    merged["logical_clients"] = spec.processes * spec.clients_per_process
    merged["failed_drivers"] = failed
    if driver_errors:
        merged["driver_errors"] = driver_errors[:8]
        print(
            f"# loadgen: {failed} driver(s) failed: {driver_errors[:3]}",
            file=sys.stderr,
        )
    return merged
