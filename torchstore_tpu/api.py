"""Public module-level async API.

TPU-native equivalent of /root/reference/torchstore/api.py:27-438: a store
registry keyed by ``store_name``, ``initialize`` spawning volumes + the
controller, and module-level ``put/get/...`` delegating to a cached
``LocalClient``. Store handles are published through an env var
(``TORCHSTORE_TPU_STORE_<name>``) so actor processes spawned afterwards
discover the controller the way Monarch's global actor naming served the
reference (/root/reference/torchstore/api.py:118-123).
"""

from __future__ import annotations

import asyncio
import base64
import os
import pickle
import time
from dataclasses import dataclass
from typing import Any, Optional

from torchstore_tpu.client import LocalClient, Shard
from torchstore_tpu.config import StoreConfig, default_config
from torchstore_tpu.controller import Controller
from torchstore_tpu.logging import get_logger, set_log_level
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.runtime import (
    ActorMesh,
    ActorRef,
    get_or_spawn_singleton,
    spawn_actors,
    stop_singleton,
)
from torchstore_tpu.storage_volume import StorageVolume
from torchstore_tpu.strategy import (
    LocalRankStrategy,
    SingletonStrategy,
    StoreStrategy,
)

logger = get_logger("torchstore_tpu.api")

ENV_STORE_PREFIX = "TORCHSTORE_TPU_STORE_"
DEFAULT_STORE = "default"


@dataclass
class _StoreHandle:
    controller: ActorRef
    volume_mesh: Optional[ActorMesh]  # only in the initializing process
    client: Optional[LocalClient]
    config: StoreConfig
    owner: bool
    inproc_volume: Any = None  # (server, ref) when colocated
    volume_env: dict = None  # env the volumes were spawned with (repair)
    repair_meshes: list = None  # replacement volumes spawned by repair()
    shard_mesh: Any = None  # ControllerShard actors (sharded metadata plane)
    retired_shard_meshes: list = None  # pre-reshard meshes (stopped at shutdown)
    autoscale_meshes: list = None  # [{"vid", "mesh"}] spawned by ts.autoscale()
    volume_env_fn: Any = None  # per-rank env overrides (reused by autoscale)


# Per-process store registry: forked actor children never reuse the parent's
# handles — they rebuild from the TORCHSTORE_TPU_STORE_* env their spawner
# passes explicitly (see spawn_actors' env forwarding).
_stores: dict[str, _StoreHandle] = {}  # tslint: disable=fork-safety


def _publish_handle(store_name: str, controller: ActorRef) -> None:
    payload = base64.b64encode(pickle.dumps(controller)).decode()
    os.environ[ENV_STORE_PREFIX + store_name] = payload


def _discover_handle(store_name: str) -> Optional[ActorRef]:
    payload = os.environ.get(ENV_STORE_PREFIX + store_name)
    if not payload:
        return None
    return pickle.loads(base64.b64decode(payload))


async def initialize(
    num_storage_volumes: int = 1,
    strategy: Optional[StoreStrategy] = None,
    store_name: str = DEFAULT_STORE,
    config: Optional[StoreConfig] = None,
    storage_dir: Optional[str] = None,
    recover: bool = False,
    colocated: bool = False,
    volume_env_fn: Optional[Any] = None,
    controller_shards: Optional[int] = None,
) -> ActorRef:
    """Boot a store: spawn volume actors, the singleton controller, wire them
    (/root/reference/torchstore/api.py:33-81). With ``storage_dir`` the
    volumes persist entries to disk; ``recover=True`` additionally rebuilds
    the metadata index from what the directory already holds (crash/restart
    recovery — beyond the reference, whose store is memory-only).

    ``volume_env_fn(rank) -> dict`` adds per-volume env overrides on top of
    the store's base volume env — e.g. a distinct
    ``TORCHSTORE_TPU_HOSTNAME`` per volume to emulate a multi-host fleet on
    one box (the relay fanout bench / tests measure per-host egress this
    way). Ignored for ``colocated`` stores (the single volume lives in this
    process).

    ``colocated=True`` hosts the (single) storage volume IN THIS PROCESS:
    local endpoint calls become direct method invocations — no RPC hop, no
    serialization — which drops same-process small-op latency to the tens
    of microseconds (the VERDICT r1 colocated-volume fast path). Remote
    processes still reach the volume over its real actor server, which
    serves as long as this process's event loop runs.

    ``controller_shards`` (default: ``TORCHSTORE_TPU_CONTROLLER_SHARDS``,
    1) partitions the metadata plane: the key->volume index is split
    across that many ControllerShard actors by stable key hash, with
    fleet-scoped state (placement epoch, health, streams, relay, leases)
    on the coordinator — locate/notify throughput scales with the shard
    count instead of funneling through one actor queue."""
    if store_name in _stores:
        raise RuntimeError(f"store {store_name!r} already initialized")
    config = config or default_config()
    if recover and not storage_dir:
        raise ValueError("recover=True requires storage_dir")
    if colocated and num_storage_volumes != 1:
        raise ValueError("colocated=True hosts exactly one volume")
    set_log_level(config.log_level)
    if config.use_native:
        from torchstore_tpu import native

        native.get_lib()  # build/load once at bootstrap, not mid-transfer
    if strategy is None:
        strategy = (
            SingletonStrategy() if num_storage_volumes == 1 else LocalRankStrategy()
        )
    if getattr(strategy, "replication", 1) > num_storage_volumes:
        raise ValueError(
            f"replication={strategy.replication} needs at least that many "
            f"storage volumes (have {num_storage_volumes})"
        )
    # Per-spawn env (NOT process-global os.environ: a failure mid-initialize
    # or a concurrent initialize must not leak the dir into other stores).
    volume_env = (
        {"TORCHSTORE_TPU_STORAGE_DIR": storage_dir} if storage_dir else {}
    )
    if config.auth_secret:
        # Volume processes must present/verify the same secret. A
        # programmatically-set secret is also exported to this process's env
        # (and the cached default config refreshed) so module-level client
        # paths — connection pool, rendezvous — see it too. Auth is
        # process-global: one secret per process, so a second store with a
        # DIFFERENT secret would silently break the first one's connections
        # — reject that instead.
        existing = os.environ.get("TORCHSTORE_TPU_AUTH_SECRET")
        if existing and existing != config.auth_secret:
            raise ValueError(
                "a different TORCHSTORE_TPU_AUTH_SECRET is already active "
                "in this process; auth secrets are per-process, not "
                "per-store"
            )
        volume_env["TORCHSTORE_TPU_AUTH_SECRET"] = config.auth_secret
        if existing != config.auth_secret:
            os.environ["TORCHSTORE_TPU_AUTH_SECRET"] = config.auth_secret
            from torchstore_tpu import config as config_mod

            config_mod._default_config = None
    inproc_volume = None
    if colocated:
        volume_mesh, inproc_volume = await _host_colocated_volume(
            store_name, strategy, volume_env
        )
    else:
        volume_mesh = await spawn_actors(
            num_storage_volumes,
            StorageVolume,
            f"ts_{store_name}_volume",
            strategy,
            env_fn=lambda rank: {
                **volume_env,
                **((volume_env_fn(rank) or {}) if volume_env_fn else {}),
            },
        )
    n_shards = (
        controller_shards
        if controller_shards is not None
        else config.controller_shards
    )
    shard_mesh = None
    try:
        controller = await get_or_spawn_singleton(
            f"ts_{store_name}_controller", Controller
        )
        await controller.init.call_one(strategy, volume_mesh.refs)
        if n_shards and n_shards > 1:
            # Sharded metadata plane: spawn the shard actors and hand each
            # its slot BEFORE any key is indexed (recover included — the
            # rebuild below partitions survivors to their owning shards).
            from torchstore_tpu.metadata.shards import ControllerShard

            shard_mesh = await spawn_actors(
                int(n_shards),
                ControllerShard,
                f"ts_{store_name}_ctrlshard",
            )
            await controller.attach_shards.call_one(
                controller, shard_mesh.refs
            )
        if recover:
            recovered = await controller.rebuild_index.call_one()
            logger.info(
                "recovered %d entries from %s", recovered, storage_dir
            )
    except BaseException:
        # Failed bootstrap must not leak volume/shard processes.
        if inproc_volume is not None:
            await _stop_colocated_volume(inproc_volume)
        else:
            await volume_mesh.stop()
        if shard_mesh is not None:
            await shard_mesh.stop()
        await stop_singleton(f"ts_{store_name}_controller")
        raise
    _publish_handle(store_name, controller)
    _stores[store_name] = _StoreHandle(
        controller=controller,
        volume_mesh=None if colocated else volume_mesh,
        client=None,
        config=config,
        owner=True,
        inproc_volume=inproc_volume,
        volume_env=dict(volume_env),
        repair_meshes=[],
        shard_mesh=shard_mesh,
        retired_shard_meshes=[],
        autoscale_meshes=[],
        volume_env_fn=volume_env_fn,
    )
    return controller


async def _host_colocated_volume(store_name: str, strategy, volume_env: dict):
    """Host one StorageVolume in THIS process: real actor server (remote
    clients reach it over RPC) + in-process registration (local endpoint
    calls dispatch directly)."""
    import socket as _socket

    from torchstore_tpu.runtime.actors import ActorServer, register_inproc

    old_env = {k: os.environ.get(k) for k in volume_env}
    os.environ.update(volume_env)  # StorageVolume reads STORAGE_DIR etc.
    try:
        volume = StorageVolume(strategy)
    finally:
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    name = f"ts_{store_name}_volume_0"
    server = ActorServer()
    server.register(name, volume)
    bind_host = os.environ.get("TORCHSTORE_TPU_BIND_HOST", "127.0.0.1")
    port = await server.start(bind_host)
    advertise = os.environ.get("TORCHSTORE_TPU_ADVERTISE_HOST")
    if advertise is None:
        advertise = (
            _socket.gethostname() if bind_host in ("0.0.0.0", "::") else bind_host
        )
    ref = ActorRef(name, advertise, port)
    register_inproc(advertise, port, name, volume)
    mesh = ActorMesh([ref], [])
    return mesh, (server, ref, volume)


async def _stop_colocated_volume(inproc_volume) -> None:
    from torchstore_tpu.runtime.actors import unregister_inproc

    server, ref, volume = inproc_volume
    unregister_inproc(ref.host, ref.port, ref.name)
    # A process-hosted volume's /dev/shm segments outlive ts.shutdown()
    # unless released here: the orphan reaper keys on dead creator pids,
    # and THIS process stays alive (normal volumes are reclaimed by
    # process exit). Idempotent after controller teardown already reset.
    try:
        volume.store.reset()
        volume.ctx.clear()
    except Exception:
        logger.exception("colocated volume cleanup failed")
    await server.close()


async def initialize_spmd(
    strategy: Optional[StoreStrategy] = None,
    store_name: str = DEFAULT_STORE,
    config: Optional[StoreConfig] = None,
    storage_dir: Optional[str] = None,
    recover: bool = False,
) -> None:
    """Collective bootstrap from torchrun-style env — call on every rank
    (/root/reference/torchstore/spmd.py:246-362). ``storage_dir``/``recover``
    enable durable volumes + index recovery, as in ``initialize``."""
    from torchstore_tpu import spmd as spmd_mod

    await spmd_mod.initialize(
        strategy=strategy,
        store_name=store_name,
        config=config,
        storage_dir=storage_dir,
        recover=recover,
    )


def client(store_name: str = DEFAULT_STORE) -> LocalClient:
    """The per-process cached LocalClient
    (/root/reference/torchstore/api.py:141-153)."""
    handle = _stores.get(store_name)
    if handle is None:
        controller = _discover_handle(store_name)
        if controller is None:
            raise RuntimeError(
                f"store {store_name!r} is not initialized in this process and "
                "no published handle was found; call ts.initialize() first"
            )
        handle = _StoreHandle(
            controller=controller,
            volume_mesh=None,
            client=None,
            config=default_config(),
            owner=False,
        )
        _stores[store_name] = handle
    if handle.client is None:
        handle.client = LocalClient(handle.controller, handle.config)
    return handle.client


def reset_client(store_name: str = DEFAULT_STORE) -> None:
    handle = _stores.get(store_name)
    if handle is not None:
        handle.client = None


async def put(key: str, value: Any, store_name: str = DEFAULT_STORE) -> None:
    await client(store_name).put(key, value)


async def put_batch(items: dict[str, Any], store_name: str = DEFAULT_STORE) -> None:
    await client(store_name).put_batch(items)


async def get(key: str, like: Any = None, store_name: str = DEFAULT_STORE) -> Any:
    return await client(store_name).get(key, like)


async def get_batch(
    items, store_name: str = DEFAULT_STORE
) -> dict[str, Any]:
    """Batched get: ``items`` is a list of keys or {key: target_or_None}."""
    return await client(store_name).get_batch(items)


async def delete(key: str, store_name: str = DEFAULT_STORE) -> None:
    await client(store_name).delete(key)


async def delete_batch(keys: list[str], store_name: str = DEFAULT_STORE) -> None:
    await client(store_name).delete_batch(keys)


async def delete_prefix(prefix: str, store_name: str = DEFAULT_STORE) -> int:
    return await client(store_name).delete_prefix(prefix)


async def keys(
    prefix: Optional[str] = None, store_name: str = DEFAULT_STORE
) -> list[str]:
    return await client(store_name).keys(prefix)


async def exists(key: str, store_name: str = DEFAULT_STORE) -> bool:
    return await client(store_name).exists(key)


async def wait_for(
    keys, timeout: Optional[float] = None, store_name: str = DEFAULT_STORE
) -> None:
    """Block until every key (str or list of str) exists and is fully
    committed (sharded keys: all mesh coordinates landed). Raises
    TimeoutError on expiry. Replaces the reference's poll-in-try/except
    consumer idiom with a push notification from the controller."""
    await client(store_name).wait_for(keys, timeout=timeout)


async def put_state_dict(
    key: str,
    state_dict: Any,
    transfer_dtype=None,
    transfer_quant: Optional[str] = None,
    direct: bool = False,
    rank: int = 0,
    num_ranks: int = 1,
    store_name: str = DEFAULT_STORE,
) -> None:
    from torchstore_tpu import state_dict_utils

    await state_dict_utils.put_state_dict(
        client(store_name),
        key,
        state_dict,
        transfer_dtype=transfer_dtype,
        transfer_quant=transfer_quant,
        direct=direct,
        rank=rank,
        num_ranks=num_ranks,
    )


def direct_staging_buffers(key: str, store_name: str = DEFAULT_STORE) -> Any:
    """Registered staging buffers for a direct-pushed state dict (write
    weights straight into them to make later direct puts copy-free); None
    when unavailable. See state_dict_utils.direct_staging_buffers."""
    from torchstore_tpu import state_dict_utils

    return state_dict_utils.direct_staging_buffers(client(store_name), key)


async def get_state_dict(
    key: str,
    user_state_dict: Any = None,
    direct: bool = False,
    strict: bool = True,
    key_order: Optional[list] = None,
    on_layer: Any = None,
    stream: bool = False,
    store_name: str = DEFAULT_STORE,
) -> Any:
    from torchstore_tpu import state_dict_utils

    return await state_dict_utils.get_state_dict(
        client(store_name),
        key,
        user_state_dict,
        direct=direct,
        strict=strict,
        key_order=key_order,
        on_layer=on_layer,
        stream=stream,
    )


def state_dict_stream(
    key: str,
    transfer_dtype=None,
    transfer_quant: Optional[str] = None,
    store_name: str = DEFAULT_STORE,
):
    """Open an incremental (layer-streamed) publish of ``key``: push
    fragments with ``await stream.put(...)`` as tensors become ready, then
    ``await stream.seal()`` — each batch is watermarked per key so
    streaming consumers (``get_state_dict(stream=True)`` /
    ``WeightSubscriber.acquire_streamed``) serve it immediately, while
    barrier readers still wake only on the sealed, complete dict.
    ``transfer_quant`` ships floating layers as fused blockwise blobs
    (delta encoding is a weight_channel feature — see
    ``WeightPublisher(delta=True)``). See
    :mod:`torchstore_tpu.stream_sync`."""
    from torchstore_tpu import state_dict_utils

    return state_dict_utils.stream_state_dict(
        client(store_name),
        key,
        transfer_dtype=transfer_dtype,
        transfer_quant=transfer_quant,
    )


async def get_state_dict_streamed(
    key: str,
    user_state_dict: Any = None,
    key_order: Optional[list] = None,
    on_layer: Any = None,
    strict: bool = True,
    timeout: Optional[float] = None,
    wait_for_stream_s: Optional[float] = None,
    relay_volume: Optional[str] = None,
    store_name: str = DEFAULT_STORE,
) -> Any:
    """Acquire a streamed publish layer by layer (long-poll, no spin):
    each key is served the moment its watermark lands, in ``key_order``
    when given, with ``on_layer(flat_key, value)`` per served leaf.
    ``wait_for_stream_s`` waits for a publisher that hasn't begun yet.
    ``relay_volume`` gates + routes the acquire through this host's
    broadcast relay copy (see ``WeightSubscriber(relay=True)``, which
    manages the subscription for you). Never mixes generations — see
    torchstore_tpu/stream_sync.py."""
    from torchstore_tpu import stream_sync

    return await stream_sync.get_state_dict_streamed(
        client(store_name),
        key,
        user_state_dict=user_state_dict,
        key_order=key_order,
        on_layer=on_layer,
        strict=strict,
        timeout=timeout,
        wait_for_stream_s=wait_for_stream_s,
        relay_volume=relay_volume,
    )


async def repair(store_name: str = DEFAULT_STORE) -> dict:
    """Elastic recovery: replace dead storage volumes with fresh actors and
    re-replicate every key a surviving replica still holds (the recovery
    story the reference lacks entirely — SURVEY §5 "no elasticity").

    Must run in the process that initialized the store. Returns
    ``{"replaced": [vids], "rereplicated": n_keys, "lost": [keys],
    "failed": [keys], "wedged": [vids]}``. Keys with no surviving copy are
    reported lost and dropped from the index (reads fail loudly with
    missing); keys whose re-replication read failed are reported in
    ``failed`` (their surviving copies stay indexed — run repair again).
    All dead volumes are REPLACED FIRST, then re-replication runs, so a
    multi-volume failure repairs whatever any survivor holds. Wedged
    (alive-but-stuck) volumes are NOT replaced — they may recover; kill
    the process first if replacement is wanted. Durable stores
    (``storage_dir``) can instead restart the volume and use
    ``recover=True`` to reload from disk."""
    from torchstore_tpu.runtime import spawn_actors as _spawn
    from torchstore_tpu.transport.types import Request

    handle = _stores.get(store_name)
    if handle is None or not handle.owner or handle.volume_mesh is None:
        raise RuntimeError(
            "repair must run in the process that initialized the store "
            "(with process-backed volumes)"
        )
    c = client(store_name)
    statuses = await handle.controller.check_volumes.call_one()
    dead = sorted(v for v, s in statuses.items() if s.startswith("dead"))
    wedged = sorted(v for v, s in statuses.items() if s.startswith("wedged"))
    if dead or wedged:
        # Repair is a postmortem-grade moment: capture the last seconds of
        # local history BEFORE replacement scrambles the fleet.
        from torchstore_tpu.observability import recorder as obs_recorder

        obs_recorder.record("health", "repair", dead=dead, wedged=wedged)
        obs_recorder.dump_postmortem("repair")
    report = {
        "replaced": [],
        "rereplicated": 0,
        "lost": [],
        "failed": [],
        "wedged": wedged,
    }
    strategy = await handle.controller.get_strategy.call_one()
    # Phase 1: replace EVERY dead volume before any re-replication read —
    # a key whose listed survivor is another dead volume would otherwise
    # abort the whole repair mid-way.
    recoverable_by_vid: dict[str, dict] = {}
    for vid in dead:
        gen = len(handle.repair_meshes)
        mesh = await _spawn(
            1,
            StorageVolume,
            f"ts_{store_name}_volume_repair{gen}",
            strategy,
            env_fn=lambda rank, _vid=vid: {
                **handle.volume_env,
                "TORCHSTORE_TPU_VOLUME_ID": _vid,
            },
        )
        handle.repair_meshes.append(mesh)
        new_ref = mesh.refs[0]
        info = await new_ref.get_id.call_one()
        result = await handle.controller.replace_volume.call_one(
            vid, new_ref, info["hostname"]
        )
        report["replaced"].append(vid)
        report["lost"].extend(result["lost"])
        recoverable_by_vid[vid] = result["recoverable"]
    await c.refresh_volumes()
    # Phase 2: re-replicate, grouped by KEY ("rereplicated" counts keys,
    # matching the report's documentation) with each payload fetched ONCE
    # however many replacements need it — but replicated per volume with
    # the exact slices THAT volume held (different dead volumes may have
    # held different shards of one key). A key whose read fails (e.g. its
    # survivor was itself among the dead) is reported, never aborts the
    # others.
    plan: dict[str, dict[str, Any]] = {}  # key -> {vid: slices | None}
    for vid, recoverable in recoverable_by_vid.items():
        for key, slices in recoverable.items():
            if key in report["lost"]:
                continue  # its last copy died in a later replacement
            plan.setdefault(key, {})[vid] = slices
    for key, by_vid in plan.items():
        try:
            whole_requests = None
            slice_cache: dict = {}
            for vid, slices in by_vid.items():
                if slices is None:
                    if whole_requests is None:
                        value = await c.get(key)
                        whole_requests = LocalClient._value_to_requests(
                            key, value
                        )
                    requests = whole_requests
                else:
                    requests = []
                    for ts in slices:
                        ckey = (ts.offsets, ts.local_shape)
                        arr = slice_cache.get(ckey)
                        if arr is None:
                            arr = await c.get(key, like=ts)
                            slice_cache[ckey] = arr
                        requests.append(
                            Request.from_tensor_slice(key, ts, arr)
                        )
                await c.replicate_to(vid, requests)
            report["rereplicated"] += 1
        except Exception as exc:  # noqa: BLE001 - reported, not fatal
            logger.warning(
                "repair: re-replicating %r onto %s failed: %s",
                key,
                sorted(by_vid),
                exc,
            )
            report["failed"].append(key)
    if dead:
        logger.info(
            "repair(%s): replaced %s, re-replicated %d key(s), lost %s",
            store_name,
            report["replaced"],
            report["rereplicated"],
            report["lost"] or "none",
        )
    return report


async def prewarm(
    state_dict_or_manifest: Any,
    store_name: str = DEFAULT_STORE,
    transfer_dtype=None,
    direct: bool = False,
    acquire_key: Optional[str] = None,
) -> dict:
    """Cold-start provisioning: size and warm every layer the first sync of
    this working set will touch, BEFORE the first byte moves.

    Accepts a state dict (nested; jax/numpy/torch/ShapeDtypeStruct leaves —
    only metadata is read, no device->host copies) or a prebuilt
    :class:`~torchstore_tpu.provision.StateDictManifest`. The planner fans
    the manifest out over the strategy's put volumes (replication included),
    reserves tmpfs capacity through the controller (concurrent prewarms
    can't oversubscribe /dev/shm), then provisions per transport rung:
    SHM volumes pre-create hugepage-advised, prefaulted pool segments; bulk
    volumes pre-dial the promoted connection (+ stripe set for payloads
    above the striping threshold); device-resident working sets start the
    ICI transfer server.

    ``direct=True`` additionally pre-creates the client-local staging
    segments a direct-source ``register`` will draw. ``acquire_key`` (with
    the state dict as the ACQUIRE targets) precomputes the direct-dest
    transfer plan for an already-published direct key: plan build, source
    dials, and same-host segment attaches all happen now, so iteration 0 of
    ``get_state_dict(direct=True)`` / ``WeightSubscriber.acquire`` starts at
    the data movement.

    ADVISORY by contract: prewarm never raises and never fails the
    subsequent sync — stage failures are logged, counted in
    ``ts_prewarm_errors_total``, reported in the returned dict, and the
    lazy path serves exactly as before. Returns the provisioning report
    (``segments``, ``bytes``, ``dials``, ``granted_bytes``, ``errors``,
    ...)."""
    from torchstore_tpu import provision

    def _advisory_failure(stage: str, exc: Exception) -> dict:
        logger.warning(
            "prewarm %s failed: %s; lazy path will serve", stage, exc
        )
        obs_metrics.counter(
            "ts_prewarm_errors_total",
            "Prewarm stage failures (lazy path proceeded)",
        ).inc(stage=stage)
        return {"ok": False, "errors": {stage: str(exc)}}

    try:
        c = client(store_name)
    except Exception as exc:  # noqa: BLE001 - advisory, never raises
        return _advisory_failure("client", exc)
    if acquire_key is not None:
        from torchstore_tpu import state_dict_utils

        try:
            return await state_dict_utils.preplan_direct(
                c, acquire_key, state_dict_or_manifest
            )
        except Exception as exc:  # noqa: BLE001 - advisory
            return _advisory_failure("preplan", exc)
    try:
        arrays = None
        if isinstance(state_dict_or_manifest, provision.StateDictManifest):
            manifest = state_dict_or_manifest
        else:
            # ONE flatten serves both the manifest and the registration
            # scan (flattening an already-flat dict is a shallow pass).
            import numpy as _np

            from torchstore_tpu.state_dict_utils import flatten_state_dict

            flat, _ = flatten_state_dict(state_dict_or_manifest)
            manifest = provision.StateDictManifest.from_state_dict(
                flat, transfer_dtype=transfer_dtype
            )
            if transfer_dtype is None:
                # Real source buffers in hand: feed the bulk registration
                # cache too (numpy leaves only; a transfer-dtype cast
                # produces fresh arrays at put time, which the put
                # registers itself).
                arrays = [
                    v for v in flat.values() if isinstance(v, _np.ndarray)
                ]
    except Exception as exc:  # noqa: BLE001 - manifest derivation is
        # advisory too (e.g. flatten's duplicate-key ValueError): the sync
        # itself will surface real problems loudly.
        return _advisory_failure("manifest", exc)
    return await provision.prewarm_manifest(
        c, manifest, direct=direct, arrays=arrays
    )


def metrics_snapshot() -> dict:
    """This process's observability registry: every counter/gauge/histogram
    the store's layers emit (client ops, per-transport bytes, SHM pool
    economics, ...), as ``{name: {"kind", "help", "series": [...]}}`` —
    JSON-serializable. Metrics are PROCESS-LOCAL (Prometheus client-library
    semantics): volume and controller processes expose their registries
    through their ``stats()`` endpoints
    (``controller.stats.call_one(include_volumes=True)`` collects the whole
    fleet), and ``TORCHSTORE_TPU_METRICS_DUMP=/path`` makes every process
    periodically write its own dump. For the MERGED fleet view, see
    :func:`fleet_snapshot`."""
    return obs_metrics.metrics_snapshot()


async def fleet_snapshot(
    store_name: str = DEFAULT_STORE, render: Optional[str] = None
) -> Any:
    """One merged, process-labeled registry for the whole store fleet.

    Scrapes the controller's registry and — through the controller's
    ``stats(include_volumes=True)`` fan-out — every live volume's, merges
    them with this process's own (the client), and labels every series with
    ``process="client" | "controller" | "volume"`` (volumes additionally
    carry ``volume_id``; pre-existing colliding labels are preserved under
    an ``exported_`` prefix). Unreachable volumes land in ``errors`` instead
    of failing the scrape (heartbeat tolerance), and kind conflicts are
    dropped into ``conflicts`` rather than corrupting the document.

    Returns ``{"ts", "scraper_pid", "processes", "errors", "conflicts",
    "hot_keys", "metrics"}`` (JSON-serializable; ``hot_keys`` maps
    ``client``/volume ids to their rolling top-K keys by bytes).
    ``render="prometheus"`` returns one Prometheus-text document instead;
    ``render="json"`` a JSON string."""
    from torchstore_tpu.observability import aggregate, profile
    from torchstore_tpu.observability import ledger as obs_ledger

    c = client(store_name)
    stats = await c.controller.stats.call_one(include_volumes=True)
    entries: list[tuple[dict, dict]] = [
        ({"process": "client"}, obs_metrics.metrics_snapshot()),
        ({"process": "controller"}, stats.get("metrics") or {}),
    ]
    errors: dict[str, str] = {}
    hot: dict[str, list] = {"client": profile.hot_keys(10)}
    one_sided_hot = profile.hot_keys(10, source="one_sided")
    if one_sided_hot:
        # The labeled zero-RPC view: bytes these keys moved never touched
        # any volume, so no volume's hot_keys can account for them.
        hot["client:one_sided"] = one_sided_hot
    ledgers: dict[str, dict] = {"client": obs_ledger.snapshot()}
    for vid, vstats in sorted((stats.get("volumes") or {}).items()):
        if "metrics" not in vstats:
            errors[vid] = str(vstats.get("error", "no metrics in stats()"))
            continue
        entries.append(
            ({"process": "volume", "volume_id": vid}, vstats["metrics"])
        )
        if vstats.get("hot_keys"):
            hot[f"volume:{vid}"] = vstats["hot_keys"]
        if vstats.get("ledger"):
            ledgers[f"volume:{vid}"] = vstats["ledger"]
    doc = aggregate.fleet_doc(
        entries, errors=errors, hot_keys=hot, ledgers=ledgers
    )
    if render == "prometheus":
        return aggregate.render_prometheus(doc["metrics"])
    if render == "json":
        return aggregate.render_json(doc)
    return doc


async def traffic_matrix(store_name: str = DEFAULT_STORE) -> dict:
    """Fleet traffic matrix — the placement solver's input (ROADMAP item
    5) and the O(1)-egress measurement for broadcast trees (item 1).

    Scrapes every process's traffic ledger (``fleet_snapshot`` under the
    hood) and folds the cells into ``{"edges": {src_host: {dst_host:
    {"bytes", "ops"}}}, "egress": {host: bytes}, "ingress": {host: bytes},
    "volumes": {vid: {"bytes_in", "bytes_out"}}, "unattributed": ...,
    "keys": {process: top-K rolling-window keys}}``. Every transfer is
    counted exactly once, at the side that can attribute both endpoints
    (see observability/ledger.py)."""
    from torchstore_tpu.observability import ledger as obs_ledger

    doc = await fleet_snapshot(store_name)
    ledgers = doc.get("ledgers") or {}
    matrix = obs_ledger.traffic_matrix(ledgers)
    matrix["keys"] = {
        label: snap.get("keys", []) for label, snap in ledgers.items()
    }
    return matrix


async def flight_record(store_name: Optional[str] = DEFAULT_STORE) -> dict:
    """The merged fleet flight-recorder timeline: this process's ring plus
    the controller's and every reachable volume's, time-sorted — the
    on-demand post-mortem (``store_name=None`` returns the local ring
    only). Unreachable processes land in ``errors`` instead of failing
    the merge. See observability/recorder.py for what gets recorded and
    which faults auto-dump."""
    from torchstore_tpu.observability import recorder as obs_recorder

    events = [
        {**event, "process": "client"}
        for event in obs_recorder.snapshot()
    ]
    errors: dict[str, str] = {}
    if store_name is not None:
        try:
            c = client(store_name)
            await c._ensure_setup()
        except Exception as exc:  # noqa: BLE001 - local ring still serves
            errors["fleet"] = f"{type(exc).__name__}: {exc}"
        else:
            try:
                for event in await c.controller.flight_record.call_one():
                    events.append({**event, "process": "controller"})
            except Exception as exc:  # noqa: BLE001 - dead controller
                errors["controller"] = f"{type(exc).__name__}: {exc}"
            for vid in sorted(c._volume_refs or {}):
                try:
                    remote = await c._volume_refs[
                        vid
                    ].actor.flight_record.call_one()
                except Exception as exc:  # noqa: BLE001 - dead volume
                    errors[f"volume:{vid}"] = f"{type(exc).__name__}: {exc}"
                    continue
                for event in remote:
                    events.append({**event, "process": f"volume:{vid}"})
    events.sort(key=lambda e: e.get("ts") or 0)
    return {"events": events, "errors": errors}


async def history(
    series: Optional[Any] = None,
    since: Optional[float] = None,
    store_name: Optional[str] = DEFAULT_STORE,
) -> dict:
    """Fleet time-series history: every process's retained metric rings.

    Each torchstore process samples its own registry into bounded
    multi-resolution rings (observability/history.py). This collects
    them — this client's, the controller's, and every reachable
    volume's, riding the ``stats()`` endpoints the way ledgers and
    hot_keys do — without merging (label-identical series from different
    processes are different series; ``observability.history.merge_points``
    folds them when a consumer wants fleet totals).

    ``series`` is a glob or list of globs over series ids
    (``name{k="v"}``; a bare name also matches its labeled variants);
    ``since`` is a lookback in seconds (default 300) or an absolute wall
    timestamp. ``store_name=None`` returns the local view only.

    Returns ``{"generated_ts", "processes": {"client" | "controller" |
    "volume:<vid>": <SeriesStore.query() doc>}, "errors": {...}}`` —
    unreachable processes land in ``errors``, never fail the scrape."""
    from torchstore_tpu.observability import history as obs_history

    request = {"series": series, "since": since}
    doc: dict = {
        "generated_ts": time.time(),
        "processes": {
            "client": obs_history.history(series=series, since=since)
        },
        "errors": {},
    }
    if store_name is None:
        return doc
    try:
        c = client(store_name)
        await c._ensure_setup()
    except Exception as exc:  # noqa: BLE001 - no fleet: local view serves
        doc["errors"]["fleet"] = f"{type(exc).__name__}: {exc}"
        return doc
    try:
        stats = await c.controller.stats.call_one(history=request)
        if stats.get("history"):
            doc["processes"]["controller"] = stats["history"]
    except Exception as exc:  # noqa: BLE001 - dead controller
        doc["errors"]["controller"] = f"{type(exc).__name__}: {exc}"[:200]

    async def scrape(vid: str) -> None:
        try:
            st = await c._volume_refs[vid].actor.stats.call_one(
                history=request
            )
        except Exception as exc:  # noqa: BLE001 - dead volume: report it
            doc["errors"][f"volume:{vid}"] = f"{type(exc).__name__}: {exc}"[:200]
            return
        if st.get("history"):
            doc["processes"][f"volume:{vid}"] = st["history"]

    await asyncio.gather(*(scrape(vid) for vid in sorted(c._volume_refs or {})))
    return doc


async def sync_timeline(
    key: str, store_name: str = DEFAULT_STORE
) -> Optional[dict]:
    """One weight-sync generation's reconstructed lifecycle: stream begin
    -> per-key watermark landings -> seal -> per-subscriber acquire
    completions, with publish-window / first-layer / completion-lag
    figures (observability.timeline.reconstruct). None when ``key`` was
    never streamed (or its record was evicted)."""
    from torchstore_tpu.observability import timeline as obs_timeline

    state = await client(store_name).stream_state(key)
    return obs_timeline.reconstruct(state)


async def slo_report(store_name: Optional[str] = DEFAULT_STORE) -> dict:
    """The live SLO scoreboard: every configured ``TORCHSTORE_TPU_SLO_*``
    threshold with its current value, violation count, violated flag, and
    — per violated SLO — the dominant stage (plan / transport / landing /
    stamp_verify / watermark_wait / notify) with the full per-stage
    wall-time breakdown, so "p99 blew the budget" comes with "and THIS
    stage ate it".

    With a ``store_name`` (default store when omitted) the report also
    carries fleet ``overload`` signals — per-volume inflight landings,
    resident doorbell plans, rolling-window transfer totals, each
    volume's OWN per-stage digests (its landing bracket / serve legs:
    read these next to the client's dominant stage — a client
    "transport" verdict whose wall time is rivaled by a volume's
    "landing" row means the landing pool, not the wire, is the stall),
    and this client's per-shard metadata-RPC inflight — the inputs
    admission control (ROADMAP item 3) consumes. ``store_name=None``
    returns the process-local scoreboard only (what loadgen drivers ship
    home; see ``loadgen.report.merge_slo_reports`` for the fleet fold).

    Returns ``{"slos": {name: {"env", "threshold", "current",
    "violations", "violated", "op", "dominant_stage"?, "stages"?}},
    "stages": {op: {stage: {...}}}, "overload": {"volumes": {vid: {...}},
    "metadata_rpc_inflight": {...}, "errors": {...}}, "generated_ts"}``."""
    from torchstore_tpu.observability import timeline as obs_timeline

    report = obs_timeline.slo_report()
    if store_name is None:
        return report
    overload: dict = {
        "volumes": {},
        "metadata_rpc_inflight": {},
        "errors": {},
    }
    report["overload"] = overload
    try:
        c = client(store_name)
        await c._ensure_setup()
    except Exception as exc:  # noqa: BLE001 - no fleet: local view serves
        overload["errors"]["fleet"] = f"{type(exc).__name__}: {exc}"
        return report
    snapshot_fn = getattr(c.controller, "inflight_snapshot", None)
    if snapshot_fn is not None:
        overload["metadata_rpc_inflight"] = snapshot_fn()

    async def scrape(vid: str) -> None:
        try:
            st = await c._volume_refs[vid].actor.stats.call_one()
        except Exception as exc:  # noqa: BLE001 - dead volume: report it
            overload["errors"][vid] = f"{type(exc).__name__}: {exc}"[:200]
            return
        entry = dict(st.get("overload") or {})
        window = (st.get("ledger") or {}).get("window") or {}
        entry["window_ops"] = window.get("ops", 0)
        entry["window_bytes"] = window.get("bytes", 0)
        # The volume's OWN per-stage digests ride the report next to the
        # client-side attribution. They are NOT summed into the client's
        # stage table: the client's "transport" span CONTAINS the
        # volume's "landing" bracket (nested wall time — summing would
        # double-count and can never flip the vote), so a wedged landing
        # pool is diagnosed by reading the volume rows — e.g. put.landing
        # p99 here rivaling the client's put.transport p99.
        if st.get("stages"):
            entry["stages"] = st["stages"]
        if st.get("trends"):
            entry["trends"] = st["trends"]
        overload["volumes"][vid] = entry

    await asyncio.gather(*(scrape(vid) for vid in sorted(c._volume_refs or {})))
    # Active volume-side trends surface at top level next to the client's
    # own (report["trends"], from timeline.slo_report) so "which process
    # is in a regime change" needs no drill-down: keys are
    # volume:<vid>:<detector>.
    trends = report.setdefault("trends", {})
    for vid, entry in overload["volumes"].items():
        for name, result in (entry.get("trends") or {}).items():
            if result.get("active"):
                trends[f"volume:{vid}:{name}"] = result
    return report


async def inject_fault(
    name: str,
    action: str,
    count: Optional[int] = None,
    prob: Optional[float] = None,
    delay_ms: Optional[float] = None,
    scope: str = "volumes",
    store_name: str = DEFAULT_STORE,
) -> dict:
    """Arm a deterministic faultpoint across the fleet (test/chaos control
    plane; see ``torchstore_tpu/faults.py`` for sites and actions).

    ``scope``: ``"client"`` (this process), ``"controller"``, ``"volumes"``
    (every volume), ``"shards"`` (every controller shard) or
    ``"shard:<i>"`` (one of them, by index), a specific volume id, or
    ``"all"``. Arming rides the ``inject_fault`` control RPC, so it
    reaches ALREADY-RUNNING forked actor processes — the capability the
    old monkeypatch-per-test idiom never had. Returns
    ``{target: armed spec}``."""
    from torchstore_tpu import faults

    c = client(store_name)
    await c._ensure_setup()
    kwargs = {"count": count, "prob": prob, "delay_ms": delay_ms}
    out: dict[str, dict] = {}
    if scope in ("client", "all"):
        out["client"] = faults.arm(name, action, **kwargs)
    if scope in ("controller", "all"):
        out["controller"] = await c.controller.inject_fault.call_one(
            name, action, **kwargs
        )
    shard_refs = c.controller.shard_refs
    if scope in ("shards", "all"):
        for i, ref in enumerate(shard_refs):
            out[f"shard:{i}"] = await ref.inject_fault.call_one(
                name, action, **kwargs
            )
    elif scope.startswith("shard:"):
        try:
            i = int(scope.split(":", 1)[1])
            ref = shard_refs[i]
        except (ValueError, IndexError):
            raise ValueError(
                f"unknown fault scope {scope!r}: this store has "
                f"{len(shard_refs)} controller shard(s)"
            ) from None
        out[f"shard:{i}"] = await ref.inject_fault.call_one(
            name, action, **kwargs
        )
    if scope in ("volumes", "all"):
        targets = list(c._volume_refs)
    elif scope in c._volume_refs:
        targets = [scope]
    elif scope in ("client", "controller", "shards") or scope.startswith(
        "shard:"
    ):
        targets = []
    else:
        raise ValueError(
            f"unknown fault scope {scope!r}; expected 'client', "
            f"'controller', 'volumes', 'shards', 'shard:<i>', 'all', or a "
            f"volume id ({sorted(c._volume_refs)})"
        )
    for vid in targets:
        out[f"volume:{vid}"] = await c._volume_refs[
            vid
        ].actor.inject_fault.call_one(name, action, **kwargs)
    return out


async def clear_faults(
    name: Optional[str] = None, store_name: str = DEFAULT_STORE
) -> int:
    """Disarm ``name`` (or ALL faultpoints when None) in every reachable
    fleet process; returns how many armed specs were dropped. Unreachable
    processes are skipped — a volume a test killed cannot answer."""
    from torchstore_tpu import faults

    cleared = faults.disarm(name)
    try:
        c = client(store_name)
        await c._ensure_setup()
    except Exception:  # noqa: BLE001 - no fleet: local disarm is all there is
        return cleared
    try:
        cleared += await c.controller.clear_faults.call_one(name)
    except Exception:  # noqa: BLE001 - best-effort cleanup
        pass
    for ref in list(c.controller.shard_refs):
        try:
            cleared += await ref.clear_faults.call_one(name)
        except Exception:  # noqa: BLE001 - a killed shard can't disarm
            pass
    for vid in list(c._volume_refs):
        try:
            cleared += await c._volume_refs[vid].actor.clear_faults.call_one(
                name
            )
        except Exception:  # noqa: BLE001 - dead volumes can't disarm
            pass
    return cleared


async def relay_topology(store_name: str = DEFAULT_STORE) -> dict:
    """The current broadcast relay topology, per channel: members (with
    subscriber refcounts), topology epoch, configured fanout, and every
    live run's tree + per-member landed progress — the operator view of
    the fan-out shape (each re-parenting decision is additionally recorded
    in the flight recorder as a ``health`` event). See
    torchstore_tpu/relay.py."""
    c = client(store_name)
    await c._ensure_setup()
    return await c.controller.relay_topology.call_one()


async def volume_health(store_name: str = DEFAULT_STORE) -> dict:
    """The health supervisor's per-volume view:
    ``{volume_id: {"state": "ok"|"probation"|"quarantined", "misses",
    "oks"}}``."""
    c = client(store_name)
    await c._ensure_setup()
    return await c.controller.volume_health.call_one()


async def version_catalog(
    channel: Optional[str] = None, store_name: str = DEFAULT_STORE
) -> dict:
    """Per-channel version inventory (torchstore_tpu/tiering/): for every
    ``{channel}/v{n}`` group the store holds — keys, logical bytes, replica
    volumes, tier split (resident vs spilled-to-disk), and the live cohort
    leases pinning it. The operator's answer to "which cohort is holding
    which version where, and what is it costing"."""
    return await client(store_name).version_catalog(channel)


async def lease_acquire(
    cohort: str,
    channel: str,
    version: int,
    ttl_s: Optional[float] = None,
    store_name: str = DEFAULT_STORE,
) -> dict:
    """Pin ``(channel, version)`` for ``cohort``: the version is exempt
    from the publisher's GC (and the controller refuses deletes under it)
    and from the spill tier's demotion while the lease lives. TTL'd
    (default ``TORCHSTORE_TPU_LEASE_TTL_S``) — renew to keep. Returns the
    lease description; pass its ``lease_id`` to renew/release.
    ``WeightSubscriber.acquire(version=...)`` manages a read-scoped lease
    for you; use this directly for long-lived cohort pins."""
    return await client(store_name).lease_acquire(
        cohort, channel, version, ttl_s
    )


async def lease_renew(
    lease_id: str,
    ttl_s: Optional[float] = None,
    store_name: str = DEFAULT_STORE,
) -> dict:
    """Extend a live lease; raises KeyError when it already expired (the
    cohort must re-acquire and re-validate the version still exists)."""
    return await client(store_name).lease_renew(lease_id, ttl_s)


async def lease_release(
    lease_id: str, store_name: str = DEFAULT_STORE
) -> bool:
    """Drop a lease (idempotent). The version becomes GC- and
    spill-eligible again once its LAST lease is gone."""
    return await client(store_name).lease_release(lease_id)


async def lease_list(
    channel: Optional[str] = None, store_name: str = DEFAULT_STORE
) -> dict:
    """Live pins as ``{channel: {version: [cohort, ...]}}``."""
    return await client(store_name).lease_list(channel)


async def tier_sweep(store_name: str = DEFAULT_STORE) -> dict:
    """Run one spill pass across the fleet NOW (instead of waiting for the
    background ``TORCHSTORE_TPU_TIER_SWEEP_INTERVAL_S`` cadence); returns
    per-volume ``{spilled, fault_ins, resident_bytes, spilled_bytes}``
    summaries. A no-op reporting ``enabled: False`` per volume when
    ``TORCHSTORE_TPU_TIER_ENABLED`` is unset."""
    return await client(store_name).tier_sweep()


async def _control_signals(
    store_name: str,
) -> tuple[Optional[dict], Optional[dict]]:
    """Fleet-wide signals only a client can fully assemble — the traffic
    matrix (every process's ledger) and the SLO overload view — shipped to
    the controller's policy engine alongside its own volume scrape. Either
    half degrades to None on scrape failure: the engine solves on what it
    has rather than refusing to plan."""
    traffic = overload = None
    try:
        traffic = await traffic_matrix(store_name)
    except Exception as exc:  # noqa: BLE001 - partial signals still solve
        logger.warning("control signals: traffic matrix scrape failed: %s", exc)
    try:
        overload = (await slo_report(store_name)).get("overload")
    except Exception as exc:  # noqa: BLE001 - partial signals still solve
        logger.warning("control signals: slo report scrape failed: %s", exc)
    return traffic, overload


async def control_plan(store_name: str = DEFAULT_STORE) -> dict:
    """Dry run of the placement policy engine: assemble the same telemetry
    snapshot a reconcile round would (fleet traffic matrix + SLO overload
    signals + per-volume stats), run the pure solver, and return the
    actions it WOULD take — applying nothing, recording nothing. The
    inspection surface for "what does the control plane think right now":
    ``{"actions": [{kind, subject, reason, ...}], "snapshot": {...}}``."""
    c = client(store_name)
    await c._ensure_setup()
    traffic, overload = await _control_signals(store_name)
    return await c.controller.control_plan.call_one(
        traffic=traffic, overload=overload
    )


async def rebalance(
    store_name: str = DEFAULT_STORE, shards: Optional[int] = None
) -> dict:
    """Manual control-plane trigger.

    Without ``shards``: run ONE reconcile round now — snapshot, solve,
    apply, audit — and return ``{"actions": [...], "applied": N}``. Safe
    alongside the periodic loop (``TORCHSTORE_TPU_CONTROL_INTERVAL_S``):
    per-subject cooldowns keep back-to-back rounds from thrashing.

    With ``shards=N``: elastically reshard the metadata plane at runtime —
    spawn a new ControllerShard mesh (N==1 merges back onto the
    coordinator), freeze-export-replay the whole index onto it, bump the
    placement epoch, retire the old mesh. Zero lost keys, zero failed
    client ops: in-flight mutations park during the swap and stale-topology
    errors are retried by the metadata router after a topology reload.
    Must run in the process that initialized the store (it owns actor
    spawning). Returns the controller's reshard summary
    ``{"shards", "was", "keys", "reindexed", "epoch"}``."""
    c = client(store_name)
    await c._ensure_setup()
    if shards is None:
        traffic, overload = await _control_signals(store_name)
        return await c.controller.control_reconcile.call_one(
            traffic=traffic, overload=overload
        )
    shards = int(shards)
    if shards < 1:
        raise ValueError(f"rebalance(shards={shards}): need >= 1")
    handle = _stores.get(store_name)
    if handle is None:
        raise RuntimeError(
            "rebalance(shards=N) spawns controller-shard actors and must "
            f"run in the process that initialized store {store_name!r}"
        )
    new_mesh = None
    if shards > 1:
        from torchstore_tpu.metadata.shards import ControllerShard

        generation = len(handle.retired_shard_meshes or ()) + 1
        new_mesh = await spawn_actors(
            shards,
            ControllerShard,
            f"ts_{store_name}_ctrlshard_g{generation}",
        )
    try:
        result = await handle.controller.reshard.call_one(
            handle.controller, new_mesh.refs if new_mesh is not None else []
        )
    except BaseException:
        # The old authority thawed controller-side; don't leak the new mesh.
        if new_mesh is not None:
            await new_mesh.stop()
        raise
    # Old shards are retired (they still drain scheduled reclaims); their
    # processes stop with the store.
    if handle.shard_mesh is not None:
        if handle.retired_shard_meshes is None:
            handle.retired_shard_meshes = []
        handle.retired_shard_meshes.append(handle.shard_mesh)
    handle.shard_mesh = new_mesh
    # Re-route this client onto the new mesh immediately (other clients
    # recover through the stale-topology retry + epoch confirmation).
    await c.controller.load_topology()
    return result


async def autoscale_plan(store_name: str = DEFAULT_STORE) -> dict:
    """Dry run of the elastic-fleet policy engine: assemble the autoscale
    telemetry snapshot (fleet traffic + SLO overload + per-volume stats
    with spilled-key counts), run the pure solver, and return the actions
    it WOULD take — applying nothing, recording nothing, not even
    advancing the idle-round hysteresis counter. Returns ``{"actions":
    [{kind, subject, reason, ...}], "snapshot": {...}, "fleet": {...}}``."""
    c = client(store_name)
    await c._ensure_setup()
    traffic, overload = await _control_signals(store_name)
    return await c.controller.autoscale_plan.call_one(
        traffic=traffic, overload=overload
    )


async def autoscale(store_name: str = DEFAULT_STORE) -> dict:
    """Run ONE autoscale round now — snapshot, solve, apply, audit — and
    execute any deferred ``scale_out`` actions by actually spawning fresh
    volume actors (actor spawning is client-side, so the controller defers
    spawns exactly like ``rebalance(shards=N)`` defers resharding).

    Drain / retire / blob-demote actions apply controller-side inside the
    round. Scale-out spawns happen HERE, in the process that initialized
    the store: each new volume gets a unique forced volume id, the store's
    base volume env (plus ``volume_env_fn`` overrides at a fresh rank),
    and is attached through ``controller.attach_volume`` — then one
    control-plane reconcile runs so hot-key splits can seed placement onto
    the new capacity immediately. Retired autoscale-spawned volumes have
    their actor processes stopped (fixed fleet volumes retire from the
    placement maps but their processes stop with the store).

    Safe alongside the periodic loop
    (``TORCHSTORE_TPU_AUTOSCALE_INTERVAL_S``): per-subject cooldowns and
    reversal damping keep back-to-back rounds from thrashing. Returns the
    round report with ``spawned``/``stopped`` volume-id lists merged in."""
    c = client(store_name)
    await c._ensure_setup()
    traffic, overload = await _control_signals(store_name)
    result = await c.controller.autoscale_reconcile.call_one(
        traffic=traffic, overload=overload
    )
    handle = _stores.get(store_name)
    actions = result.get("actions", [])
    wants = sum(
        int(a.get("count") or 1)
        for a in actions
        if a.get("kind") == "scale_out"
        and str(a.get("outcome", "")).startswith("deferred")
    )
    spawned: list[str] = []
    stopped: list[str] = []
    if wants:
        if handle is None or not handle.owner:
            # Only the initializing process owns actor spawning; other
            # processes surface the deferral for it to pick up.
            result["spawn_deferred"] = wants
        else:
            spawned = await _autoscale_spawn(store_name, handle, wants)
            if spawned:
                # Seed placement onto the new capacity immediately: one
                # control round can split hot keys / rebalance replicas
                # instead of waiting for the next interval.
                try:
                    await c.controller.control_reconcile.call_one(
                        traffic=traffic, overload=overload
                    )
                except Exception as exc:  # noqa: BLE001 - placement seeding
                    # is best-effort; the periodic loop converges anyway
                    logger.warning(
                        "autoscale: placement seeding reconcile failed: %s",
                        exc,
                    )
            await c.refresh_volumes()
    retired = {
        str(a.get("subject"))
        for a in actions
        if a.get("kind") == "retire_volume"
        and str(a.get("outcome", "")).startswith("applied")
    }
    if (
        handle is not None
        and handle.owner
        and any(rec["mesh"] is not None for rec in handle.autoscale_meshes or [])
    ):
        # Reclaim the processes of autoscale-spawned volumes no longer
        # attached to the fleet — THIS is what makes scale-in save
        # volume-seconds. Reconciling against the controller's live
        # volume map (not just this round's retire actions) also sweeps
        # volumes the periodic loop retired between manual rounds, whose
        # processes would otherwise idle until shutdown.
        attached = set(await c.controller.get_volume_map.call_one())
        for rec in handle.autoscale_meshes:
            if rec["mesh"] is not None and rec["vid"] not in attached:
                await rec["mesh"].stop()
                rec["mesh"] = None
                stopped.append(rec["vid"])
    if retired or stopped:
        await c.refresh_volumes()
    result["spawned"] = spawned
    result["stopped"] = stopped
    return result


async def _autoscale_spawn(
    store_name: str, handle: _StoreHandle, count: int
) -> list[str]:
    """Spawn ``count`` fresh storage volumes and attach them to the live
    fleet (the actuator half of a ``scale_out`` decision). Each spawn
    crosses the ``autoscale.spawn`` faultpoint; a failed spawn stops the
    batch and reports what DID attach rather than raising away the round."""
    from torchstore_tpu import faults

    strategy = await handle.controller.get_strategy.call_one()
    if handle.autoscale_meshes is None:
        handle.autoscale_meshes = []
    spawned: list[str] = []
    for _ in range(count):
        gen = len(handle.autoscale_meshes)
        vid = f"scale-{gen}"
        try:
            await faults.afire("autoscale.spawn")
            mesh = await spawn_actors(
                1,
                StorageVolume,
                f"ts_{store_name}_volume_{vid}",
                strategy,
                env_fn=lambda rank, _vid=vid, _gen=gen: {
                    **handle.volume_env,
                    **(
                        (handle.volume_env_fn(_gen) or {})
                        if handle.volume_env_fn
                        else {}
                    ),
                    "TORCHSTORE_TPU_VOLUME_ID": _vid,
                },
            )
        except Exception as exc:  # noqa: BLE001 - partial scale-out is
            # still progress; the next round retries the remainder
            logger.warning("autoscale: spawning %s failed: %s", vid, exc)
            break
        handle.autoscale_meshes.append({"vid": vid, "mesh": mesh})
        new_ref = mesh.refs[0]
        try:
            info = await new_ref.get_id.call_one()
            await handle.controller.attach_volume.call_one(
                vid, new_ref, info["hostname"]
            )
        except Exception as exc:  # noqa: BLE001 - an unattachable volume
            # must not leak its process
            logger.warning("autoscale: attaching %s failed: %s", vid, exc)
            await mesh.stop()
            handle.autoscale_meshes[-1]["mesh"] = None
            break
        spawned.append(vid)
    if spawned:
        logger.info(
            "autoscale(%s): spawned + attached %s", store_name, spawned
        )
    return spawned


async def blob_checkpoint(store_name: str = DEFAULT_STORE) -> dict:
    """Archive every live volume's committed payloads into the blob cold
    tier and write the durable fleet manifest — the prerequisite for
    scale-to-zero. After this returns, the whole fleet can be killed and a
    fresh one cold-started with ``ts.blob_restore()`` recovering every
    committed generation from the blob tier. Requires
    ``TORCHSTORE_TPU_BLOB_ENABLED=1``. Returns ``{"outcome", "keys",
    "volumes", "errors"}``."""
    c = client(store_name)
    await c._ensure_setup()
    return await c.controller.blob_checkpoint.call_one()


async def blob_restore(store_name: str = DEFAULT_STORE) -> dict:
    """Cold-start restore: read the durable fleet manifest from the blob
    tier, decode each archived object, and land every committed key into
    the (fresh) fleet via the targeted-replication path — byte-for-byte
    the payloads the last ``ts.blob_checkpoint()`` captured. Keys restore
    round-robin across live volumes and are indexed with fresh write
    generations (reclaim tokens stay sound on the new fleet). Failed keys
    are reported, never abort the rest. Returns ``{"restored", "failed",
    "keys", "seconds"}`` and audits the round as an
    ``autoscale/blob_restore`` decision."""
    from torchstore_tpu.observability import recorder as obs_recorder
    from torchstore_tpu.tiering import blob as blob_mod
    from torchstore_tpu.transport.types import Request

    if not blob_mod.enabled():
        raise RuntimeError(
            "blob tier disabled; set TORCHSTORE_TPU_BLOB_ENABLED=1"
        )
    store = blob_mod.BlobStore()
    doc = blob_mod.read_fleet_manifest(store)
    if doc is None:
        raise RuntimeError(
            "no fleet manifest in the blob tier; run ts.blob_checkpoint() "
            "on a live fleet first"
        )
    c = client(store_name)
    await c._ensure_setup()
    vmap = await c.controller.get_volume_map.call_one()
    vids = sorted(
        vid
        for vid, info in vmap.items()
        if info.get("health") not in ("quarantined", "draining")
    )
    if not vids:
        raise RuntimeError("no live volumes to restore onto")
    t0 = time.perf_counter()
    restored: list[str] = []
    failed: list[str] = []
    for i, (key, info) in enumerate(sorted(doc.get("keys", {}).items())):
        try:
            metas, values = blob_mod.BlobTier.decode_entry(
                store.get(info["object"])
            )
            requests = []
            for idx, meta in enumerate(metas):
                val = values[idx]
                if meta.is_object:
                    requests.append(Request(key=key, is_object=True, objects=val))
                elif meta.tensor_slice is not None:
                    requests.append(
                        Request.from_tensor_slice(key, meta.tensor_slice, val)
                    )
                else:
                    requests.append(Request.from_tensor(key, val))
            await c.replicate_to(vids[i % len(vids)], requests)
            restored.append(key)
        except Exception as exc:  # noqa: BLE001 - reported, not fatal
            logger.warning("blob_restore: %r failed: %s", key, exc)
            failed.append(key)
    seconds = time.perf_counter() - t0
    obs_recorder.record(
        "decision",
        "autoscale/blob_restore",
        subject="fleet",
        reason="cold restore from the blob-tier fleet manifest",
        outcome="applied" if not failed else "applied: %d failed" % len(failed),
        restored=len(restored),
        failed=len(failed),
        seconds=round(seconds, 3),
    )
    logger.info(
        "blob_restore(%s): %d key(s) restored, %d failed, %.2fs",
        store_name,
        len(restored),
        len(failed),
        seconds,
    )
    return {
        "restored": len(restored),
        "failed": failed,
        "keys": len(doc.get("keys", {})),
        "seconds": seconds,
    }


def collect_trace(out_path: Optional[str] = None) -> Optional[dict]:
    """Merge every process's Chrome-trace file (``TORCHSTORE_TPU_TRACE``
    base + pid-suffixed siblings) into ONE Perfetto-loadable timeline with
    labeled process tracks and cross-process trace ids. Call after
    ``ts.shutdown()`` so actor processes have flushed their atexit dumps.
    Returns ``{"path", "files", "events", "trace_ids"}`` or None when
    tracing is disabled. Default output: ``<root>.merged<ext>``."""
    from torchstore_tpu.observability import tracing

    return tracing.collect_trace(out_path)


async def barrier(
    name: str, store_name: str = DEFAULT_STORE, timeout: float = 300.0
) -> None:
    """Collective barrier across the SPMD world that initialized this store
    (put-barrier-get is the canonical exchange pattern). Requires
    ``initialize_spmd``."""
    from torchstore_tpu import spmd as spmd_mod

    session = spmd_mod._spmd_sessions.get(store_name)
    if session is None:
        raise RuntimeError(
            f"barrier requires an SPMD-initialized store (none for "
            f"{store_name!r}); call ts.initialize_spmd() first"
        )
    await session.client.barrier(name, session.env.world_size, timeout=timeout)


async def shutdown(store_name: str = DEFAULT_STORE) -> None:
    """Tear down a store. Routes to the SPMD session when one owns this
    store; otherwise, in the initializing process this resets + stops the
    volume/controller actors, elsewhere it only drops local caches
    (/root/reference/torchstore/api.py:100-109)."""
    from torchstore_tpu import spmd as spmd_mod

    if await spmd_mod.shutdown(store_name):
        return
    handle = _stores.pop(store_name, None)
    if handle is None:
        return
    if handle.client is not None:
        from torchstore_tpu import state_dict_utils

        await state_dict_utils.close_direct_caches(handle.client)
    # Cross-host metadata mirrors subscribe per (process, feed root);
    # once the LAST store is gone their feeds are dead — close them so
    # the receiver tasks and local replica segments don't outlive the
    # fleet (they would spin re-subscribing against nothing).
    if not _stores:
        from torchstore_tpu.metadata import mirror as mirror_mod

        mirror_mod.close_mirrors()
    # Release prewarmed-but-undrawn direct staging segments once the LAST
    # store is gone (the pool is process-local and advisory; another live
    # store may have prewarmed it, so a per-store shutdown must not discard
    # its segments — but without this, segments a register() never took
    # would pin tmpfs until process exit).
    if not _stores:
        from torchstore_tpu.provision.pool import local_pool

        local_pool().clear()
    if handle.owner:
        try:
            await handle.controller.teardown.call_one()
        except Exception:
            logger.exception("controller teardown failed")
        if handle.volume_mesh is not None:
            await handle.volume_mesh.stop()
        if handle.shard_mesh is not None:
            await handle.shard_mesh.stop()
        for mesh in handle.retired_shard_meshes or []:
            await mesh.stop()
        for mesh in handle.repair_meshes or []:
            await mesh.stop()
        for rec in handle.autoscale_meshes or []:
            if rec["mesh"] is not None:
                await rec["mesh"].stop()
        if handle.inproc_volume is not None:
            await _stop_colocated_volume(handle.inproc_volume)
        await stop_singleton(f"ts_{store_name}_controller")
        os.environ.pop(ENV_STORE_PREFIX + store_name, None)


__all__ = [
    "DEFAULT_STORE",
    "Shard",
    "autoscale",
    "autoscale_plan",
    "barrier",
    "blob_checkpoint",
    "blob_restore",
    "client",
    "collect_trace",
    "control_plan",
    "delete",
    "delete_batch",
    "delete_prefix",
    "exists",
    "fleet_snapshot",
    "flight_record",
    "get",
    "get_batch",
    "get_state_dict",
    "get_state_dict_streamed",
    "initialize",
    "initialize_spmd",
    "keys",
    "lease_acquire",
    "lease_list",
    "lease_release",
    "lease_renew",
    "metrics_snapshot",
    "prewarm",
    "put",
    "put_batch",
    "direct_staging_buffers",
    "put_state_dict",
    "rebalance",
    "relay_topology",
    "repair",
    "reset_client",
    "shutdown",
    "state_dict_stream",
    "slo_report",
    "sync_timeline",
    "tier_sweep",
    "traffic_matrix",
    "version_catalog",
    "wait_for",
]
