"""Reshard math and small helpers (numpy-only, no jax imports at module scope).

This is the TPU-native equivalent of the reference's ``torchstore/utils.py``
(see /root/reference/torchstore/utils.py:25-307): byte views for bulk
transports, global->local destination-view mapping for in-place writes,
interval intersection of tensor slices, and bounding-box assembly of fetched
parts. All math operates on host ``numpy`` arrays; ``jax.Array`` values are
converted to host views at the client boundary (see ``sharding.py``).
"""

from __future__ import annotations

import os
import socket
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np


@dataclass(frozen=True)
class Box:
    """An axis-aligned region of a global index space: ``offsets`` + ``shape``."""

    offsets: tuple[int, ...]
    shape: tuple[int, ...]

    def __post_init__(self) -> None:
        if len(self.offsets) != len(self.shape):
            raise ValueError(
                f"rank mismatch: offsets={self.offsets} shape={self.shape}"
            )

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    def stops(self) -> tuple[int, ...]:
        return tuple(o + s for o, s in zip(self.offsets, self.shape))

    def contains(self, other: "Box") -> bool:
        return all(
            oo >= so and oo + osz <= so + ssz
            for so, ssz, oo, osz in zip(
                self.offsets, self.shape, other.offsets, other.shape
            )
        )

    def to_index(self) -> tuple[slice, ...]:
        return tuple(slice(o, o + s) for o, s in zip(self.offsets, self.shape))


def intersect_boxes(a: Box, b: Box) -> Optional[Box]:
    """Per-dimension interval intersection; None when disjoint.

    Equivalent role to the reference's ``get_slice_intersection``
    (/root/reference/torchstore/utils.py:248-307), expressed over ``Box``
    regions in global coordinates.
    """
    if a.ndim != b.ndim:
        raise ValueError(f"rank mismatch: {a} vs {b}")
    offsets = []
    shape = []
    for ao, asz, bo, bsz in zip(a.offsets, a.shape, b.offsets, b.shape):
        start = max(ao, bo)
        stop = min(ao + asz, bo + bsz)
        if stop <= start:
            return None
        offsets.append(start)
        shape.append(stop - start)
    return Box(tuple(offsets), tuple(shape))


def subtract_box(base: Box, cut: Box) -> list[Box]:
    """``base`` minus ``cut``: up to 2*ndim disjoint boxes covering every
    element of ``base`` outside ``cut``. Returns ``[base]`` when disjoint,
    ``[]`` when fully covered — the exact-coverage primitive (overlap-safe,
    unlike element-count sums)."""
    inter = intersect_boxes(base, cut)
    if inter is None:
        return [base]
    out: list[Box] = []
    cur_off = list(base.offsets)
    cur_shape = list(base.shape)
    for d in range(base.ndim):
        lo, hi = cur_off[d], cur_off[d] + cur_shape[d]
        ilo = inter.offsets[d]
        ihi = ilo + inter.shape[d]
        if ilo > lo:
            off = list(cur_off)
            shp = list(cur_shape)
            shp[d] = ilo - lo
            out.append(Box(tuple(off), tuple(shp)))
        if ihi < hi:
            off = list(cur_off)
            shp = list(cur_shape)
            off[d] = ihi
            shp[d] = hi - ihi
            out.append(Box(tuple(off), tuple(shp)))
        cur_off[d], cur_shape[d] = ilo, ihi - ilo
    return out


def boxes_cover(region: Box, covers: list[Box]) -> bool:
    """True iff the union of ``covers`` contains every element of
    ``region`` (overlaps and duplicates are fine)."""
    remaining = [region]
    for cut in covers:
        if not remaining:
            return True
        remaining = [r for base in remaining for r in subtract_box(base, cut)]
    return not remaining


def to_byte_view(arr: np.ndarray) -> np.ndarray:
    """Flat uint8 view over a contiguous array (for bulk/byte transports).

    Mirrors the role of the reference's ``to_byte_view``
    (/root/reference/torchstore/utils.py:25-33).
    """
    if not arr.flags["C_CONTIGUOUS"]:
        raise ValueError("to_byte_view requires a C-contiguous array")
    return arr.view(np.uint8).reshape(-1)


def get_destination_view(
    dest: np.ndarray,
    dest_box: Box,
    region: Box,
    require_contiguous: bool = True,
) -> Optional[np.ndarray]:
    """View into ``dest`` (which occupies ``dest_box`` of the global space)
    covering global ``region``; None when the region is not representable as
    a single C-contiguous view and ``require_contiguous`` is set.

    The contiguity requirement exists because byte-oriented transports (SHM,
    bulk TCP, ICI staging) land data into a flat destination buffer — same
    constraint as the reference's RDMA path
    (/root/reference/torchstore/utils.py:36-98).
    """
    if not dest_box.contains(region):
        return None
    rel = tuple(ro - do for ro, do in zip(region.offsets, dest_box.offsets))
    index = tuple(slice(r, r + s) for r, s in zip(rel, region.shape))
    view = dest[index]
    if require_contiguous and view.size > 1 and not view.flags["C_CONTIGUOUS"]:
        return None
    return view


def tensors_overlap_in_memory(dest: np.ndarray, parts: Sequence[np.ndarray]) -> bool:
    """True when every part aliases memory inside ``dest`` (i.e. all parts
    already landed in-place and no assembly copy is needed). Equivalent of
    /root/reference/torchstore/utils.py:101-120."""
    if dest.size == 0:
        return False
    d0, d1 = byte_range(dest)
    for p in parts:
        if p.size == 0:
            continue
        p0, p1 = byte_range(p)
        if p0 < d0 or p1 > d1 or p.base is None:
            return False
    return True


def byte_range(arr: np.ndarray) -> tuple[int, int]:
    """[lo, hi) byte address range touched by ``arr`` under arbitrary
    (including negative) strides."""
    start = arr.__array_interface__["data"][0]
    if arr.size == 0:
        return (start, start)
    lo = start
    hi = start
    for sz, st in zip(arr.shape, arr.strides):
        if sz > 1:
            extent = (sz - 1) * st
            if extent > 0:
                hi += extent
            else:
                lo += extent
    return (lo, hi + arr.itemsize)


def bounding_box(boxes: Sequence[Box]) -> Box:
    if not boxes:
        raise ValueError("bounding_box of no boxes")
    ndim = boxes[0].ndim
    mins = [min(b.offsets[d] for b in boxes) for d in range(ndim)]
    maxs = [max(b.offsets[d] + b.shape[d] for b in boxes) for d in range(ndim)]
    return Box(tuple(mins), tuple(m - n for m, n in zip(maxs, mins)))


def assemble_tensor(
    parts: Sequence[tuple[np.ndarray, tuple[int, ...]]],
) -> tuple[np.ndarray, tuple[int, ...]]:
    """Assemble fetched parts (each with its global offsets) into one array.

    Returns ``(array, offsets)`` where ``offsets`` is the global offset of the
    assembled bounding box (so a full fetch yields offsets == zeros).
    Equivalent of /root/reference/torchstore/utils.py:158-245.
    """
    if not parts:
        raise ValueError("assemble_tensor of no parts")
    dtype = parts[0][0].dtype
    for p, _ in parts:
        if p.dtype != dtype:
            raise ValueError(f"dtype mismatch during assembly: {p.dtype} vs {dtype}")
        if p.ndim != parts[0][0].ndim:
            raise ValueError("rank mismatch during assembly")
    boxes = [Box(tuple(off), tuple(p.shape)) for p, off in parts]
    bbox = bounding_box(boxes)
    if len(parts) == 1 and boxes[0] == bbox:
        return parts[0][0], bbox.offsets
    out = np.empty(bbox.shape, dtype=dtype)
    covered = 0
    for (p, off), box in zip(parts, boxes):
        rel = tuple(o - bo for o, bo in zip(off, bbox.offsets))
        out[tuple(slice(r, r + s) for r, s in zip(rel, p.shape))] = p
        covered += box.size
    if covered < bbox.size:
        raise ValueError(
            f"assembled parts cover {covered} elements but bounding box has "
            f"{bbox.size}; parts do not tile the requested region"
        )
    # A plain size sum double-counts OVERLAPPING parts and can mask an
    # uncovered hole (np.empty garbage served as tensor data). Overlaps only
    # occur in anomalous states (e.g. mixed-layout crash recovery), so the
    # exact check — painting a coverage byte per cell — runs only then.
    if any(
        intersect_boxes(a, b) is not None
        for i, a in enumerate(boxes)
        for b in boxes[i + 1 :]
    ):
        painted = np.zeros(bbox.shape, dtype=np.uint8)
        for (p, off), box in zip(parts, boxes):
            rel = tuple(o - bo for o, bo in zip(off, bbox.offsets))
            painted[tuple(slice(r, r + s) for r, s in zip(rel, p.shape))] = 1
        holes = int(painted.size - int(painted.sum()))
        if holes:
            raise ValueError(
                f"assembled parts overlap yet leave {holes} of {bbox.size} "
                "elements uncovered; parts do not tile the requested region"
            )
    return out, bbox.offsets


async def maybe_await(value):
    """Await ``value`` when it is a coroutine, else return it — lets
    transport hooks be either sync or async."""
    import inspect

    if inspect.iscoroutine(value):
        return await value
    return value


def get_free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def get_hostname() -> str:
    """THE host identity every layer keys on — same-host transport
    selection, volume hostnames, ledger host labels, relay membership.
    ``TORCHSTORE_TPU_HOSTNAME`` overrides it (tests/benches emulating a
    multi-host fleet on one box); keeping every consumer on one source
    means an emulated host is consistently 'remote' everywhere instead of
    same-host for transports but cross-host for traffic attribution."""
    return os.environ.get("TORCHSTORE_TPU_HOSTNAME") or socket.gethostname()


# jax platform names that mean "a real accelerator is attached". On this
# image the TPU is reached through the axon tunnel, whose devices report
# platform 'axon', not 'tpu' — any hardware check that tests only 'tpu'
# silently falls through to CPU/interpret mode (ADVICE r5). Shared by
# bench.py's device section, benchmarks/flash_kernel_bench.py, and
# scripts/tpu_watch.sh's probe.
DEVICE_PLATFORMS = ("tpu", "axon")


def is_device_platform(platform) -> bool:
    """True when a jax ``device.platform`` string names real TPU hardware
    (direct or tunneled) rather than a CPU/interpret fallback."""
    return str(platform).lower() in DEVICE_PLATFORMS


def spawn_logged(coro, *, name: str, tasks: Optional[set] = None, log=None):
    """``asyncio.ensure_future`` with the retention + error contract every
    fire-and-forget task in this codebase must honor (tslint rule
    ``orphan-task``): the task is retained in ``tasks`` until done (asyncio
    holds spawned tasks weakly — an unretained task can be garbage-collected
    mid-flight), and a done-callback RETRIEVES the exception, logs it, and
    increments ``ts_background_task_errors_total{task=name}`` instead of
    letting the failure vanish. Cancellation is not an error."""
    import asyncio

    task = asyncio.ensure_future(coro)
    if tasks is not None:
        tasks.add(task)

    def _done(t: "asyncio.Task") -> None:
        if tasks is not None:
            tasks.discard(t)
        if t.cancelled():
            return
        exc = t.exception()
        if exc is not None:
            from torchstore_tpu.logging import get_logger
            from torchstore_tpu.observability import metrics as obs_metrics

            obs_metrics.counter(
                "ts_background_task_errors_total",
                "Unhandled exceptions from background (fire-and-forget) tasks",
            ).inc(task=name)
            (log or get_logger("torchstore_tpu.tasks")).error(
                "background task %r failed: %r", name, exc, exc_info=exc
            )

    task.add_done_callback(_done)
    return task
