"""Repo-specific static analysis (``tslint``): mechanical enforcement of
the conventions the store's correctness rests on.

Seven AST-based checkers (see ``analysis/checkers/``), a committed baseline
of grandfathered findings (``tslint_baseline.json``), per-line
``# tslint: disable=<rule>`` pragmas, and a CLI (``scripts/tslint.py``)
with human and ``--json`` output plus a ``--fail-on-new`` gate mode wired
into tier-1 via tests/test_static_analysis.py.
"""

from torchstore_tpu.analysis.core import (
    DEFAULT_BASELINE,
    Finding,
    Project,
    RunResult,
    load_baseline,
    run_checks,
    save_baseline,
)

__all__ = [
    "DEFAULT_BASELINE",
    "Finding",
    "Project",
    "RunResult",
    "load_baseline",
    "run_checks",
    "save_baseline",
]
