"""SARIF 2.1.0 serialization for tslint results.

CI code-scanning UIs (GitHub code scanning, most SARIF viewers) ingest one
``sarif-log`` document per run. The mapping is deliberately thin:

- one ``run`` with ``tool.driver.rules`` built from the checker modules'
  docstrings (first line = shortDescription, full docstring = help text),
- one ``result`` per finding, ``level: error`` for NEW findings and
  ``level: note`` + ``baselineState: unchanged`` for baselined ones,
- ``partialFingerprints`` derived from the repo's existing line-independent
  ``(rule, path, message)`` finding identity, so a finding keeps its
  identity across unrelated edits exactly as the committed baseline does.

stdlib-only, like everything under ``analysis/``.
"""

from __future__ import annotations

import hashlib
import sys

from torchstore_tpu.analysis.core import Finding, RunResult

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)

_TOOL_NAME = "tslint"
_INFO_URI = "https://example.invalid/torchstore_tpu/docs/ARCHITECTURE.md"


def _fingerprint(finding: Finding) -> str:
    ident = "|".join(finding.key)
    return hashlib.sha256(ident.encode("utf-8")).hexdigest()


def _rule_docs(checkers: dict) -> dict[str, tuple[str, str]]:
    """rule -> (short, full) help text from each checker module docstring."""
    docs: dict[str, tuple[str, str]] = {}
    for rule, checkfn in checkers.items():
        module = sys.modules.get(getattr(checkfn, "__module__", ""), None)
        doc = (getattr(module, "__doc__", None) or rule).strip()
        short = doc.splitlines()[0].strip()
        docs[rule] = (short, doc)
    return docs


def to_sarif(result: RunResult, checkers: dict) -> dict:
    """One SARIF log for one ``run_checks`` result."""
    docs = _rule_docs({r: checkers[r] for r in result.rules if r in checkers})
    rules_obj = [
        {
            "id": rule,
            "name": rule,
            "shortDescription": {"text": docs.get(rule, (rule, rule))[0]},
            "help": {"text": docs.get(rule, (rule, rule))[1]},
            "defaultConfiguration": {"level": "error"},
        }
        for rule in result.rules
    ]
    rule_index = {rule: i for i, rule in enumerate(result.rules)}
    new_keys = {f.key for f in result.new}

    results_obj = []
    for f in result.findings:
        is_new = f.key in new_keys
        results_obj.append(
            {
                "ruleId": f.rule,
                "ruleIndex": rule_index.get(f.rule, -1),
                "level": "error" if is_new else "note",
                "baselineState": "new" if is_new else "unchanged",
                "message": {"text": f.message},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {
                                "uri": f.path,
                                "uriBaseId": "SRCROOT",
                            },
                            "region": {"startLine": max(1, f.line)},
                        }
                    }
                ],
                "partialFingerprints": {
                    "tslintIdentity/v1": _fingerprint(f),
                },
            }
        )

    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": _TOOL_NAME,
                        "informationUri": _INFO_URI,
                        "rules": rules_obj,
                    }
                },
                "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
                "results": results_obj,
            }
        ],
    }
