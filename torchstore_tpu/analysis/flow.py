"""Intraprocedural CFG + dataflow layer for flow-aware tslint rules.

The syntactic checkers under ``analysis/checkers/`` match single AST nodes;
the ordering disciplines the store actually depends on — seqlock write
brackets that must close on every path, structural index mutations followed
by a placement-epoch bump, no ``await`` inside a stamp bracket — are
properties of PATHS, including the exception paths no single-node match can
see (PR 7's raise-escaping ``_begin_landing`` leaked the inflight count
forever and was caught by a human; this module makes that review
mechanical).

What it builds, per function (sync or async, methods and nested defs
included):

- One :class:`FlowNode` per simple statement and per compound-statement
  header (the ``if``/``while`` test, the ``for`` iterable, the ``with``
  context expression). Nested function/lambda bodies are OPAQUE — they are
  a single definition node in the enclosing CFG and get their own CFG.
- **Normal edges** (``succ``) for fallthrough, branches, and loop
  back-edges, and **exception edges** (``exc``) out of every statement
  that can raise, routed to the innermost enclosing handler dispatch /
  ``finally`` copy / the function's synthetic RAISE exit. The can-raise
  model is deliberately conservative: only ``pass``/``break``/``continue``/
  ``global``/``nonlocal`` are raise-free, so "provable straight-line code"
  between a bracket open and close means *no statement between them at
  all* — anything else needs the close on the exception path too.
- **``finally`` lowering by duplication**: each ``finally`` body is lowered
  once per continuation that traverses it (normal completion, the
  exception path, and each ``return``/``break``/``continue`` that jumps
  through it), so "the close post-dominates the open via ``finally``"
  falls out of plain reachability with no special casing.
- ``except`` handler dispatch is a synthetic node; a handler list with no
  catch-all (bare ``except``, ``Exception``, ``BaseException``) keeps an
  escape edge to the outer handler, and a raise INSIDE a handler routes
  through the ``finally`` copy before escaping.
- **``await`` annotation**: every node records whether it contains an
  ``await`` expression (``async for``/``async with`` headers count), which
  is both the await-atomicity checker's subject and an implicit can-raise
  (CancelledError surfaces at every await).

On top of the graph, generic solvers:

- :func:`escaping_opens` — the bracket lattice: a boolean open/closed state
  propagated over normal + exception edges; reports every open site from
  which the function exit (or the raise exit) is reachable while open.
- :func:`dominated_by` / :func:`post_dominated_by` — must-reach facts over
  NORMAL edges only (an explicit ``raise`` or an escaping exception
  terminates a path without violating post-dominance; exception-path
  completeness is bracket-discipline's job, not epoch/decision flow's).
- :func:`nodes_between` — the nodes on some open→close path, for "no await
  strictly inside the bracket".

Everything here is stdlib-only (``ast``) and read-only over the shared
one-parse :class:`~torchstore_tpu.analysis.core.Project`.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Callable, Iterable, Iterator, Optional

__all__ = [
    "FlowNode",
    "FunctionCFG",
    "build_cfg",
    "iter_cfgs",
    "escaping_opens",
    "dominated_by",
    "post_dominated_by",
    "nodes_between",
    "solve_forward",
]


# Statement types that can never raise. Everything else gets an exception
# edge: even ``x = y`` can NameError, and an await can always deliver
# CancelledError. Conservatism is the point — a bracket is only provably
# closed on the exception path via ``finally`` or an except-all that closes.
_NO_RAISE_STMTS = (ast.Pass, ast.Break, ast.Continue, ast.Global, ast.Nonlocal)


@dataclass
class FlowNode:
    """One CFG node: a simple statement, a compound-statement header, or a
    synthetic entry/exit/raise/dispatch marker."""

    id: int
    kind: str  # "entry" | "exit" | "raise" | "stmt" | "except"
    stmt: Optional[ast.AST] = None
    label: str = ""
    lineno: int = 0
    succ: set = field(default_factory=set)  # normal out-edges (node ids)
    exc: set = field(default_factory=set)  # exception out-edges (node ids)
    has_await: bool = False
    calls: tuple = ()  # ast.Call nodes in this statement (own scope only)

    def render(self) -> str:
        return f"[{self.id}] {self.kind} {self.label} L{self.lineno}"


class FunctionCFG:
    """The per-function graph plus its three synthetic anchors."""

    def __init__(self, func) -> None:
        self.func = func
        self.is_async = isinstance(func, ast.AsyncFunctionDef)
        self.name = func.name
        self.nodes: list[FlowNode] = []
        self.entry_id = self._new("entry").id
        self.exit_id = self._new("exit").id
        self.raise_id = self._new("raise").id

    def _new(
        self,
        kind: str,
        stmt: Optional[ast.AST] = None,
        label: str = "",
        lineno: int = 0,
    ) -> FlowNode:
        node = FlowNode(
            id=len(self.nodes), kind=kind, stmt=stmt, label=label, lineno=lineno
        )
        self.nodes.append(node)
        return node

    @property
    def entry(self) -> FlowNode:
        return self.nodes[self.entry_id]

    @property
    def exit(self) -> FlowNode:
        return self.nodes[self.exit_id]

    @property
    def raise_exit(self) -> FlowNode:
        return self.nodes[self.raise_id]

    def node(self, nid: int) -> FlowNode:
        return self.nodes[nid]

    def stmt_nodes(self) -> Iterator[FlowNode]:
        for n in self.nodes:
            if n.kind == "stmt":
                yield n

    def preds(self, include_exc: bool = True) -> dict[int, set]:
        out: dict[int, set] = {n.id: set() for n in self.nodes}
        for n in self.nodes:
            for s in n.succ:
                out[s].add(n.id)
            if include_exc:
                for s in n.exc:
                    out[s].add(n.id)
        return out

    def render(self) -> str:  # debugging aid, exercised by tests
        lines = []
        for n in self.nodes:
            lines.append(
                f"{n.render()} -> {sorted(n.succ)} exc-> {sorted(n.exc)}"
                + (" AWAIT" if n.has_await else "")
            )
        return "\n".join(lines)


def _own_scope_walk(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk that does not descend into nested function/lambda bodies
    (their statements belong to their own CFG) nor comprehension bodies'
    lambdas; comprehensions themselves stay visible (they run inline)."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(cur))


def _exprs_of_header(stmt: ast.AST) -> list[ast.AST]:
    """The expressions evaluated by a compound statement's HEADER (the part
    that belongs to the header node, body statements excluded)."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter, stmt.target]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.Match):
        return [stmt.subject]
    return [stmt]


def _collect_marks(exprs: Iterable[ast.AST], async_header: bool = False):
    """(has_await, calls) for the given own-scope expressions."""
    has_await = async_header
    calls = []
    for expr in exprs:
        if expr is None:
            continue
        for sub in _own_scope_walk(expr):
            if isinstance(sub, ast.Await):
                has_await = True
            elif isinstance(sub, ast.Call):
                calls.append(sub)
    return has_await, tuple(calls)


@dataclass(frozen=True)
class _Ctx:
    """Lowering context: where exceptions, breaks, continues, and returns
    go from here, and which finally bodies a jump must traverse."""

    exc: int  # node id receiving in-flight exceptions
    brk: Optional[int] = None  # loop exit (post-finally chain target)
    cont: Optional[int] = None  # loop head
    # finally bodies between here and the function exit, innermost first:
    # (finalbody, ctx_for_that_finally). return traverses all of them.
    ret_finallies: tuple = ()
    # finally bodies between here and the innermost loop, innermost first.
    # break/continue traverse these.
    loop_finallies: tuple = ()


class _Lowerer:
    def __init__(self, cfg: FunctionCFG) -> None:
        self.cfg = cfg

    # -- edge helpers ------------------------------------------------------

    def _connect(self, ends: Iterable[int], target: int) -> None:
        for e in ends:
            self.cfg.node(e).succ.add(target)

    def _stmt_node(self, stmt: ast.AST, label: str, ctx: _Ctx) -> FlowNode:
        async_header = isinstance(stmt, (ast.AsyncFor, ast.AsyncWith))
        has_await, calls = _collect_marks(_exprs_of_header(stmt), async_header)
        node = self.cfg._new(
            "stmt", stmt, label, getattr(stmt, "lineno", 0)
        )
        node.has_await = has_await
        node.calls = calls
        if has_await or not isinstance(stmt, _NO_RAISE_STMTS):
            node.exc.add(ctx.exc)
        return node

    # -- jump-through-finally ----------------------------------------------

    def _through_finallies(
        self, finallies: tuple, final_target: int
    ) -> int:
        """Lower a fresh copy of each pending finally body (innermost
        first), chain them, and return the id the JUMP statement should
        edge to. With no pending finallies this is just ``final_target``."""
        target = final_target
        # Build outermost-last: chain inner copy -> outer copy -> target.
        for body, fctx in reversed(finallies):
            entry, ends = self._block(body, fctx)
            self._connect(ends, target)
            target = entry
        return target

    # -- block lowering ----------------------------------------------------

    def _block(self, stmts: list, ctx: _Ctx) -> tuple[int, set]:
        """Lower a statement list. Returns (entry_id, open_ends). The entry
        is a real node id to point edges at; open_ends are node ids whose
        normal successor is the code AFTER this block. An empty block
        lowers to a synthetic pass-through node."""
        if not stmts:
            node = self.cfg._new("stmt", None, "<empty>", 0)
            return node.id, {node.id}
        entry: Optional[int] = None
        ends: set = set()
        prev_ends: Optional[set] = None
        for stmt in stmts:
            s_entry, s_ends = self._stmt(stmt, ctx)
            if entry is None:
                entry = s_entry
            if prev_ends is not None:
                self._connect(prev_ends, s_entry)
            prev_ends = s_ends
            if not s_ends:
                # Terminal statement (return/raise/break/continue): the
                # rest of the block is unreachable but still lowered so
                # its nodes exist (dead-code opens are never flagged —
                # they are unreachable from entry).
                prev_ends = set()
        ends = prev_ends if prev_ends is not None else set()
        return entry, ends

    def _stmt(self, stmt: ast.AST, ctx: _Ctx) -> tuple[int, set]:
        """Lower one statement. Returns (entry_id, open_ends)."""
        cfg = self.cfg
        if isinstance(stmt, ast.If):
            test = self._stmt_node(stmt, "if", ctx)
            b_entry, b_ends = self._block(stmt.body, ctx)
            test.succ.add(b_entry)
            ends = set(b_ends)
            if stmt.orelse:
                o_entry, o_ends = self._block(stmt.orelse, ctx)
                test.succ.add(o_entry)
                ends |= o_ends
            else:
                ends.add(test.id)
            return test.id, ends

        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head = self._stmt_node(
                stmt, "while" if isinstance(stmt, ast.While) else "for", ctx
            )
            after = cfg._new("stmt", None, "<loop-exit>", getattr(stmt, "lineno", 0))
            body_ctx = _Ctx(
                exc=ctx.exc,
                brk=after.id,
                cont=head.id,
                ret_finallies=ctx.ret_finallies,
                loop_finallies=(),
            )
            b_entry, b_ends = self._block(stmt.body, body_ctx)
            head.succ.add(b_entry)
            self._connect(b_ends, head.id)  # back-edge
            if stmt.orelse:
                o_entry, o_ends = self._block(stmt.orelse, ctx)
                head.succ.add(o_entry)
                self._connect(o_ends, after.id)
            else:
                head.succ.add(after.id)
            return head.id, {after.id}

        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            head = self._stmt_node(stmt, "with", ctx)
            b_entry, b_ends = self._block(stmt.body, ctx)
            head.succ.add(b_entry)
            return head.id, set(b_ends)

        if isinstance(stmt, ast.Try):
            return self._try(stmt, ctx)

        if isinstance(stmt, ast.Match):
            head = self._stmt_node(stmt, "match", ctx)
            ends: set = {head.id}  # no case may match
            for case in stmt.cases:
                c_entry, c_ends = self._block(case.body, ctx)
                head.succ.add(c_entry)
                ends |= c_ends
            return head.id, ends

        if isinstance(stmt, ast.Return):
            has_await, calls = _collect_marks([stmt.value] if stmt.value else [])
            node = cfg._new("stmt", stmt, "return", stmt.lineno)
            node.has_await = has_await
            node.calls = calls
            node.exc.add(ctx.exc)
            target = self._through_finallies(ctx.ret_finallies, cfg.exit_id)
            node.succ.add(target)
            return node.id, set()

        if isinstance(stmt, ast.Raise):
            node = self._stmt_node(stmt, "raise", ctx)
            node.succ.clear()  # a raise only leaves via the exception edge
            return node.id, set()

        if isinstance(stmt, ast.Break):
            node = cfg._new("stmt", stmt, "break", stmt.lineno)
            target = self._through_finallies(
                ctx.loop_finallies, ctx.brk if ctx.brk is not None else cfg.exit_id
            )
            node.succ.add(target)
            return node.id, set()

        if isinstance(stmt, ast.Continue):
            node = cfg._new("stmt", stmt, "continue", stmt.lineno)
            target = self._through_finallies(
                ctx.loop_finallies, ctx.cont if ctx.cont is not None else cfg.exit_id
            )
            node.succ.add(target)
            return node.id, set()

        # Simple statement (incl. nested def/class definitions: opaque).
        node = self._stmt_node(stmt, type(stmt).__name__.lower(), ctx)
        return node.id, {node.id}

    # -- try/except/finally ------------------------------------------------

    @staticmethod
    def _is_catch_all(handler: ast.ExceptHandler) -> bool:
        if handler.type is None:
            return True
        names = []
        t = handler.type
        if isinstance(t, ast.Tuple):
            names = [getattr(e, "id", getattr(e, "attr", "")) for e in t.elts]
        else:
            names = [getattr(t, "id", getattr(t, "attr", ""))]
        return any(n in ("Exception", "BaseException") for n in names)

    def _try(self, stmt: ast.Try, ctx: _Ctx) -> tuple[int, set]:
        cfg = self.cfg
        finalbody = stmt.finalbody or []

        # Exception continuation once the try is done with an exception:
        # through a fresh finally copy (if any) to the outer handler.
        if finalbody:
            fexc_entry, fexc_ends = self._block(finalbody, ctx)
            self._connect(fexc_ends, ctx.exc)
            unhandled_target = fexc_entry
        else:
            unhandled_target = ctx.exc

        # Context for code INSIDE the try body: exceptions go to the
        # handler dispatch; returns/breaks/continues traverse this finally
        # first, then any outer ones.
        if stmt.handlers:
            dispatch = cfg._new("except", stmt, "except-dispatch", stmt.lineno)
        else:
            dispatch = None

        inner_finallies_ret = ctx.ret_finallies
        inner_finallies_loop = ctx.loop_finallies
        if finalbody:
            # The finally copy a jump traverses sees the OUTER ctx (an
            # exception raised inside the finally propagates outward).
            inner_finallies_ret = ((finalbody, ctx),) + ctx.ret_finallies
            inner_finallies_loop = ((finalbody, ctx),) + ctx.loop_finallies

        body_ctx = _Ctx(
            exc=dispatch.id if dispatch is not None else unhandled_target,
            brk=ctx.brk,
            cont=ctx.cont,
            ret_finallies=inner_finallies_ret,
            loop_finallies=inner_finallies_loop,
        )
        b_entry, b_ends = self._block(stmt.body, body_ctx)

        # orelse runs after a clean body; its exceptions are NOT caught by
        # this try's handlers but do traverse the finally.
        orelse_ctx = _Ctx(
            exc=unhandled_target,
            brk=ctx.brk,
            cont=ctx.cont,
            ret_finallies=inner_finallies_ret,
            loop_finallies=inner_finallies_loop,
        )
        if stmt.orelse:
            o_entry, o_ends = self._block(stmt.orelse, orelse_ctx)
            self._connect(b_ends, o_entry)
            clean_ends = o_ends
        else:
            clean_ends = b_ends

        # Handlers: exceptions inside a handler body go through the finally
        # to the outer handler; jumps traverse the finally too.
        handler_ends: set = set()
        if dispatch is not None:
            caught_all = False
            handler_ctx = _Ctx(
                exc=unhandled_target,
                brk=ctx.brk,
                cont=ctx.cont,
                ret_finallies=inner_finallies_ret,
                loop_finallies=inner_finallies_loop,
            )
            for handler in stmt.handlers:
                h_entry, h_ends = self._block(handler.body, handler_ctx)
                dispatch.succ.add(h_entry)
                handler_ends |= h_ends
                if self._is_catch_all(handler):
                    caught_all = True
            if not caught_all:
                # The in-flight exception may match no handler: escape.
                dispatch.succ.add(unhandled_target)

        # Normal completion (clean body/orelse or a handler that fell
        # through) runs ITS OWN finally copy, then continues after the try.
        done_ends = clean_ends | handler_ends
        if finalbody:
            fnorm_entry, fnorm_ends = self._block(finalbody, ctx)
            self._connect(done_ends, fnorm_entry)
            ends = fnorm_ends
        else:
            ends = done_ends

        entry = b_entry
        return entry, set(ends)


def build_cfg(func) -> FunctionCFG:
    """Build the CFG for one ``FunctionDef`` / ``AsyncFunctionDef``."""
    cfg = FunctionCFG(func)
    lowerer = _Lowerer(cfg)
    ctx = _Ctx(exc=cfg.raise_id)
    entry, ends = lowerer._block(func.body, ctx)
    cfg.entry.succ.add(entry)
    lowerer._connect(ends, cfg.exit_id)
    return cfg


def iter_cfgs(tree: ast.AST) -> Iterator[FunctionCFG]:
    """A CFG for every function in ``tree`` (methods and nested included)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield build_cfg(node)


# --------------------------------------------------------------------------
# Solvers
# --------------------------------------------------------------------------


def solve_forward(
    cfg: FunctionCFG,
    is_fact: Callable[[FlowNode], bool],
    include_exc: bool = True,
) -> set:
    """Generic forward MUST-reach: the node ids at which every path from
    the entry has already traversed a fact node (the fact node itself
    counts at its own id). The meet is intersection — one fact-free path
    in kills the fact. Unreachable nodes report True vacuously."""
    nodes = cfg.nodes
    preds = cfg.preds(include_exc=include_exc)
    # OUT[n] = IN[n] or is_fact(n); IN[n] = AND over preds OUT.
    out = {n.id: True for n in nodes}  # top = "fact on all paths so far"
    out[cfg.entry_id] = False
    changed = True
    while changed:
        changed = False
        for n in nodes:
            if n.id == cfg.entry_id:
                continue
            p = preds[n.id]
            if p:
                new_in = all(out[q] for q in p)
            else:
                new_in = True  # unreachable: vacuous
            new_out = new_in or is_fact(n)
            if new_out != out[n.id]:
                out[n.id] = new_out
                changed = True
    return {n.id for n in nodes if out[n.id]}


def _reachable_from_entry(cfg: FunctionCFG) -> set:
    seen: set = set()
    stack = [cfg.entry_id]
    while stack:
        nid = stack.pop()
        if nid in seen:
            continue
        seen.add(nid)
        node = cfg.node(nid)
        stack.extend(node.succ)
        stack.extend(node.exc)
    return seen


def escaping_opens(
    cfg: FunctionCFG,
    is_open: Callable[[FlowNode], bool],
    is_close: Callable[[FlowNode], bool],
    escape_normal_ok: bool = False,
) -> list[tuple[FlowNode, str]]:
    """Every reachable open node from which the function can be left with
    the bracket still open. Returns (open_node, "raise"|"return") pairs.

    The open's OWN exception edge leaves with the bracket closed (if the
    open call raised, the bracket never opened); a close node's out-edges
    all leave closed (the close ran). ``escape_normal_ok`` licenses the
    bracket-implementation idiom — a wrapper whose CONTRACT is to return
    with the bracket open (``_begin_landing``) — while still requiring the
    exception path to close (the exact PR 7 invariant)."""
    reachable = _reachable_from_entry(cfg)
    findings: list[tuple[FlowNode, str]] = []
    for node in cfg.nodes:
        if node.id not in reachable or not is_open(node):
            continue
        # DFS with state open=True from the open's NORMAL successors.
        seen: set = set()
        stack = list(node.succ)
        escaped_raise = False
        escaped_return = False
        while stack:
            nid = stack.pop()
            if nid in seen:
                continue
            seen.add(nid)
            cur = cfg.node(nid)
            if nid == cfg.raise_id:
                escaped_raise = True
                continue
            if nid == cfg.exit_id:
                escaped_return = True
                continue
            if is_close(cur):
                continue  # bracket closed on this path; stop propagating
            stack.extend(cur.succ)
            stack.extend(cur.exc)
        if escaped_raise:
            findings.append((node, "raise"))
        if escaped_return and not escape_normal_ok:
            findings.append((node, "return"))
    return findings


def nodes_between(
    cfg: FunctionCFG,
    open_node: FlowNode,
    is_close: Callable[[FlowNode], bool],
) -> list[FlowNode]:
    """The statement nodes on some path strictly between ``open_node`` and
    a close node (close nodes excluded), over normal AND exception edges —
    i.e. everything that can execute while the bracket is held."""
    seen: set = set()
    stack = list(open_node.succ)
    out: list[FlowNode] = []
    while stack:
        nid = stack.pop()
        if nid in seen or nid in (cfg.exit_id, cfg.raise_id):
            continue
        seen.add(nid)
        cur = cfg.node(nid)
        if is_close(cur):
            continue
        if cur.kind == "stmt" and cur.stmt is not None:
            out.append(cur)
        stack.extend(cur.succ)
        stack.extend(cur.exc)
    out.sort(key=lambda n: n.id)
    return out


def dominated_by(
    cfg: FunctionCFG, node: FlowNode, is_fact: Callable[[FlowNode], bool]
) -> bool:
    """True when every NORMAL path from the entry to ``node`` traverses a
    fact node strictly before it (the fact dominates the node)."""
    facts = solve_forward(cfg, is_fact, include_exc=False)
    if node.id in facts and not is_fact(node):
        return True
    # solve_forward counts the node's own fact at its own id; dominance
    # wants the fact strictly before, so recompute IN for this node.
    preds = cfg.preds(include_exc=False)
    p = preds[node.id]
    return bool(p) and all(q in facts for q in p)


def post_dominated_by(
    cfg: FunctionCFG, node: FlowNode, is_fact: Callable[[FlowNode], bool]
) -> bool:
    """True when no NORMAL path from ``node`` reaches the function exit
    without traversing a fact node. Exception edges are not followed: an
    escaping raise aborts the operation and is the CALLER's audit/bump
    problem (bracket-discipline owns exception-path completeness)."""
    seen: set = set()
    stack = list(node.succ)
    while stack:
        nid = stack.pop()
        if nid in seen:
            continue
        seen.add(nid)
        if nid == cfg.exit_id:
            return False
        cur = cfg.node(nid)
        if is_fact(cur):
            continue
        stack.extend(cur.succ)
    return True
