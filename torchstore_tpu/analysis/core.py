"""Core infrastructure for the repo's static-analysis suite (``tslint``).

The store's correctness rests on conventions no general-purpose linter knows
about: actor endpoints are dispatched dynamically by name (a typo'd RPC only
fails at runtime), coroutines must never swallow ``asyncio.CancelledError``,
forkserver children inherit module state, every ``TORCHSTORE_TPU_*`` knob
must live in the typed registry in ``config.py``, and the metric/span
namespace must not fork. Each of those conventions has shipped at least one
real bug (see ISSUE 4 / CHANGES.md); the checkers under
``analysis/checkers/`` turn them into mechanical, tier-1-enforced rules.

This module provides the shared plumbing:

- ``SourceFile`` / ``Project`` — the scanned tree, parsed once (one
  ``ast.parse`` per file shared by every checker).
- ``Finding`` — one diagnostic, with a line-independent identity
  (rule, path, message) so the baseline survives unrelated edits.
- pragma suppression — ``# tslint: disable=<rule>[,<rule>...]`` on the
  finding line or the line directly above; ``# tslint: disable-file=<rule>``
  in the first 20 lines disables a rule for the whole file.
- baseline — a checked-in JSON multiset of grandfathered findings;
  ``run_checks`` splits results into baselined and NEW findings so the
  tier-1 gate can fail only on regressions.
"""

from __future__ import annotations

import ast
import json
import os
import re
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Iterable, Optional

# Mirrors scripts/check_metric_names.py's historical scope: the shipped
# package plus every executable entry point. Tests are deliberately excluded
# — they seed intentional violations against private registries/fixtures.
SCAN_DIRS = ("torchstore_tpu", "benchmarks", "scripts", "examples")
SCAN_FILES = ("bench.py", "__graft_entry__.py")

DEFAULT_BASELINE = "tslint_baseline.json"

_PRAGMA_RE = re.compile(r"#\s*tslint:\s*disable=([a-z0-9_,\- ]+)")
_PRAGMA_FILE_RE = re.compile(r"#\s*tslint:\s*disable-file=([a-z0-9_,\- ]+)")


@dataclass(frozen=True)
class Finding:
    """One diagnostic. ``message`` must not embed line numbers — the
    baseline matches on (rule, path, message) so unrelated edits that shift
    lines do not resurrect grandfathered findings."""

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }


class SourceFile:
    """One parsed file: source text, AST, and pragma tables."""

    def __init__(self, root: str, abspath: str) -> None:
        self.abspath = abspath
        self.path = os.path.relpath(abspath, root).replace(os.sep, "/")
        with open(abspath, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.text, filename=abspath)
        except SyntaxError as exc:
            self.parse_error = f"{type(exc).__name__}: {exc}"
        # line -> set of rules disabled on that line (pragma on the line
        # itself or the line directly above).
        self._line_disables: dict[int, set[str]] = {}
        self._file_disables: set[str] = set()
        for idx, line in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                self._line_disables.setdefault(idx, set()).update(rules)
                self._line_disables.setdefault(idx + 1, set()).update(rules)
            if idx <= 20:
                m = _PRAGMA_FILE_RE.search(line)
                if m:
                    self._file_disables.update(
                        r.strip() for r in m.group(1).split(",") if r.strip()
                    )

    def disabled(self, rule: str, line: int) -> bool:
        if rule in self._file_disables or "all" in self._file_disables:
            return True
        rules = self._line_disables.get(line)
        return bool(rules) and (rule in rules or "all" in rules)


# (root, abspath, mtime_ns, size) -> SourceFile. SourceFile is immutable
# once built (checkers only read it), so a file whose stat signature hasn't
# moved can reuse its parse across Project constructions — the test suite
# builds Project(REPO_ROOT) once per live-tree test and this collapses all
# of those re-parses into one.
_PARSE_CACHE: dict[tuple[str, str, int, int], SourceFile] = {}


def _cached_source_file(root: str, abspath: str) -> SourceFile:
    try:
        st = os.stat(abspath)
        key = (root, abspath, st.st_mtime_ns, st.st_size)
    except OSError:
        return SourceFile(root, abspath)
    sf = _PARSE_CACHE.get(key)
    if sf is None:
        sf = _PARSE_CACHE[key] = SourceFile(root, abspath)
    return sf


class Project:
    """The scanned tree, parsed once and shared by every checker."""

    def __init__(self, root: str, paths: Optional[Iterable[str]] = None) -> None:
        self.root = os.path.abspath(root)
        if paths is None:
            paths = discover_files(self.root)
        self.files: list[SourceFile] = [
            _cached_source_file(self.root, p) for p in sorted(paths)
        ]

    def file(self, relpath: str) -> Optional[SourceFile]:
        for sf in self.files:
            if sf.path == relpath:
                return sf
        return None


def discover_files(root: str) -> list[str]:
    paths: list[str] = []
    for rel in SCAN_DIRS:
        base = os.path.join(root, rel)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            paths.extend(
                os.path.join(dirpath, f) for f in filenames if f.endswith(".py")
            )
    for rel in SCAN_FILES:
        path = os.path.join(root, rel)
        if os.path.exists(path):
            paths.append(path)
    return paths


# --------------------------------------------------------------------------
# Baseline
# --------------------------------------------------------------------------


def load_baseline(path: str) -> dict[tuple[str, str, str], int]:
    """{(rule, path, message): count} multiset of grandfathered findings."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    out: dict[tuple[str, str, str], int] = {}
    for entry in doc.get("findings", ()):
        key = (entry["rule"], entry["path"], entry["message"])
        out[key] = out.get(key, 0) + int(entry.get("count", 1))
    return out


def save_baseline(path: str, findings: list[Finding]) -> None:
    counts: dict[tuple[str, str, str], int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    doc = {
        "comment": (
            "Grandfathered tslint findings. Entries here do NOT fail the "
            "tier-1 gate; fix the code and delete the entry rather than "
            "adding new ones. Regenerate with: python scripts/tslint.py "
            "--write-baseline"
        ),
        "findings": [
            {"rule": rule, "path": p, "message": msg, "count": n}
            for (rule, p, msg), n in sorted(counts.items())
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")


@dataclass
class RunResult:
    findings: list[Finding] = field(default_factory=list)
    new: list[Finding] = field(default_factory=list)
    baselined: list[Finding] = field(default_factory=list)
    rules: tuple[str, ...] = ()
    timings: dict[str, float] = field(default_factory=dict)  # rule -> seconds

    def to_dict(self) -> dict:
        new_keys = {f.key for f in self.new}
        return {
            "rules": list(self.rules),
            "total": len(self.findings),
            "new": len(self.new),
            "baselined": len(self.baselined),
            "rule_seconds": {
                rule: round(sec, 4)
                for rule, sec in sorted(
                    self.timings.items(), key=lambda kv: -kv[1]
                )
            },
            "findings": [
                dict(f.to_dict(), baselined=f.key not in new_keys)
                for f in self.findings
            ],
        }


def run_checks(
    root: str,
    rules: Optional[Iterable[str]] = None,
    baseline_path: Optional[str] = None,
    project: Optional[Project] = None,
) -> RunResult:
    """Run (a subset of) the checkers over ``root``; split findings into
    baselined and new against ``baseline_path`` (None = no baseline)."""
    from torchstore_tpu.analysis.checkers import CHECKERS

    if project is None:
        project = Project(root)
    selected = dict(CHECKERS)
    if rules is not None:
        wanted = set(rules)
        unknown = wanted - set(selected)
        if unknown:
            raise ValueError(
                f"unknown rule(s) {sorted(unknown)}; have {sorted(selected)}"
            )
        selected = {k: v for k, v in selected.items() if k in wanted}

    # Checkers are pure functions of the read-only Project, so they run
    # concurrently; per-rule wall time is recorded so --json can point at
    # the slowest rule when the runtime budget regresses.
    timings: dict[str, float] = {}

    def _run_one(item: tuple) -> list[Finding]:
        rule, checkfn = item
        t0 = time.perf_counter()
        try:
            return checkfn(project)
        finally:
            timings[rule] = time.perf_counter() - t0

    if len(selected) > 1:
        with ThreadPoolExecutor(
            max_workers=min(8, len(selected)), thread_name_prefix="tslint"
        ) as pool:
            per_rule = list(pool.map(_run_one, selected.items()))
    else:
        per_rule = [_run_one(item) for item in selected.items()]

    findings: list[Finding] = []
    for batch in per_rule:
        for f in batch:
            sf = project.file(f.path)
            if sf is not None and sf.disabled(f.rule, f.line):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))

    result = RunResult(findings=findings, rules=tuple(selected), timings=timings)
    budget = load_baseline(baseline_path) if baseline_path else {}
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
            result.baselined.append(f)
        else:
            result.new.append(f)
    return result


# --------------------------------------------------------------------------
# Shared AST helpers
# --------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_tail(node: ast.Call) -> Optional[str]:
    """Last attribute/name of the called object ('sleep' for time.sleep(..))."""
    func = node.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def iter_function_scopes(tree: ast.AST):
    """Yield (func_node_or_None, body_statements) for the module and every
    function, with nested function bodies EXCLUDED from the enclosing
    scope's statement walk (a nested sync ``def`` inside an ``async def``
    runs on its own rules)."""
    yield None, getattr(tree, "body", [])
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, node.body


def walk_scope(stmts: Iterable[ast.stmt]):
    """ast.walk over statements without descending into nested function or
    lambda bodies."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue  # nested scope: yielded as a leaf, body not entered
        stack.extend(ast.iter_child_nodes(node))
