"""mirror-discipline: METADATA segments are attached only through stamped/.

The cross-host metadata tier (metadata/mirror.py) republishes the fleet's
seqlock-stamped METADATA segments into per-host local replicas; which
segment name backs a given logical reader is a MOVING TARGET — the feed
tombstones and re-creates replica segments on every topology reshape, and
``stamped.attach_reader`` is the one accessor that absorbs gone/renamed/
cross-mount publishers (returning None so the RPC plane serves loudly).
A raw ``MetaStampReader(...)`` construction outside the stamped/mirror
modules pins a segment NAME: it works until the first reshape, then reads
a tombstoned (or recycled) segment forever — the silent-stale failure the
whole torn/stale fallback ladder exists to rule out.

Rule: outside ``torchstore_tpu/metadata/stamped.py`` and
``torchstore_tpu/metadata/mirror.py``, any call whose callee name is
``MetaStampReader`` is forbidden — attach through
``stamped.attach_reader(descriptor)`` (local publishers) or
``MetadataMirror.descriptors()`` (remote publishers) instead. Writer
construction stays legal everywhere: publishers own their segments'
lifecycles, readers must not.
"""

from __future__ import annotations

import ast

from torchstore_tpu.analysis.core import Finding, Project

RULE = "mirror-discipline"

_EXEMPT_FILES = (
    "torchstore_tpu/metadata/stamped.py",
    "torchstore_tpu/metadata/mirror.py",
)

_FORBIDDEN = "MetaStampReader"

_MESSAGE = (
    "raw MetaStampReader attach outside metadata/stamped.py//mirror.py: "
    "segment names move on every reshape — attach through "
    "stamped.attach_reader(descriptor) (or MetadataMirror.descriptors() "
    "for remote publishers) so gone/renamed segments fall back loudly "
    "instead of pinning a tombstoned name"
)


def _callee_name(func: ast.AST) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        if sf.tree is None or sf.path in _EXEMPT_FILES:
            continue
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Call)
                and _callee_name(node.func) == _FORBIDDEN
            ):
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.path,
                        line=node.lineno,
                        message=_MESSAGE,
                    )
                )
    return findings
