"""quant-discipline: scale tables live with the payload, nowhere else.

The blockwise quant wire tier (state_dict_utils + the arena layout in
transport/landing.py) is only sound because scales travel IN the fused blob
— the same segment as the codes they decode (compute_arena_layout's
scale-slot mode), parsed and applied by the one blessed codec. Code
elsewhere that reads or writes a scale table by hand (a ``["scales"]``
subscript on a blob section, a marker meta, or a stream record) re-derives
the layout — and the first drift (a stale offset after a block-size change,
scales fetched over a different RPC than their payload) silently decodes
weights with the WRONG scales, the exact corruption the fused format
exists to kill.

Rule: outside the codec's home (``state_dict_utils.py``) and the layout
module (``transport/landing.py``), any subscript or ``.get(...)`` whose key
is the string literal ``"scales"`` is a finding in the data-plane modules
(transport/, client, controller, storage_volume, weight_channel,
stream_sync, direct_weight_sync, api, provision/). Tests and scripts are
out of scope.
"""

from __future__ import annotations

import ast

from torchstore_tpu.analysis.core import Finding, Project

RULE = "quant-discipline"

_BLESSED = (
    "torchstore_tpu/state_dict_utils.py",
    "torchstore_tpu/transport/landing.py",
)

_SCOPED_PREFIXES = (
    "torchstore_tpu/transport/",
    "torchstore_tpu/provision/",
)

_SCOPED_FILES = (
    "torchstore_tpu/client.py",
    "torchstore_tpu/controller.py",
    "torchstore_tpu/storage_volume.py",
    "torchstore_tpu/weight_channel.py",
    "torchstore_tpu/stream_sync.py",
    "torchstore_tpu/direct_weight_sync.py",
    "torchstore_tpu/api.py",
)

_MESSAGE = (
    "raw scale-table access outside the quant codec: scales are part of "
    "the fused blob layout owned by state_dict_utils + "
    "transport/landing.py (compute_arena_layout scale slots) — reading or "
    "writing them by hand can silently decode weights with the wrong "
    "scales; route through parse_quant_blob / the DeltaDecoder"
)


def _in_scope(path: str) -> bool:
    if path in _BLESSED:
        return False
    if path in _SCOPED_FILES:
        return True
    return any(path.startswith(p) for p in _SCOPED_PREFIXES)


def _is_scales_literal(node: ast.expr) -> bool:
    return isinstance(node, ast.Constant) and node.value == "scales"


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        if sf.tree is None or not _in_scope(sf.path):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Subscript) and _is_scales_literal(
                node.slice
            ):
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.path,
                        line=node.lineno,
                        message=_MESSAGE,
                    )
                )
                continue
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and node.args
                and _is_scales_literal(node.args[0])
            ):
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.path,
                        line=node.lineno,
                        message=_MESSAGE,
                    )
                )
    return findings
