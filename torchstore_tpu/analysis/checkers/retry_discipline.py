"""retry-discipline: retries ride ``config.RetryPolicy``; faultpoint names
are registered.

Two sub-rules, both grounded in this PR's unification work:

1. **Bare-sleep retry loops.** A ``time.sleep``/``asyncio.sleep`` with a
   hardcoded (constant) delay inside a loop that also catches exceptions is
   the ad-hoc retry idiom the unified ``RetryPolicy`` replaced (the reclaim
   drainer's env-list delays, hardcoded client deadlines). Such loops must
   derive their schedule from a policy (``policy.backoff(attempt)``) — a
   computed delay expression is accepted, a numeric literal inside a
   try-bearing loop is flagged. Sleeps outside loops, or in loops that
   never catch (pacing loops like the health supervisor's interval sleep),
   are fine.

2. **Faultpoint name drift.** Every ``faults.fire("...")`` /
   ``faults.afire("...")`` / ``faults.arm("...")`` call site with a literal
   name must name a site in ``faults.REGISTRY`` — a typo'd faultpoint never
   fires, silently turning the chaos test that arms it vacuous. (Names
   passed as variables are out of scope: the registry check in
   ``faults.arm`` catches those at runtime, loudly.)
"""

from __future__ import annotations

import ast

from torchstore_tpu.analysis.core import Finding, Project, dotted_name

RULE = "retry-discipline"

_SLEEP_CALLS = ("time.sleep", "asyncio.sleep")
_FAULT_CALLS = {
    "faults.fire": 0,
    "faults.afire": 0,
    "faults.arm": 0,
    "fire": 0,
    "afire": 0,
}

_SLEEP_MESSAGE = (
    "hardcoded sleep inside a retry loop: derive the backoff schedule from "
    "config.RetryPolicy (policy.backoff(attempt) / should_retry) instead of "
    "an ad-hoc constant delay"
)


def _constant_delay(call: ast.Call) -> bool:
    if not call.args:
        return False
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, (int, float)):
        # sleep(0) is the cooperative-yield idiom, not a backoff.
        return arg.value > 0
    # Unary minus on a literal etc. still counts as hardcoded.
    if (
        isinstance(arg, ast.UnaryOp)
        and isinstance(arg.operand, ast.Constant)
        and isinstance(arg.operand.value, (int, float))
    ):
        return True
    return False


_OPAQUE = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _walk_opaque(root: ast.AST):
    """ast.walk that does NOT descend into nested function/lambda bodies:
    a loop that merely DEFINES a retrying closure is not itself the retry
    loop, and a closure's sleep belongs to the closure's own loops."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, _OPAQUE):
            stack.extend(ast.iter_child_nodes(node))


def _loop_catches(loop: ast.AST) -> bool:
    """Does this loop body contain a try/except (the retry shape)?"""
    return any(
        isinstance(node, ast.Try) and node.handlers
        for node in _walk_opaque(loop)
    )


def _registry() -> frozenset[str]:
    from torchstore_tpu.faults import REGISTRY

    return REGISTRY


def check(project: Project) -> list[Finding]:
    registry = _registry()
    findings: list[Finding] = []
    for sf in project.files:
        if sf.tree is None or not sf.path.startswith("torchstore_tpu/"):
            continue
        if sf.path == "torchstore_tpu/faults.py":
            continue  # the framework itself (wedge sleeps, registry source)
        # Collect loops that catch exceptions, then flag constant-delay
        # sleeps lexically inside them (excluding nested function bodies,
        # matched by walking each loop with the same opacity rule).
        retry_loops = [
            node
            for node in ast.walk(sf.tree)
            if isinstance(node, (ast.While, ast.For, ast.AsyncFor))
            and _loop_catches(node)
        ]
        flagged: set[int] = set()
        for loop in retry_loops:
            for node in _walk_opaque(loop):
                if (
                    isinstance(node, ast.Call)
                    and dotted_name(node.func) in _SLEEP_CALLS
                    and _constant_delay(node)
                    and node.lineno not in flagged
                ):
                    flagged.add(node.lineno)
                    findings.append(
                        Finding(
                            rule=RULE,
                            path=sf.path,
                            line=node.lineno,
                            message=_SLEEP_MESSAGE,
                        )
                    )
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            if dotted not in _FAULT_CALLS:
                continue
            if not node.args or not isinstance(node.args[0], ast.Constant):
                continue
            name = node.args[0].value
            if isinstance(name, str) and name not in registry:
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.path,
                        line=node.lineno,
                        message=(
                            f"faultpoint {name!r} is not in faults.REGISTRY:"
                            " a typo'd site never fires (chaos tests arming"
                            " it run vacuously)"
                        ),
                    )
                )
    return findings
