"""control-discipline: every actuator call site in ``torchstore_tpu/control/``
and ``torchstore_tpu/autoscale/`` must record a flight-recorder
``decision`` event in the same function.

The control plane's whole audit story (ISSUE 16) is that *no* placement
mutation happens silently: the engine funnels every applied/deferred/
abandoned action through ``_decision()``, which increments
``ts_control_decisions_total`` and records a ``decision`` flight-recorder
event. The autoscale plane (ISSUE 18) inherits the same contract for
scale/drain/retire/demote actuations. A new actuator call site that
skips the funnel would mutate the fleet invisibly — exactly the
regression this rule pins.

Mechanics: for each function scope in a ``control/`` or ``autoscale/``
module, if the scope calls an actuator — ``migrate_key``, ``pull_from``,
``tier_sweep``, ``set_tiers``, ``attach_volume``, ``detach_volume``,
``drop_volume``, ``mark_draining``, ``blob_sweep``, ``blob_archive``
(directly or through an endpoint wrapper like
``ref.tier_sweep.call_one``), or re-parents a relay by assigning into
``_relay_prefer`` — the same scope must also contain a decision-audit
call: a call to ``_decision``/``record_decision``, or a ``record(...)``
whose first argument is the literal ``"decision"``. Nested function
bodies are separate scopes (the audit must live where the actuation
lives, not in a sibling closure).

Modules outside these planes are out of scope: the storage/metadata
planes call these same primitives on their own authority (auto-repair,
reclaim, the api-layer spawn executor) with their own event discipline.
"""

from __future__ import annotations

import ast

from torchstore_tpu.analysis.core import (
    Finding,
    Project,
    call_tail,
    dotted_name,
    iter_function_scopes,
    walk_scope,
)

RULE = "control-discipline"

_SCOPE_PREFIXES = ("torchstore_tpu/control/", "torchstore_tpu/autoscale/")

# Attribute names that mutate placement/tier/relay/fleet state when called.
_ACTUATORS = {
    "migrate_key",
    "pull_from",
    "tier_sweep",
    "set_tiers",
    "attach_volume",
    "detach_volume",
    "drop_volume",
    "mark_draining",
    "blob_sweep",
    "blob_archive",
}

# Endpoint-invocation wrappers: ``ref.tier_sweep.call_one(...)`` actuates
# tier_sweep even though the call tail is ``call_one``.
_ENDPOINT_WRAPPERS = {"call_one", "call", "broadcast", "choose"}

# Assigning into this mapping re-parents a relay tree — an actuation with
# no call involved.
_RELAY_STATE = "_relay_prefer"

_AUDIT_CALLS = {"_decision", "record_decision"}


def _actuator_name(node: ast.Call) -> str | None:
    """The actuator a call invokes, or None."""
    tail = call_tail(node)
    if tail in _ACTUATORS:
        return tail
    if tail in _ENDPOINT_WRAPPERS:
        dotted = dotted_name(node.func)
        if dotted:
            hits = _ACTUATORS.intersection(dotted.split("."))
            if hits:
                return sorted(hits)[0]
    return None


def _is_audit_call(node: ast.Call) -> bool:
    tail = call_tail(node)
    if tail in _AUDIT_CALLS:
        return True
    if tail == "record" and node.args:
        first = node.args[0]
        return isinstance(first, ast.Constant) and first.value == "decision"
    return False


def _relay_assign_target(node: ast.AST) -> bool:
    """True for ``<expr>._relay_prefer[...] = ...`` style targets."""
    if isinstance(node, ast.Subscript):
        node = node.value
    return isinstance(node, ast.Attribute) and node.attr == _RELAY_STATE


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        if sf.tree is None or not sf.path.startswith(_SCOPE_PREFIXES):
            continue
        for func, body in iter_function_scopes(sf.tree):
            actuations: list[tuple[int, str]] = []  # (line, actuator)
            audited = False
            for node in walk_scope(body):
                if isinstance(node, ast.Call):
                    name = _actuator_name(node)
                    if name is not None:
                        actuations.append((node.lineno, name))
                    elif _is_audit_call(node):
                        audited = True
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    if any(_relay_assign_target(t) for t in targets):
                        actuations.append((node.lineno, _RELAY_STATE))
            if not actuations or audited:
                continue
            where = func.name if func is not None else "<module>"
            for line, name in actuations:
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.path,
                        line=line,
                        message=(
                            f"control actuator '{name}' in '{where}' "
                            "without a flight-recorder decision event — "
                            "route it through the engine's _decision() "
                            "(or record('decision', ...)) so the action "
                            "is auditable"
                        ),
                    )
                )
    return findings
