"""Checker registry: rule name -> check(project) -> list[Finding].

Adding a checker: write ``checkers/<name>.py`` with ``RULE`` and
``check(project)``, register it here, add fixture self-tests in
tests/test_static_analysis.py proving it catches a seeded defect, run
``python scripts/tslint.py`` and triage what it finds in the live tree
(fix, pragma with justification, or baseline), and document the rule in
docs/ARCHITECTURE.md.
"""

from torchstore_tpu.analysis.checkers import (
    async_blocking,
    await_atomicity,
    bracket_discipline,
    cancellation,
    control_discipline,
    decision_flow,
    endpoint_drift,
    env_registry,
    epoch_discipline,
    fork_safety,
    history_discipline,
    landing_copy,
    metric_discipline,
    mirror_discipline,
    one_sided,
    orphan_task,
    quant_discipline,
    retry_discipline,
    shard_discipline,
    stage_discipline,
    stream_discipline,
)

CHECKERS = {
    endpoint_drift.RULE: endpoint_drift.check,
    async_blocking.RULE: async_blocking.check,
    cancellation.RULE: cancellation.check,
    orphan_task.RULE: orphan_task.check,
    fork_safety.RULE: fork_safety.check,
    env_registry.RULE: env_registry.check,
    metric_discipline.RULE: metric_discipline.check,
    landing_copy.RULE: landing_copy.check,
    retry_discipline.RULE: retry_discipline.check,
    one_sided.RULE: one_sided.check,
    stream_discipline.RULE: stream_discipline.check,
    quant_discipline.RULE: quant_discipline.check,
    shard_discipline.RULE: shard_discipline.check,
    mirror_discipline.RULE: mirror_discipline.check,
    stage_discipline.RULE: stage_discipline.check,
    control_discipline.RULE: control_discipline.check,
    history_discipline.RULE: history_discipline.check,
    bracket_discipline.RULE: bracket_discipline.check,
    epoch_discipline.RULE: epoch_discipline.check,
    await_atomicity.RULE: await_atomicity.check,
    decision_flow.RULE: decision_flow.check,
}
