"""decision-flow: every actuator call site must meet a ``_decision()``
audit on its OWN control-flow path, not merely in the same function.

control-discipline (rule 16) checks that a function which actuates also
audits — somewhere. Its blind spot is exactly the shape audits rot into:
an early return between the actuator and the ``_decision()`` call, or an
actuator on a branch the audit-bearing path never joins. The fleet then
mutates with no flight-recorder event, and the post-incident
reconstruction (ISSUE 16's whole point) has a hole where the action was.

This rule closes the gap with the CFG: an actuator call site in
``control/``/``autoscale/`` passes iff a decision-audit call *dominates*
it (audit strictly before the actuation on every normal path from entry —
the "record intent, then act" idiom of ``checkpoint``) or *post-dominates*
it (every normal path from the actuation to the exit audits before
returning — the ``_apply_*`` idiom of act-then-``return self._decision``).
The actuator node's own exception edge is exempt: a raise out of the
actuation is caught by ``_apply``'s wrapper, which funnels the error
through ``_decision(..., "error: ...")`` itself.

Actuator/audit vocabularies are shared with control-discipline (both
rules run; this one subsumes but does not replace the scope check).
Suppressions carry ``# tslint: disable=decision-flow`` naming the audit
path that covers the site.
"""

from __future__ import annotations

import ast

from torchstore_tpu.analysis.core import Finding, Project
from torchstore_tpu.analysis.checkers.control_discipline import (
    _SCOPE_PREFIXES,
    _actuator_name,
    _is_audit_call,
    _relay_assign_target,
)
from torchstore_tpu.analysis.flow import (
    FlowNode,
    dominated_by,
    iter_cfgs,
    post_dominated_by,
)

RULE = "decision-flow"


def _node_actuator(node: FlowNode) -> str | None:
    for c in node.calls:
        name = _actuator_name(c)
        if name is not None:
            return name
    if isinstance(node.stmt, (ast.Assign, ast.AugAssign)):
        targets = (
            node.stmt.targets
            if isinstance(node.stmt, ast.Assign)
            else [node.stmt.target]
        )
        if any(_relay_assign_target(t) for t in targets):
            return "_relay_prefer"
    return None


def _is_audit(node: FlowNode) -> bool:
    return any(_is_audit_call(c) for c in node.calls)


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        if sf.tree is None or not sf.path.startswith(_SCOPE_PREFIXES):
            continue
        for cfg in iter_cfgs(sf.tree):
            for node in cfg.stmt_nodes():
                name = _node_actuator(node)
                if name is None or _is_audit(node):
                    continue
                if dominated_by(cfg, node, _is_audit):
                    continue
                if post_dominated_by(cfg, node, _is_audit):
                    continue
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.path,
                        line=node.lineno,
                        message=(
                            f"actuator '{name}' in '{cfg.name}' has a "
                            "normal path that skips the _decision() "
                            "audit (early return or unaudited branch) — "
                            "every actuation must be dominated or "
                            "post-dominated by the decision event"
                        ),
                    )
                )
    return findings
