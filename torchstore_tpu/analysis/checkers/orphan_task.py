"""orphan-task: fire-and-forget tasks must retain + retrieve exceptions.

``asyncio`` holds spawned tasks weakly: a ``create_task`` whose result is
dropped can be garbage-collected mid-flight, and a task whose exception is
never retrieved dies silently (one "Task exception was never retrieved"
line at GC time, long after the fact — if at all). The store's reclaim
drainer, SHM pool warmer, and pre-attacher were all spawned this way.

Rule: every ``asyncio.create_task`` / ``ensure_future`` /
``loop.create_task`` call must either

- assign the task to an attribute (``self._reader_task = ...`` — the owner
  awaits/cancels it), or
- be awaited / returned / gathered in the same scope, or
- register a done-callback that can RETRIEVE the exception. A callback
  that is just ``<set>.discard`` / ``.remove`` only un-retains — it never
  calls ``task.exception()``, so failures stay silent; use
  ``utils.spawn_logged`` which retains AND logs + counts failures.
"""

from __future__ import annotations

import ast

from torchstore_tpu.analysis.core import (
    Finding,
    Project,
    iter_function_scopes,
    walk_scope,
)

RULE = "orphan-task"

_SPAWN_ATTRS = {"create_task", "ensure_future"}


def _is_spawn(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and (
            (isinstance(node.func, ast.Attribute) and node.func.attr in _SPAWN_ATTRS)
            or (isinstance(node.func, ast.Name) and node.func.id in _SPAWN_ATTRS)
        )
    )


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        if sf.tree is None:
            continue
        for _fn, body in iter_function_scopes(sf.tree):
            stmts = list(walk_scope(body))
            # name -> spawn line, for tasks bound to a local name
            spawned: dict[str, int] = {}
            callbacks: dict[str, list[ast.expr]] = {}
            safe: set[str] = set()
            for node in stmts:
                # task = create_task(...)
                if isinstance(node, ast.Assign) and _is_spawn(node.value):
                    if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
                        spawned[node.targets[0].id] = node.value.lineno
                    # self._x = create_task(...): owner-managed, fine
                    continue
                # bare create_task(...) statement: nothing retains it
                if isinstance(node, ast.Expr) and _is_spawn(node.value):
                    findings.append(
                        Finding(
                            RULE,
                            sf.path,
                            node.value.lineno,
                            "fire-and-forget task: create_task result is "
                            "dropped (GC can cancel it mid-flight; its "
                            "exception is never retrieved) — use "
                            "utils.spawn_logged",
                        )
                    )
                    continue
            for node in stmts:
                # t.add_done_callback(cb)
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_done_callback"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in spawned
                    and node.args
                ):
                    callbacks.setdefault(node.func.value.id, []).append(node.args[0])
                # await t / return t / gather(.., t, ..) / wait([...t...])
                if isinstance(node, ast.Await) and isinstance(node.value, ast.Name):
                    safe.add(node.value.id)
                if isinstance(node, ast.Return) and isinstance(node.value, ast.Name):
                    safe.add(node.value.id)
                if isinstance(node, ast.Call):
                    tail = (
                        node.func.attr
                        if isinstance(node.func, ast.Attribute)
                        else node.func.id
                        if isinstance(node.func, ast.Name)
                        else None
                    )
                    if tail in ("gather", "wait", "wait_for", "shield", "as_completed"):
                        for a in node.args:
                            for sub in ast.walk(a):
                                if isinstance(sub, ast.Name):
                                    safe.add(sub.id)
                # self.attr = t  (ownership transferred)
                if (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Name)
                    and any(isinstance(t, ast.Attribute) for t in node.targets)
                ):
                    safe.add(node.value.id)
            for name, line in spawned.items():
                if name in safe:
                    continue
                cbs = callbacks.get(name, [])
                has_logging_cb = any(
                    not (isinstance(cb, ast.Attribute) and cb.attr in ("discard", "remove"))
                    for cb in cbs
                )
                if has_logging_cb:
                    continue
                if cbs:
                    msg = (
                        f"task {name!r} is retained only until completion: "
                        "its sole done-callback is a set discard, which "
                        "never retrieves the exception — failures vanish "
                        "silently; use utils.spawn_logged"
                    )
                else:
                    msg = (
                        f"task {name!r} is spawned but never awaited, "
                        "stored, or given a done-callback — it can be "
                        "garbage-collected mid-flight and its exception is "
                        "never retrieved; use utils.spawn_logged"
                    )
                findings.append(Finding(RULE, sf.path, line, msg))
    return findings
