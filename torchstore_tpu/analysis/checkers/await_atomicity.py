"""await-atomicity: no suspension point inside an atomic seqlock bracket,
and no dict mutated both under and outside an ``asyncio.Lock``.

Two interleaving-race shapes, one rule:

**(a) Awaits inside an atomic publish bracket.** The metadata seqlock
bracket (``_publish_open`` … ``_publish_close``) keeps the sequence word
odd while the writer mutates the mapped words; readers spin until it
settles even. The bracket is correct only if the writer gets from open to
close without suspending: an ``await`` (or a call into async_blocking's
known-blocking table — a stalled thread is the same wedge without the
event loop's help) strictly between open and close parks the bracket odd
for an unbounded time and every reader burns its torn-read retries. The
checker walks every CFG path between an open and its close — normal and
exception edges both — and flags any node that can suspend. The DATA-plane
landing bracket (``begin_writes``/``_begin_landing``) is deliberately NOT
in the atomic set: it is designed to be held across the awaited landing
copy (readers of those specific keys retry by contract while bytes land).

**(b) Lock-skipping dict mutation.** The PR 18 ledger-singleton race:
a module holds an ``asyncio.Lock`` and mutates a shared dict under it on
one path, but a second path mutates the same dict with no lock held —
the lock guards nothing. The checker collects, per module, every dict
attribute/name initialized with a literal ``{}``/``dict()`` alongside an
``asyncio.Lock()``, then flags identities that are subscript-mutated both
inside an ``async with <lock>`` body and outside any lock in an
``async def`` of the same module. Read-only access is fine; the race
needs two mutators.

Suppressions carry ``# tslint: disable=await-atomicity`` with the
invariant that makes the interleaving safe.
"""

from __future__ import annotations

import ast

from torchstore_tpu.analysis.core import Finding, Project, call_tail
from torchstore_tpu.analysis.checkers.async_blocking import blocking_reason
from torchstore_tpu.analysis.flow import FlowNode, iter_cfgs, nodes_between

RULE = "await-atomicity"

# (open, close) pairs that must be suspension-free between them.
ATOMIC_BRACKETS = (("_publish_open", "_publish_close"),)


def _calls(node: FlowNode, name: str) -> bool:
    return any(call_tail(c) == name for c in node.calls)


def _suspension(node: FlowNode) -> str | None:
    if node.has_await:
        return "await suspends the coroutine"
    for c in node.calls:
        reason = blocking_reason(c)
        if reason is not None:
            return f"known-blocking call ({call_tail(c)})"
    return None


def _check_brackets(sf, findings: list[Finding]) -> None:
    for cfg in iter_cfgs(sf.tree):
        for opn, close in ATOMIC_BRACKETS:
            for node in cfg.stmt_nodes():
                if not _calls(node, opn):
                    continue
                for mid in nodes_between(
                    cfg, node, lambda n, c=close: _calls(n, c)
                ):
                    why = _suspension(mid)
                    if why is None:
                        continue
                    findings.append(
                        Finding(
                            rule=RULE,
                            path=sf.path,
                            line=mid.lineno,
                            message=(
                                f"suspension point inside the {opn}/"
                                f"{close} bracket in '{cfg.name}': {why} "
                                "while the seqlock is odd — readers spin "
                                "until their torn-read retries are "
                                "exhausted; move it outside the bracket"
                            ),
                        )
                    )


# -- (b) lock-skipping dict mutation ---------------------------------------


def _attr_or_name(node: ast.AST) -> str | None:
    """Identity for ``self._x`` / ``cls._x`` / module-level ``_x``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_lock_ctor(value: ast.AST) -> bool:
    return (
        isinstance(value, ast.Call)
        and call_tail(value) == "Lock"
    )


def _is_dict_ctor(value: ast.AST) -> bool:
    if isinstance(value, ast.Dict) and not value.keys:
        return True
    return (
        isinstance(value, ast.Call)
        and isinstance(value.func, ast.Name)
        and value.func.id == "dict"
        and not value.args
    )


def _mutated_dict(node: ast.AST) -> str | None:
    """The identity a statement subscript-mutates, or None."""
    target = None
    if isinstance(node, ast.Assign):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                target = t.value
    elif isinstance(node, ast.AugAssign) and isinstance(node.target, ast.Subscript):
        target = node.target.value
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                target = t.value
    elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
        call = node.value
        if call_tail(call) in ("pop", "setdefault", "update", "clear", "popitem"):
            f = call.func
            if isinstance(f, ast.Attribute):
                target = f.value
    if target is None:
        return None
    return _attr_or_name(target)


def _lock_names_in_items(stmt) -> set:
    names = set()
    for item in stmt.items:
        expr = item.context_expr
        # ``async with self._lock:`` / ``async with _lock:``
        name = _attr_or_name(expr)
        if name:
            names.add(name)
    return names


def _check_lock_skew(sf, findings: list[Finding]) -> None:
    tree = sf.tree
    # Identities initialized as bare dicts and as asyncio Locks anywhere in
    # the module (class bodies, __init__, module level).
    dicts: set = set()
    locks: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            name = _attr_or_name(node.targets[0])
            if name is None:
                continue
            if _is_dict_ctor(node.value):
                dicts.add(name)
            elif _is_lock_ctor(node.value):
                locks.add(name)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            name = _attr_or_name(node.target)
            if name is None:
                continue
            if _is_dict_ctor(node.value):
                dicts.add(name)
            elif _is_lock_ctor(node.value):
                locks.add(name)
    if not dicts or not locks:
        return

    # Mutation sites, split by whether a known lock is held. Only async
    # functions count — a sync mutator can't interleave with the loop.
    guarded: dict = {}
    bare: dict = {}

    def scan(body, lock_held: bool, fname: str) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.Lambda)):
                continue
            if isinstance(stmt, ast.AsyncFunctionDef):
                continue  # separate scope, scanned at its own def
            held_here = lock_held
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                if _lock_names_in_items(stmt) & locks:
                    held_here = True
            name = _mutated_dict(stmt)
            if name in dicts:
                side = guarded if lock_held else bare
                side.setdefault(name, []).append((stmt.lineno, fname))
            for child_body in (
                getattr(stmt, "body", []),
                getattr(stmt, "orelse", []),
                getattr(stmt, "finalbody", []),
            ):
                if child_body:
                    scan(child_body, held_here, fname)
            for handler in getattr(stmt, "handlers", []):
                scan(handler.body, held_here, fname)

    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            scan(node.body, False, node.name)

    for name in sorted(set(guarded) & set(bare)):
        for line, fname in sorted(set(bare[name])):
            findings.append(
                Finding(
                    rule=RULE,
                    path=sf.path,
                    line=line,
                    message=(
                        f"dict '{name}' is mutated under an asyncio.Lock "
                        f"elsewhere in this module but '{fname}' mutates "
                        "it with no lock held — the lock guards nothing; "
                        "take the same lock (or pragma with the invariant "
                        "that serializes these paths)"
                    ),
                )
            )


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        if sf.tree is None or not sf.path.startswith("torchstore_tpu/"):
            continue
        _check_brackets(sf, findings)
        _check_lock_skew(sf, findings)
    return findings
