"""stage-discipline: timeline stage labels come from the registered catalog.

The stage-attribution layer (observability/timeline.py) only answers
"which stage ate the p99 budget" if client and volume sites record their
wall-clock segments under the SAME taxonomy: a volume labeling its landing
bracket ``"landing_copy"`` while the client records ``"landing"`` splits
one stage into two digests and the dominant-stage vote silently fragments.
``ts.slo_report()``, the loadgen scoreboard merge, and the fleet_scale
bench all assume the catalog is closed.

Rule: every ``observe_stage(op, stage, ...)`` call site must pass the
stage as a STRING LITERAL naming an entry of
``observability.timeline.STAGE_CATALOG``:

- a literal outside the catalog is drift (add the stage to the catalog
  deliberately, in review, or use a registered one);
- a non-literal stage argument is flagged too — a free-string variable
  defeats the static guarantee (the runtime ValueError in
  ``StageQuantiles.observe`` is the backstop, but it fires in production,
  not in review).

``observability/timeline.py`` itself (the catalog's home: the module-level
helpers forward through these names) is exempt.
"""

from __future__ import annotations

import ast

from torchstore_tpu.analysis.core import Finding, Project, dotted_name

RULE = "stage-discipline"

_EXEMPT_FILES = ("torchstore_tpu/observability/timeline.py",)


def _catalog() -> frozenset[str]:
    from torchstore_tpu.observability.timeline import STAGE_CATALOG

    return STAGE_CATALOG


def _stage_arg(call: ast.Call) -> ast.expr | None:
    """The ``stage`` argument of an observe_stage(op, stage, dur) call."""
    if len(call.args) >= 2:
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "stage":
            return kw.value
    return None


def check(project: Project) -> list[Finding]:
    catalog = _catalog()
    findings: list[Finding] = []
    for sf in project.files:
        if sf.tree is None or sf.path in _EXEMPT_FILES:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None or name.split(".")[-1] != "observe_stage":
                continue
            stage = _stage_arg(node)
            if stage is None:
                continue  # arity error: Python itself will fail louder
            if isinstance(stage, ast.Constant) and isinstance(
                stage.value, str
            ):
                if stage.value not in catalog:
                    findings.append(
                        Finding(
                            rule=RULE,
                            path=sf.path,
                            line=node.lineno,
                            message=(
                                f"stage {stage.value!r} is not in "
                                "observability.timeline.STAGE_CATALOG "
                                f"({sorted(catalog)}): free-string stage "
                                "labels fragment the dominant-stage "
                                "attribution — register the stage "
                                "deliberately or use a catalog entry"
                            ),
                        )
                    )
                continue
            findings.append(
                Finding(
                    rule=RULE,
                    path=sf.path,
                    line=node.lineno,
                    message=(
                        "observe_stage called with a non-literal stage: "
                        "the stage catalog is enforced statically — pass "
                        "a STAGE_CATALOG string literal so drift is "
                        "caught in review, not at runtime"
                    ),
                )
            )
    return findings
