"""bracket-discipline: every seqlock/lease bracket open must reach its
close on ALL paths, including exception edges.

The store's one-sided planes are bracketed: a writer opens a stamp bracket
(``begin_writes`` — stamps go odd, readers retry), does the mutation, and
closes it (``end_writes`` — stamps settle even). A bracket that opens and
never closes is not a crash, it is a WEDGE: every reader of those keys
retries forever, and the landing inflight counter blocks volume retirement.
PR 7 shipped exactly this — ``_begin_landing`` could raise out of its fault
hook after ``begin_writes`` + ``_landing_open`` had run, leaking the
inflight count until a reviewer caught it by hand. This rule makes that
review mechanical: for each known bracket pair, every reachable open site
must have its matching close on every CFG path out of the function —
normal AND exception — unless the function's contract is to return with
the bracket open (the ``_begin_landing`` implementer idiom, where the
normal-exit escape is the point but a raise must still unwind).

A close "matches" if it is the pair's own close or a recognized composite
closer (``_end_landing`` closes both the stamp bracket and the inflight
counter). Lease brackets (``lease_acquire``/``lease_release``) are checked
only in functions that contain BOTH calls — acquire-only functions
transfer ownership to the caller by design.

Fix pattern: ``try/finally`` around the bracketed region, or an
``except BaseException: <close>; raise`` when the close must not run on
the normal path. Justified escapes carry a
``# tslint: disable=bracket-discipline`` pragma with a comment.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from torchstore_tpu.analysis.core import Finding, Project, call_tail
from torchstore_tpu.analysis.flow import FlowNode, escaping_opens, iter_cfgs

RULE = "bracket-discipline"


@dataclass(frozen=True)
class BracketSpec:
    kind: str  # short human name for the message
    opens: frozenset
    closes: frozenset
    # Wrapper functions whose CONTRACT is to return with this bracket open
    # (they ARE the open): normal-exit escapes are fine there, exception
    # escapes are not.
    escape_ok_normal: frozenset = field(default_factory=frozenset)
    # Only check functions containing both an open and a close — for
    # brackets where acquire-only functions hand ownership to the caller.
    paired_only: bool = False


SPECS = (
    BracketSpec(
        kind="landing",
        opens=frozenset({"_begin_landing"}),
        closes=frozenset({"_end_landing"}),
        paired_only=True,  # callers hold across awaited landings by design
    ),
    BracketSpec(
        kind="stamp-writes",
        opens=frozenset({"begin_writes"}),
        closes=frozenset({"end_writes", "_end_landing"}),
        escape_ok_normal=frozenset({"_begin_landing"}),
    ),
    BracketSpec(
        kind="landing-inflight",
        opens=frozenset({"_landing_open"}),
        closes=frozenset({"_landing_close", "_end_landing"}),
        escape_ok_normal=frozenset({"_begin_landing"}),
    ),
    BracketSpec(
        kind="meta-publish",
        opens=frozenset({"_publish_open"}),
        closes=frozenset({"_publish_close"}),
    ),
    BracketSpec(
        kind="lease",
        opens=frozenset({"lease_acquire"}),
        closes=frozenset({"lease_release"}),
        paired_only=True,
    ),
)


def _calls_any(node: FlowNode, names: frozenset) -> bool:
    return any(call_tail(c) in names for c in node.calls)


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        if sf.tree is None or not sf.path.startswith("torchstore_tpu/"):
            continue
        for cfg in iter_cfgs(sf.tree):
            fn_calls = {
                call_tail(c) for n in cfg.stmt_nodes() for c in n.calls
            }
            for spec in SPECS:
                if not fn_calls & spec.opens:
                    continue
                if spec.paired_only and not fn_calls & spec.closes:
                    continue
                normal_ok = cfg.name in spec.escape_ok_normal
                escapes = escaping_opens(
                    cfg,
                    is_open=lambda n, s=spec: _calls_any(n, s.opens),
                    is_close=lambda n, s=spec: _calls_any(n, s.closes),
                    escape_normal_ok=normal_ok,
                )
                seen: set = set()
                for node, why in escapes:
                    key = (spec.kind, node.id, why)
                    if key in seen:
                        continue
                    seen.add(key)
                    verb = (
                        "a raise can escape"
                        if why == "raise"
                        else "a return path exits"
                    )
                    findings.append(
                        Finding(
                            rule=RULE,
                            path=sf.path,
                            line=node.lineno,
                            message=(
                                f"{spec.kind} bracket opened in "
                                f"'{cfg.name}' but {verb} before "
                                f"{'/'.join(sorted(spec.closes))} — an open "
                                "bracket wedges readers/retirement forever; "
                                "close it in a finally (or except "
                                "BaseException: close; raise)"
                            ),
                        )
                    )
    return findings
