"""history-discipline: trend detectors must name a registered series.

A :class:`~torchstore_tpu.observability.detect.Detector` is bound to its
input by a series selector STRING (``"ts_landing_inflight"``,
``'ts_op_p99_seconds{op="get"}'``). Nothing at runtime ties that string to
the instrument registry: rename the metric and the detector silently goes
blind — ``evaluate_trends()`` finds no matching series, reports
``active: False`` forever, and the control plane's sustained-overload
signal dies without a single error. That is the worst possible failure
mode for an alerting layer.

Rule: every ``Detector(...)`` construction must pass ``series`` as a
STRING LITERAL whose instrument name resolves against the registration
scan that already powers ``--regen-metric-docs``
(``metric_discipline.collect_sites``):

- the name part (selector minus any ``{label}`` suffix, ``:rate``
  derivation, and trailing ``*``) must be a registered instrument — or a
  histogram's derived ``_count``/``_sum``/``_bucket`` series of one;
- a remaining glob in the NAME part defeats static verification and is
  flagged (glob the labels, not the name);
- a non-literal ``series`` argument is flagged for the same reason the
  stage catalog is enforced statically: drift must be caught in review,
  not discovered as a detector that never fires.

``observability/detect.py`` itself is NOT exempt — the stock catalog is
exactly what this rule must keep honest.
"""

from __future__ import annotations

import ast

from torchstore_tpu.analysis.core import Finding, Project, dotted_name
from torchstore_tpu.analysis.checkers import metric_discipline

RULE = "history-discipline"

# Histogram registrations surface as derived series under these suffixes
# (metrics.sample_values samples <name>_count; Prometheus renderers emit
# _sum/_bucket too).
_DERIVED_SUFFIXES = ("_count", "_sum", "_bucket")


def _series_arg(call: ast.Call) -> ast.expr | None:
    """The ``series`` argument of a Detector(name, series, kind, ...)."""
    for kw in call.keywords:
        if kw.arg == "series":
            return kw.value
    if len(call.args) >= 2:
        return call.args[1]
    return None


def _base_name(selector: str) -> str:
    """Selector -> the instrument name it must resolve to."""
    base = selector.split("{", 1)[0]
    if base.endswith(":rate"):
        base = base[: -len(":rate")]
    while base.endswith("*"):
        base = base[:-1]
    return base


def _resolves(base: str, registered: set[str]) -> bool:
    if base in registered:
        return True
    for suffix in _DERIVED_SUFFIXES:
        if base.endswith(suffix) and base[: -len(suffix)] in registered:
            return True
    return False


def check(project: Project) -> list[Finding]:
    registered = {
        name
        for _path, _line, name, _kind in metric_discipline.collect_sites(
            project.root, project
        )
    }
    findings: list[Finding] = []
    for sf in project.files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None or name.split(".")[-1] != "Detector":
                continue
            series = _series_arg(node)
            if series is None:
                continue  # arity error: Python itself will fail louder
            if not (
                isinstance(series, ast.Constant)
                and isinstance(series.value, str)
            ):
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.path,
                        line=node.lineno,
                        message=(
                            "Detector constructed with a non-literal "
                            "series selector: the instrument binding is "
                            "enforced statically — pass a registered "
                            "metric name literal so a rename cannot "
                            "silently orphan the detector"
                        ),
                    )
                )
                continue
            base = _base_name(series.value)
            if any(ch in base for ch in "*?["):
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.path,
                        line=node.lineno,
                        message=(
                            f"Detector series {series.value!r} globs the "
                            "instrument NAME — that defeats the static "
                            "registered-name check (glob the label part, "
                            "not the name)"
                        ),
                    )
                )
                continue
            if not _resolves(base, registered):
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.path,
                        line=node.lineno,
                        message=(
                            f"Detector series {series.value!r} does not "
                            f"resolve to a registered instrument "
                            f"({base!r} is not in the registration scan): "
                            "a renamed or removed metric would leave this "
                            "detector permanently quiet — bind it to a "
                            "registered name"
                        ),
                    )
                )
    return findings
