"""epoch-discipline: structural index mutations must be followed by a
placement-epoch bump on every normal path.

Clients route around the controller using the placement epoch in the
stamped metadata header: a cached placement is valid only while the epoch
matches. Any structural mutation — keys deleted, copies detached, a volume
detached or the index rebuilt — that is NOT followed by
``_bump_epoch`` / ``bump_placement_epoch`` / ``on_structural`` leaves
clients happily reading a placement that no longer exists (the PR 18
phantom-volume drain loop was this shape). The discipline is centralized —
``Controller._bump_epoch`` is "the ONE way the placement epoch moves" —
so the rule is a post-dominance check: in the three files that own
structural state (``controller.py``, ``metadata/index_core.py``,
``metadata/shards.py``), every call site of a RAW mutator must be
post-dominated by a bump call on all normal paths out of the function.

Raw mutators are the non-self-bumping structural ops
(``apply_put_batch``, ``delete_keys``, ``detach_meta``,
``detach_volume``, ``reindex``); wrappers that bump internally
(``migrate_key``, ``merge_copies``, ``auto_repair_pass``,
``replace_volume``, ``drop_volume``) are deliberately not in the set —
their CALLERS are covered because the bump happens inside. Exception
paths are exempt: an escaping raise aborts the operation before the
mutation is client-visible, and the endpoint layer surfaces the error.
Sites where bump ownership is transferred by protocol (the sharded
three-phase delete, a conditional bump gated on the same flag as the
mutation) carry a ``# tslint: disable=epoch-discipline`` pragma with the
justification.
"""

from __future__ import annotations

import ast

from torchstore_tpu.analysis.core import Finding, Project, call_tail, dotted_name
from torchstore_tpu.analysis.flow import FlowNode, iter_cfgs, post_dominated_by

RULE = "epoch-discipline"

_SCOPE_FILES = (
    "torchstore_tpu/controller.py",
    "torchstore_tpu/metadata/index_core.py",
    "torchstore_tpu/metadata/shards.py",
)

# Raw structural mutators: calling one of these changes client-visible
# placement without moving the epoch itself. apply_put_batch is NOT here —
# it reports on_structural internally when the batch detaches copies, so
# its callers are covered (its own detach_meta sites are checked below).
_MUTATORS = {
    "delete_keys",
    "detach_meta",
    "detach_volume",
    "reindex",
}

_BUMPS = {"_bump_epoch", "bump_placement_epoch", "on_structural"}

# ``coordinator.bump_placement_epoch.call_one()`` bumps even though the
# call tail is the endpoint wrapper.
_ENDPOINT_WRAPPERS = {"call_one", "call", "broadcast", "choose"}


def _names_in_call(node: ast.Call) -> set:
    tail = call_tail(node)
    names = {tail} if tail else set()
    if tail in _ENDPOINT_WRAPPERS:
        dotted = dotted_name(node.func)
        if dotted:
            names |= set(dotted.split("."))
    return names


def _is_bump(node: FlowNode) -> bool:
    return any(_names_in_call(c) & _BUMPS for c in node.calls)


def _mutator_in(node: FlowNode) -> str | None:
    for c in node.calls:
        hits = _names_in_call(c) & _MUTATORS
        if hits:
            return sorted(hits)[0]
    return None


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        if sf.tree is None or sf.path not in _SCOPE_FILES:
            continue
        for cfg in iter_cfgs(sf.tree):
            # The raw mutator's own definition mutates state directly —
            # its CALLERS own the bump, per the centralized-bump design.
            if cfg.name in _MUTATORS:
                continue
            for node in cfg.stmt_nodes():
                name = _mutator_in(node)
                if name is None or _is_bump(node):
                    continue
                if post_dominated_by(cfg, node, _is_bump):
                    continue
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.path,
                        line=node.lineno,
                        message=(
                            f"structural mutation '{name}' in "
                            f"'{cfg.name}' is not followed by a "
                            "placement-epoch bump on every normal path — "
                            "clients keep routing on the stale placement; "
                            "bump via _bump_epoch/on_structural after the "
                            "mutation (or pragma with the protocol that "
                            "owns the bump)"
                        ),
                    )
                )
    return findings
