"""one-sided-discipline: client/direct modules read segments ONLY stamped.

The one-sided data plane (PR 7) lets client-side code read bytes straight
out of attached /dev/shm segments with zero RPCs. That is only sound when
every such read is bracketed by a seqlock/generation validation — the
per-entry stamp table (``shared_memory.stamped_read`` /
``stamped_read_batch``) or the direct-sync source-generation check
around ``segment_read_view``. A raw ``seg.view(...)`` /
``seg.strided_view(...)`` / ``np.frombuffer(seg.mmap, ...)`` in a client
or direct module bypasses that validation and can observe mixed-generation
bytes whenever a landing races the read — the exact silent-corruption
class the stamp protocol exists to kill.

Rule: in the client-side modules (client.py, direct_weight_sync.py,
state_dict_utils.py), attached-segment reads must go through
``shared_memory.segment_read_view`` (whose contract requires the
surrounding validation) or the stamped-read helpers. Flagged patterns:

- any ``X.strided_view(...)`` call (only segments have strided_view);
- ``X.view(...)`` where the receiver names a segment (identifier contains
  ``seg``) — numpy's dtype-``view`` on arrays stays out of scope;
- ``np.frombuffer(X.mmap, ...)`` — a raw mapping read.

``transport/shared_memory.py`` itself and the volume/transport server side
are out of scope: they implement the protocol (and the volume is the
writer — its reads of its own segments are serialized by the event loop).
Writer-side staging uses in direct_weight_sync carry a pragma with the
seqlock justification.
"""

from __future__ import annotations

import ast

from torchstore_tpu.analysis.core import Finding, Project, dotted_name

RULE = "one-sided-discipline"

_SCOPED_FILES = (
    "torchstore_tpu/client.py",
    "torchstore_tpu/direct_weight_sync.py",
    "torchstore_tpu/state_dict_utils.py",
)

_MESSAGE = (
    "raw attached-segment read in a client/direct module: route it through "
    "shared_memory.segment_read_view / stamped_read (seqlock-validated) — "
    "an unstamped read can observe mixed-generation bytes"
)


def _receiver_names_segment(node: ast.expr) -> bool:
    """True when the attribute receiver's source identifiers suggest a
    segment object (``seg``, ``segment``, ``self._segments[...]`` ...)."""
    dotted = dotted_name(node)
    if dotted is not None:
        return "seg" in dotted.lower()
    # Subscripts like self._segments[name] have no dotted name; scan ids.
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and "seg" in sub.id.lower():
            return True
        if isinstance(sub, ast.Attribute) and "seg" in sub.attr.lower():
            return True
    return False


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        if sf.tree is None or sf.path not in _SCOPED_FILES:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                if func.attr == "strided_view":
                    findings.append(
                        Finding(
                            rule=RULE,
                            path=sf.path,
                            line=node.lineno,
                            message=_MESSAGE,
                        )
                    )
                    continue
                if func.attr == "view" and _receiver_names_segment(func.value):
                    findings.append(
                        Finding(
                            rule=RULE,
                            path=sf.path,
                            line=node.lineno,
                            message=_MESSAGE,
                        )
                    )
                    continue
            dotted = dotted_name(func)
            if dotted in ("np.frombuffer", "numpy.frombuffer") and node.args:
                first = node.args[0]
                if (
                    isinstance(first, ast.Attribute)
                    and first.attr == "mmap"
                ):
                    findings.append(
                        Finding(
                            rule=RULE,
                            path=sf.path,
                            line=node.lineno,
                            message=_MESSAGE,
                        )
                    )
    return findings
