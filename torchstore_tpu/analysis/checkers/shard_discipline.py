"""shard-discipline: index-owning state is touched only inside metadata/.

The scale-out metadata plane (torchstore_tpu/metadata/) partitions the
key -> {volume_id: StorageInfo} index across controller shards; exactly
ONE process owns any key's entry, and every engine — relay forwarding,
auto-repair, tier sweeps, catalogs, rebuild — reaches the index through
the shard-routed authority surface (``IndexCore`` methods locally, their
``RemoteIndex`` fan-out twins when sharded). A direct ``.index`` /
``._key_gens`` touch in controller.py (or the client) re-creates the
single-writer assumption the sharding removed: code that "just reads the
dict" works at shards=1 and silently sees an EMPTY index — or worse,
writes one the fleet never reads — the moment the plane is sharded.

Rule: in the scoped modules (controller.py, client.py), any attribute
access or subscript whose attribute name is ``index`` or ``_key_gens``
is forbidden — route it through ``self.idx`` / the core's methods. The
metadata package itself (the state's home) is out of scope, as is any
module outside the metadata plane (``.index(...)`` the str/list method
is exempted by call-shape: the rule skips attribute CALLS whose name is
``index``, which the forbidden state never is).
"""

from __future__ import annotations

import ast

from torchstore_tpu.analysis.core import Finding, Project

RULE = "shard-discipline"

_SCOPED_FILES = (
    "torchstore_tpu/controller.py",
    "torchstore_tpu/client.py",
)

_FORBIDDEN_ATTRS = {"index", "_key_gens"}

_MESSAGE = (
    "direct index-owning state access outside torchstore_tpu/metadata/: "
    "route through the shard-routed authority (self.idx / IndexCore "
    "methods) — a raw .index/._key_gens touch reads an empty dict (or "
    "writes an unread one) the moment the metadata plane is sharded"
)


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        if sf.tree is None or sf.path not in _SCOPED_FILES:
            continue
        # Attribute nodes that are the FUNCTION of a call are method
        # lookups (str.index/list.index), never the state this rule
        # guards — collect them first so the walk can skip them.
        call_funcs = {
            id(node.func)
            for node in ast.walk(sf.tree)
            if isinstance(node, ast.Call)
        }
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr in _FORBIDDEN_ATTRS
                and id(node) not in call_funcs
            ):
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.path,
                        line=node.lineno,
                        message=_MESSAGE,
                    )
                )
    return findings
