"""metric-discipline: the metric/span namespace cannot silently fork.

Subsumes scripts/check_metric_names.py (which is now a thin shim over this
module) and extends it:

- **kind conflicts** — one metric name registered as two instrument kinds
  anywhere in the tree. The runtime guard only fires when both sites run in
  ONE process; two processes would each run fine and corrupt the merged
  fleet document (observability/aggregate.py drops + reports the conflict
  — this rule keeps it from ever landing).
- **naming** — instrument names must be snake_case AND carry the ``ts_``
  namespace prefix (grep-ability; Prometheus exposition).
- **label cardinality** — label keys used at instrument call sites
  (``.inc``/``.set``/``.dec``/``.observe`` on module-level instruments)
  must come from the bounded-key allowlist. Keys like ``key=`` or
  ``session=`` create one series per key/session — unbounded memory in
  every process and a useless merged snapshot. Bounded new keys are added
  to ``ALLOWED_LABEL_KEYS`` deliberately, in review.
- **span names** — ``span("...")`` literals must match
  ``[a-z][a-z0-9_./]*`` so traces group cleanly in Perfetto (f-string
  constant fragments are checked too: ``span(f"rpc/{m}")`` passes,
  ``span(f"RPC {m}")`` does not).
- **docs table drift** — docs/API.md carries a GENERATED metrics
  reference table between markers (like the env-var table), rebuilt from
  a static scan of every instrument registration site by
  ``python scripts/tslint.py --regen-metric-docs``. A registration added,
  renamed, or re-worded without regenerating fails this rule — the table
  can never silently drift from the tree. (Projects without docs/API.md
  — fixture trees — skip this rule.)
"""

from __future__ import annotations

import ast
import os
import re
import sys

from torchstore_tpu.analysis.core import Finding, Project

RULE = "metric-discipline"

NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
METRIC_PREFIX = "ts_"
SPAN_NAME_RE = re.compile(r"^[a-z][a-z0-9_./]*$")
SPAN_FRAGMENT_RE = re.compile(r"^[a-z0-9_./]*$")
INSTRUMENT_CALLS = {"counter", "gauge", "histogram"}
_USE_METHODS = {"inc", "dec", "set", "observe"}

# Bounded label keys (fleet-size / enum cardinality). Adding a key here is a
# deliberate, reviewed act — ask "how many distinct values can this take in
# one process's lifetime?" before extending.
ALLOWED_LABEL_KEYS = {
    "op",
    "transport",
    "outcome",
    "volume",
    "channel",
    "stage",
    "kind",
    "replicas",
    "leg",
    "direction",
    "process",
    "volume_id",
    "task",
    "reason",
    "phase",
    "rule",
    # Faultpoint metrics: one series per (site, action) — both enums are
    # closed sets in faults.py (REGISTRY, ACTIONS).
    "point",
    "action",
    # Quant wire tier: one series per mode — a closed set
    # (state_dict_utils.QUANT_MODES).
    "fmt",
    # Control plane: one series per admission tenant (tenants are a small
    # deployment-configured cohort set, not per-key) and per reconcile
    # trigger — a closed set ("interval", "manual", "plan").
    "tenant",
    "trigger",
    # SLO violations: one series per configured TORCHSTORE_TPU_SLO_* knob
    # (a small operator-set family, observability/timeline.py).
    "slo",
    # Metadata mirror feed: one series per stamped segment source — the
    # coordinator plus one per index shard (metadata/mirror.py), a
    # deployment-sized closed set.
    "source",
    # Metadata-plane inflight: one series per controller shard
    # ("coord"/"s<i>" — bounded by controller_shards, metadata/router.py).
    "shard",
    # Trend plane: one series per detector in the stock catalog
    # (observability/detect.py default_detectors — a closed, code-reviewed
    # set; history-discipline pins each one to a registered instrument).
    "detector",
}


def collect_sites(root: str, project: Project | None = None):
    """Every (file, line, metric_name, kind) instrument call site with a
    string-literal first argument under the scanned tree. Kept
    signature-compatible with the old scripts/check_metric_names.py."""
    if project is None:
        project = Project(root)
    sites: list[tuple[str, int, str, str]] = []
    for sf in project.files:
        if sf.tree is None:
            print(
                f"check_metric_names: cannot parse {sf.abspath}: {sf.parse_error}",
                file=sys.stderr,
            )
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _call_name(node)
            if kind not in INSTRUMENT_CALLS or not node.args:
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant) and isinstance(first.value, str)):
                continue  # dynamic names (registry internals) are not sites
            sites.append((sf.path, node.lineno, first.value, kind))
    return sites


def _call_name(node: ast.Call) -> str | None:
    func = node.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


# --- generated docs table (docs/API.md) -----------------------------------

METRIC_DOCS_BEGIN = (
    "<!-- tslint-metric-table:begin (generated by scripts/tslint.py "
    "--regen-metric-docs; do not edit by hand) -->"
)
METRIC_DOCS_END = "<!-- tslint-metric-table:end -->"


def collect_instruments(root: str, project: Project | None = None):
    """Every instrument registration with its help string:
    ``(path, line, name, kind, help)``. The second positional arg (or the
    ``help=`` keyword) is taken when it is a string literal."""
    if project is None:
        project = Project(root)
    out: list[tuple[str, int, str, str, str]] = []
    for sf in project.files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _call_name(node)
            if kind not in INSTRUMENT_CALLS or not node.args:
                continue
            first = node.args[0]
            if not (
                isinstance(first, ast.Constant)
                and isinstance(first.value, str)
            ):
                continue
            help_text = ""
            if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
                if isinstance(node.args[1].value, str):
                    help_text = node.args[1].value
            else:
                for kw in node.keywords:
                    if (
                        kw.arg == "help"
                        and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)
                    ):
                        help_text = kw.value.value
            out.append((sf.path, node.lineno, first.value, kind, help_text))
    return out


def render_metric_table(instruments) -> str:
    """One row per metric NAME (registrations are get-or-create: many call
    sites share one instrument; the first non-empty help wins, matching
    MetricsRegistry semantics where the creator's help sticks)."""
    by_name: dict[str, tuple[str, str]] = {}
    for _path, _line, name, kind, help_text in instruments:
        kind_now, help_now = by_name.get(name, (kind, ""))
        by_name[name] = (kind_now, help_now or help_text)
    lines = [
        "| Metric | Kind | Description |",
        "|---|---|---|",
    ]
    for name, (kind, help_text) in sorted(by_name.items()):
        doc = " ".join(help_text.split()).replace("|", "\\|")
        lines.append(f"| `{name}` | {kind} | {doc} |")
    return "\n".join(lines)


def check_names(root: str, sites=None, project: Project | None = None) -> list[str]:
    """Namespace violations as strings (the historical shim contract)."""
    if sites is None:
        sites = collect_sites(root, project)
    problems: list[str] = []
    by_name: dict[str, dict[str, list[str]]] = {}
    for path, line, name, kind in sites:
        if not NAME_RE.match(name):
            problems.append(
                f"{path}:{line}: metric name {name!r} is not snake_case "
                "([a-z][a-z0-9_]*)"
            )
        by_name.setdefault(name, {}).setdefault(kind, []).append(f"{path}:{line}")
    for name, kinds in sorted(by_name.items()):
        if len(kinds) > 1:
            detail = "; ".join(
                f"{kind} at {', '.join(locs)}" for kind, locs in sorted(kinds.items())
            )
            problems.append(
                f"metric {name!r} registered with conflicting kinds: {detail}"
            )
    return problems


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    sites = collect_sites(project.root, project)

    # --- ported rules: snake_case + kind conflicts (+ ts_ prefix) ---------
    by_name: dict[str, dict[str, list[tuple[str, int]]]] = {}
    for path, line, name, kind in sites:
        if not NAME_RE.match(name):
            findings.append(
                Finding(
                    RULE,
                    path,
                    line,
                    f"metric name {name!r} is not snake_case ([a-z][a-z0-9_]*)",
                )
            )
        elif not name.startswith(METRIC_PREFIX):
            findings.append(
                Finding(
                    RULE,
                    path,
                    line,
                    f"metric name {name!r} lacks the {METRIC_PREFIX!r} "
                    "namespace prefix every store instrument carries",
                )
            )
        by_name.setdefault(name, {}).setdefault(kind, []).append((path, line))
    for name, kinds in sorted(by_name.items()):
        if len(kinds) > 1:
            detail = "; ".join(
                f"{kind} in {', '.join(sorted({p for p, _ in locs}))}"
                for kind, locs in sorted(kinds.items())
            )
            first_path, first_line = next(iter(sorted(kinds.items())))[1][0]
            findings.append(
                Finding(
                    RULE,
                    first_path,
                    first_line,
                    f"metric {name!r} registered with conflicting kinds: {detail}",
                )
            )

    # --- label cardinality on module-level instruments --------------------
    for sf in project.files:
        if sf.tree is None:
            continue
        instruments: set[str] = set()
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if _call_name(node.value) in INSTRUMENT_CALLS:
                    instruments.update(
                        t.id for t in node.targets if isinstance(t, ast.Name)
                    )
        if not instruments:
            continue
        for node in ast.walk(sf.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _USE_METHODS
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in instruments
            ):
                continue
            for kw in node.keywords:
                if kw.arg is None or kw.arg == "n":
                    continue
                if kw.arg not in ALLOWED_LABEL_KEYS:
                    findings.append(
                        Finding(
                            RULE,
                            sf.path,
                            node.lineno,
                            f"label key {kw.arg!r} on instrument "
                            f"{node.func.value.id!r} is not in the bounded-"
                            "cardinality allowlist (one series per distinct "
                            "value; add to ALLOWED_LABEL_KEYS only if the "
                            "value set is provably small)",
                        )
                    )

    # --- span-name discipline ---------------------------------------------
    for sf in project.files:
        if sf.tree is None or sf.path == "torchstore_tpu/observability/tracing.py":
            continue
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call) and _call_name(node) == "span"):
                continue
            if not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str):
                if not SPAN_NAME_RE.match(first.value):
                    findings.append(
                        Finding(
                            RULE,
                            sf.path,
                            node.lineno,
                            f"span name {first.value!r} must match "
                            "[a-z][a-z0-9_./]* (lowercase dotted/slashed "
                            "path, no spaces)",
                        )
                    )
            elif isinstance(first, ast.JoinedStr):
                for part in first.values:
                    if isinstance(part, ast.Constant) and isinstance(part.value, str):
                        if not SPAN_FRAGMENT_RE.match(part.value):
                            findings.append(
                                Finding(
                                    RULE,
                                    sf.path,
                                    node.lineno,
                                    f"span name fragment {part.value!r} "
                                    "contains characters outside "
                                    "[a-z0-9_./]",
                                )
                            )
                            break

    # --- docs/API.md generated metrics table drift ------------------------
    docs_path = os.path.join(project.root, "docs", "API.md")
    rel = "docs/API.md"
    if os.path.exists(docs_path):
        with open(docs_path, encoding="utf-8") as f:
            docs = f.read()
        if METRIC_DOCS_BEGIN not in docs or METRIC_DOCS_END not in docs:
            findings.append(
                Finding(
                    RULE,
                    rel,
                    1,
                    "docs/API.md lacks the generated metrics-table "
                    "markers; run python scripts/tslint.py "
                    "--regen-metric-docs",
                )
            )
        else:
            block = (
                docs.split(METRIC_DOCS_BEGIN, 1)[1]
                .split(METRIC_DOCS_END, 1)[0]
                .strip()
            )
            expected = render_metric_table(
                collect_instruments(project.root, project)
            ).strip()
            if block != expected:
                findings.append(
                    Finding(
                        RULE,
                        rel,
                        1,
                        "docs/API.md metrics table is stale (does not "
                        "match the tree's instrument registrations); run "
                        "python scripts/tslint.py --regen-metric-docs",
                    )
                )
    return findings


def main() -> int:
    """Entry point kept for the scripts/check_metric_names.py shim."""
    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    )
    sites = collect_sites(root)
    problems = check_names(root, sites)
    if problems:
        for problem in problems:
            print(f"check_metric_names: {problem}", file=sys.stderr)
        print(
            f"check_metric_names: FAILED ({len(problems)} problem(s) across "
            f"{len(sites)} instrument call sites)",
            file=sys.stderr,
        )
        return 1
    names = {name for _, _, name, _ in sites}
    print(
        f"check_metric_names: OK — {len(sites)} call sites, "
        f"{len(names)} distinct metric names, no conflicts"
    )
    return 0
