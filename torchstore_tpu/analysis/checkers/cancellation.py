"""cancellation-swallow: coroutines must let CancelledError escape.

``asyncio.CancelledError`` derives from ``BaseException`` precisely so that
``except Exception`` cannot eat it — but a bare ``except:``, an
``except BaseException:``, or an explicit ``except CancelledError`` handler
that fails to re-raise swallows cancellation silently. The symptom is a
task that .cancel() cannot stop: stop() hangs for its full timeout, fleets
leak processes, tests wedge (PR 3's review pass hand-fixed this class on
the prewarm paths).

Rule: inside any ``async def``, an except handler that can catch
``CancelledError`` (bare / BaseException / CancelledError, alone or in a
tuple) must contain a ``raise``. A preceding ``except asyncio.CancelledError:
raise`` handler in the same ``try`` satisfies the rule for the broad
handlers after it (the standard forward-the-error idiom in
runtime/actors.py's dispatcher).
"""

from __future__ import annotations

import ast

from torchstore_tpu.analysis.core import Finding, Project, dotted_name, walk_scope

RULE = "cancellation-swallow"


def _catches(handler: ast.ExceptHandler) -> tuple[bool, bool, str]:
    """(catches_cancellation, is_cancel_only, description)."""
    if handler.type is None:
        return True, False, "bare except:"
    exprs = (
        list(handler.type.elts)
        if isinstance(handler.type, ast.Tuple)
        else [handler.type]
    )
    names = [dotted_name(e) or "?" for e in exprs]
    tails = {n.rsplit(".", 1)[-1] for n in names}
    catches = bool(tails & {"BaseException", "CancelledError", "KeyboardInterrupt"})
    cancel_only = tails <= {"CancelledError"}
    return catches, cancel_only, f"except ({', '.join(names)})"


def _reraises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in walk_scope(handler.body))


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        if sf.tree is None:
            continue
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in walk_scope(fn.body):
                if not isinstance(node, ast.Try):
                    continue
                cancel_reraised_earlier = False
                for handler in node.handlers:
                    catches, cancel_only, desc = _catches(handler)
                    if not catches:
                        continue
                    if _reraises(handler):
                        cancel_reraised_earlier = True
                        continue
                    if cancel_reraised_earlier and not cancel_only:
                        continue  # CancelledError already re-raised above
                    findings.append(
                        Finding(
                            RULE,
                            sf.path,
                            handler.lineno,
                            f"{desc} in async def {fn.name!r} swallows "
                            "asyncio.CancelledError (no re-raise): narrow "
                            "to except Exception, or re-raise",
                        )
                    )
    return findings
