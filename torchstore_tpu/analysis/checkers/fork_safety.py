"""fork-safety: module-level mutable state needs a fork story.

The actor runtime spawns children via forkserver, and the forkserver helper
preloads ``torchstore_tpu.runtime`` — so every module imported by that
preload has its module-level state SNAPSHOTTED at the helper's start and
inherited by every actor child. PR 2 fixed a whole class of bugs this
caused by hand (dumper/exporter threads that didn't survive the fork, a
trace collector claiming a dead run's file); the fix was per-facility
``reinit_after_fork`` hooks re-armed in ``_child_main``.

Rule: a module that creates mutable state at import time — dict/list/set
registries, ``threading`` primitives, sockets — must either define a
``reinit_after_fork`` hook (the convention ``runtime/actors.py`` re-arms in
children), call ``os.register_at_fork``, or annotate each benign global
with a ``# tslint: disable=fork-safety`` pragma whose comment explains why
stale inheritance is safe (e.g. keyed by event loop and pruned, or only
ever populated post-fork).
"""

from __future__ import annotations

import ast
import re

from torchstore_tpu.analysis.core import Finding, Project, dotted_name

RULE = "fork-safety"

_MUTABLE_CALLS = {
    "dict",
    "list",
    "set",
    "defaultdict",
    "deque",
    "OrderedDict",
    "Counter",
    "WeakValueDictionary",
    "WeakKeyDictionary",
    "WeakSet",
}
_PRIMITIVE_CALLS = {
    "Thread",
    "Lock",
    "RLock",
    "Condition",
    "Event",
    "Semaphore",
    "BoundedSemaphore",
    "Barrier",
    "local",
    "socket",
    "Queue",
    "LifoQueue",
    "PriorityQueue",
}

_EXEMPT_NAMES = {"__all__"}
# Constant-convention globals (ALL_CAPS) are rule tables, never mutated;
# inheriting them across a fork is exactly as safe as re-importing them.
_CONST_RE = re.compile(r"^_?[A-Z][A-Z0-9_]*$")


def _mutable_kind(value: ast.expr) -> str | None:
    if isinstance(value, ast.Dict):
        return "dict literal"
    if isinstance(value, ast.List):
        return "list literal"
    if isinstance(value, ast.Set):
        return "set literal"
    if isinstance(value, ast.Call):
        tail = None
        if isinstance(value.func, ast.Name):
            tail = value.func.id
        elif isinstance(value.func, ast.Attribute):
            tail = value.func.attr
        if tail in _MUTABLE_CALLS:
            return f"{tail}()"
        if tail in _PRIMITIVE_CALLS:
            dn = dotted_name(value.func) or tail
            return f"{dn}() sync/thread/socket primitive"
    return None


def _has_fork_story(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name in ("reinit_after_fork", "_reinit_after_fork")
        ):
            return True
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            if dn == "os.register_at_fork":
                return True
    return False


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        if sf.tree is None or not sf.path.startswith("torchstore_tpu/"):
            continue  # scripts/benches never run inside forked actors
        if _has_fork_story(sf.tree):
            continue
        for node in sf.tree.body:
            targets: list[ast.expr] = []
            value: ast.expr | None = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is None:
                continue
            kind = _mutable_kind(value)
            if kind is None:
                continue
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if not names or all(
                n in _EXEMPT_NAMES or _CONST_RE.match(n) for n in names
            ):
                continue
            findings.append(
                Finding(
                    RULE,
                    sf.path,
                    node.lineno,
                    f"module-level mutable state {'/'.join(names)!s} "
                    f"({kind}) in a module with no reinit_after_fork/"
                    "register_at_fork hook: forkserver children inherit "
                    "this object's pre-fork contents",
                )
            )
    return findings
