"""landing-copy: transport/landing modules never call bare ``np.copyto``.

Every copy that lands fetched or staged bytes must go through the native
helpers in ``torchstore_tpu/native.py`` (``copy_into`` / ``fast_copy``):

- they take the multi-threaded native path (contiguous memcpy + strided
  row-block) on large payloads — a bare ``np.copyto`` silently forfeits the
  data plane's throughput on exactly the hot copies;
- they REFUSE to broadcast (shapes must match exactly), so a stale-plan or
  stale-metadata fetch fails loudly instead of smearing a wrong-shaped
  payload across the destination (the ``fast_copy`` no-broadcast rule,
  native.py).

The rule covers the transport package and the landing-heavy client modules
(client.py, direct_weight_sync.py, state_dict_utils.py). ``native.py``
itself is exempt — it IS the fallback implementation. Non-landing modules
(torch interop conversion, tests) are out of scope.
"""

from __future__ import annotations

import ast

from torchstore_tpu.analysis.core import Finding, Project, dotted_name

RULE = "landing-copy"

# Modules whose copies land transport/staging bytes. native.py is the one
# transport-adjacent file allowed to spell np.copyto (it is the fallback).
_SCOPED_PREFIXES = ("torchstore_tpu/transport/",)
_SCOPED_FILES = (
    "torchstore_tpu/client.py",
    "torchstore_tpu/direct_weight_sync.py",
    "torchstore_tpu/state_dict_utils.py",
)
_EXEMPT = ("torchstore_tpu/native.py",)

_MESSAGE = (
    "bare np.copyto in a transport/landing module: use native.copy_into / "
    "native.fast_copy (multi-threaded native path, no silent broadcast)"
)


def _in_scope(path: str) -> bool:
    if path in _EXEMPT:
        return False
    return path.startswith(_SCOPED_PREFIXES) or path in _SCOPED_FILES


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        if sf.tree is None or not _in_scope(sf.path):
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            if dotted in ("np.copyto", "numpy.copyto"):
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.path,
                        line=node.lineno,
                        message=_MESSAGE,
                    )
                )
    return findings
