"""async-blocking: no synchronous blocking calls on the event loop.

Every actor endpoint runs on its process's single event loop; one blocking
call inside an ``async def`` stalls every in-flight RPC that process serves
(the SHM pool's MAP_POPULATE prefault at 0.1-0.2 s/GB was exactly this bug
before it moved to an executor thread). The checker flags a curated set of
known-blocking calls inside ``async def`` bodies. Nested synchronous
``def``/``lambda`` bodies are exempt — that is the executor-thunk idiom
(``loop.run_in_executor(None, fn)``).

Legitimate exceptions (startup-only paths, sub-millisecond file reads)
carry a ``# tslint: disable=async-blocking`` pragma with a justification
comment, or live in the baseline.
"""

from __future__ import annotations

import ast

from torchstore_tpu.analysis.core import Finding, Project, call_tail, dotted_name, walk_scope

RULE = "async-blocking"

# dotted-call suffixes that block the calling thread.
_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep() blocks the event loop; use await asyncio.sleep()",
    "os.system": "os.system() blocks the event loop",
    "os.popen": "os.popen() blocks the event loop",
    "os.waitpid": "os.waitpid() blocks the event loop",
    "subprocess.run": "subprocess.run() blocks the event loop",
    "subprocess.call": "subprocess.call() blocks the event loop",
    "subprocess.check_call": "subprocess.check_call() blocks the event loop",
    "subprocess.check_output": "subprocess.check_output() blocks the event loop",
    "shutil.copy": "sync file IO blocks the event loop",
    "shutil.copy2": "sync file IO blocks the event loop",
    "shutil.copyfile": "sync file IO blocks the event loop",
    "shutil.copytree": "sync file IO blocks the event loop",
    "shutil.rmtree": "sync file IO blocks the event loop",
    "socket.create_connection": "blocking connect; use loop.sock_connect",
    "socket.getaddrinfo": "blocking DNS resolution; use loop.getaddrinfo",
}

# bare-name calls
_BLOCKING_NAMES = {
    "open": "sync file IO in a coroutine blocks the event loop (move to an "
    "executor thread, or pragma startup-only reads)",
}

# method tails flagged regardless of receiver
_BLOCKING_TAILS = {
    "ts_prefault": "native prefault releases the GIL but still blocks THIS "
    "thread; run it via loop.run_in_executor",
}


def blocking_reason(node: ast.Call) -> str | None:
    """Why this call blocks the calling thread, or None if it is not in the
    known-blocking table. Shared with await-atomicity, which bans the same
    calls inside seqlock publish brackets (where a stalled thread wedges
    every reader, async or not)."""
    dotted = dotted_name(node.func)
    tail = call_tail(node)
    if dotted is not None and dotted in _BLOCKING_DOTTED:
        return _BLOCKING_DOTTED[dotted]
    if isinstance(node.func, ast.Name) and node.func.id in _BLOCKING_NAMES:
        return _BLOCKING_NAMES[node.func.id]
    if tail in _BLOCKING_TAILS:
        return _BLOCKING_TAILS[tail]
    if (
        isinstance(node.func, ast.Attribute)
        and node.func.attr == "result"
        and not node.args
        and not node.keywords
    ):
        return (
            ".result() on a concurrent Future blocks the event "
            "loop (await it, or asyncio.wrap_future first)"
        )
    return None


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        if sf.tree is None:
            continue
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in walk_scope(fn.body):
                if not isinstance(node, ast.Call):
                    continue
                msg = blocking_reason(node)
                if msg is not None:
                    findings.append(
                        Finding(
                            RULE,
                            sf.path,
                            node.lineno,
                            f"blocking call in async def {fn.name!r}: {msg}",
                        )
                    )
    return findings
