"""stream-discipline: watermark checks go through the blessed helpers.

The layer-streamed sync protocol (PR 9, torchstore_tpu/stream_sync.py) is
only sound because every served key's version watermark is validated the
same way: ``stream_sync.watermark_of`` / ``stream_sync.inconsistent_keys``
own the exact-equality rule ("every served key must carry the target
version watermark; newer IS mixed-generation") and the None-handling for
evicted/restarted records. Acquire-side code that reads the raw
``watermarks`` dict out of a stream-state reply (or compares versions by
hand) re-derives that rule — and the first drift (a ``>=`` instead of
``==``, a missing None guard) silently reintroduces the mixed-generation
reads the watermark protocol exists to kill.

Rule: in the acquire-side modules (client.py, direct_weight_sync.py,
state_dict_utils.py, weight_channel.py, api.py), any subscript or
``.get(...)`` whose key is the string literal ``"watermarks"`` is
forbidden — route the check through the blessed helpers instead.
``stream_sync.py`` (the helpers' home) and the controller (the protocol's
server side) are out of scope.
"""

from __future__ import annotations

import ast

from torchstore_tpu.analysis.core import Finding, Project

RULE = "stream-discipline"

_SCOPED_FILES = (
    "torchstore_tpu/client.py",
    "torchstore_tpu/direct_weight_sync.py",
    "torchstore_tpu/state_dict_utils.py",
    "torchstore_tpu/weight_channel.py",
    "torchstore_tpu/api.py",
)

_MESSAGE = (
    "raw stream-watermark read in an acquire-side module: check served "
    "keys through stream_sync.watermark_of / stream_sync.inconsistent_keys "
    "(the blessed helpers own the exact-version consistency rule) — a "
    "hand-rolled read can silently serve mixed-generation weights"
)


def _is_watermarks_literal(node: ast.expr) -> bool:
    return isinstance(node, ast.Constant) and node.value == "watermarks"


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        if sf.tree is None or sf.path not in _SCOPED_FILES:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Subscript) and _is_watermarks_literal(
                node.slice
            ):
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.path,
                        line=node.lineno,
                        message=_MESSAGE,
                    )
                )
                continue
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and node.args
                and _is_watermarks_literal(node.args[0])
            ):
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.path,
                        line=node.lineno,
                        message=_MESSAGE,
                    )
                )
    return findings
