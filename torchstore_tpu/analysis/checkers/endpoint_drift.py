"""endpoint-drift: stub/mesh RPC call sites must match a real ``@endpoint``.

The actor runtime dispatches by name: ``runtime/actors.py`` resolves
``msg["method"]`` with ``getattr`` + the ``_ENDPOINT_ATTR`` flag, and
``ActorRef.__getattr__`` happily builds an endpoint ref for ANY attribute.
A typo'd method or a re-signatured endpoint therefore raises only at
runtime, deep inside a fleet test ("RPC Considered Harmful", PAPERS.md).

This checker cross-references every ``<ref>.<method>.call_one(...)`` /
``.call(...)`` / ``.with_timeout(...).call_one(...)`` site against the
``@endpoint``-decorated methods collected from every ``Actor`` class in the
tree, including arity and keyword compatibility. Single-level local aliases
are resolved (``put = volume.actor.put; await put.with_timeout(t).call_one(..)``).
Dynamic dispatch (``getattr(ref, name)``) is invisible to the checker and
deliberately skipped.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from torchstore_tpu.analysis.core import Finding, Project, iter_function_scopes, walk_scope

RULE = "endpoint-drift"

_CALL_METHODS = ("call", "call_one")


@dataclass(frozen=True)
class EndpointSig:
    cls: str
    path: str
    params: tuple[str, ...]  # positional(+kw) params, self excluded
    defaults: int  # how many trailing params have defaults
    vararg: bool
    kwonly: tuple[str, ...]
    kwonly_required: tuple[str, ...]
    kwarg: bool

    def describe(self) -> str:
        parts = list(self.params)
        if self.vararg:
            parts.append("*args")
        parts.extend(self.kwonly)
        if self.kwarg:
            parts.append("**kwargs")
        return f"{self.cls}.({', '.join(parts)})"

    def accepts(self, n_pos: int, kwargs: set[str]) -> bool:
        if not self.vararg and n_pos > len(self.params):
            return False
        bound = set(self.params[:n_pos])
        for kw in kwargs:
            if kw in bound:
                return False  # duplicate binding
            if kw in self.params or kw in self.kwonly:
                bound.add(kw)
            elif not self.kwarg:
                return False
        required = set(self.params[: len(self.params) - self.defaults])
        required.update(self.kwonly_required)
        return required <= bound | set(self.params[:n_pos])


def collect_endpoints(project: Project) -> dict[str, list[EndpointSig]]:
    endpoints: dict[str, list[EndpointSig]] = {}
    for sf in project.files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for item in node.body:
                if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if not any(
                    (isinstance(d, ast.Name) and d.id == "endpoint")
                    or (isinstance(d, ast.Attribute) and d.attr == "endpoint")
                    for d in item.decorator_list
                ):
                    continue
                a = item.args
                params = tuple(x.arg for x in a.args[1:])  # drop self
                kwonly = tuple(x.arg for x in a.kwonlyargs)
                kw_required = tuple(
                    x.arg
                    for x, dflt in zip(a.kwonlyargs, a.kw_defaults)
                    if dflt is None
                )
                endpoints.setdefault(item.name, []).append(
                    EndpointSig(
                        cls=node.name,
                        path=sf.path,
                        params=params,
                        defaults=len(a.defaults),
                        vararg=a.vararg is not None,
                        kwonly=kwonly,
                        kwonly_required=kw_required,
                        kwarg=a.kwarg is not None,
                    )
                )
    return endpoints


def _method_of(call: ast.Call, aliases: dict[str, str]) -> tuple[str | None, bool]:
    """(endpoint method name, resolvable) for a ``.call``/``.call_one`` Call.

    Handles ``<expr>.<method>.call_one(..)`` and the ``with_timeout`` chain
    ``<expr>.<method>.with_timeout(t).call_one(..)`` plus one level of local
    alias (``put = volume.actor.put``). Returns (None, False) when the
    receiver is dynamic (getattr, subscripts, ...) — those are skipped.
    """
    base = call.func.value  # type: ignore[union-attr]
    if (
        isinstance(base, ast.Call)
        and isinstance(base.func, ast.Attribute)
        and base.func.attr == "with_timeout"
    ):
        base = base.func.value
    if isinstance(base, ast.Attribute):
        return base.attr, True
    if isinstance(base, ast.Name):
        alias = aliases.get(base.id)
        return (alias, True) if alias is not None else (None, False)
    return None, False


def check(project: Project) -> list[Finding]:
    endpoints = collect_endpoints(project)
    findings: list[Finding] = []
    if not endpoints:
        return findings  # tree defines no actors; nothing to drift from
    for sf in project.files:
        if sf.tree is None:
            continue
        for _fn, body in iter_function_scopes(sf.tree):
            # One-level alias map for this scope: name <- trailing attribute
            # of a plain attribute-chain assignment.
            aliases: dict[str, str] = {}
            for node in walk_scope(body):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Attribute)
                ):
                    aliases[node.targets[0].id] = node.value.attr
            for node in walk_scope(body):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _CALL_METHODS
                ):
                    continue
                method, ok = _method_of(node, aliases)
                if not ok or method is None:
                    continue
                if method.startswith("_") or method in (
                    "call",
                    "call_one",
                    "with_timeout",
                ):
                    continue
                sigs = endpoints.get(method)
                if sigs is None:
                    findings.append(
                        Finding(
                            RULE,
                            sf.path,
                            node.lineno,
                            f"RPC to unknown endpoint {method!r}: no actor "
                            "class defines an @endpoint method with this "
                            "name (typo or removed endpoint?)",
                        )
                    )
                    continue
                if any(isinstance(a, ast.Starred) for a in node.args) or any(
                    kw.arg is None for kw in node.keywords
                ):
                    continue  # *args/**kwargs call: arity unknowable
                n_pos = len(node.args)
                kwargs = {kw.arg for kw in node.keywords if kw.arg is not None}
                if not any(sig.accepts(n_pos, kwargs) for sig in sigs):
                    cands = "; ".join(sorted(s.describe() for s in sigs))
                    kwtxt = f" + kwargs {sorted(kwargs)}" if kwargs else ""
                    findings.append(
                        Finding(
                            RULE,
                            sf.path,
                            node.lineno,
                            f"RPC to endpoint {method!r} with {n_pos} "
                            f"positional arg(s){kwtxt} matches no endpoint "
                            f"signature (candidates: {cands})",
                        )
                    )
    return findings
