"""state_dict sync layer: flatten / commit-marker / dtype-cast / unflatten.

TPU-native equivalent of /root/reference/torchstore/state_dict_utils.py:27-275.
Protocol (invariant 3, SURVEY §2.2): all tensor entries are put under
``key/<flat_path>`` first, then ``key/MAPPING`` is written LAST as the commit
marker — its presence implies a complete state dict; readers fetch it first
and fail with "no matching push" when absent.

Flattening is dependency-free (dict / list / tuple / NamedTuple recursion)
so it handles flax param trees, optax optimizer states and plain nested
dicts without importing jax; leaves may be jax.Arrays (sharded puts/gets go
through the normal resharding pipeline), numpy arrays, or arbitrary objects.
"""

from __future__ import annotations

import weakref
from typing import Any, Optional

import numpy as np

from torchstore_tpu import sharding as shd
from torchstore_tpu import torch_interop
from torchstore_tpu.logging import LatencyTracker, get_logger
from torchstore_tpu.native import copy_into
from torchstore_tpu.transport.types import _np_dtype  # bf16-aware name->dtype

logger = get_logger("torchstore_tpu.state_dict")

MAPPING_KEY = "MAPPING"
_SEP = "/"


class NoMatchingPush(KeyError):
    pass


# --------------------------------------------------------------------------
# flatten / unflatten
# --------------------------------------------------------------------------


def _is_leaf(value: Any) -> bool:
    if isinstance(value, dict):
        return False
    if isinstance(value, (list, tuple)):
        return False
    return True


def _axis_metadata_box(value: Any):
    """The flax AxisMetadata box wrapping ``value``, or None. Trees straight
    out of ``model.init`` with ``nn.with_logical_partitioning`` carry
    LogicallyPartitioned/Partitioned leaves; stored boxed, their jax arrays
    would ride the opaque object path (no resharding, full-serialize puts).
    Flatten unboxes them — the array takes the tensor path — and records the
    empty box in the mapping so unflatten restores the exact structure."""
    try:
        from flax.core import meta as flax_meta
    except ImportError:  # pragma: no cover - flax is in this image
        return None
    if isinstance(value, flax_meta.AxisMetadata):
        return value.replace_boxed(None)
    return None


def flatten_state_dict(sd: Any) -> tuple[dict[str, Any], dict]:
    """Returns ({flat_path: leaf}, mapping). ``mapping`` is a picklable
    template that records the container structure (incl. NamedTuple types by
    import path) for exact reconstruction — the role DCP's
    ``flatten_state_dict`` plays in the reference."""
    flat: dict[str, Any] = {}
    mapping = _flatten_rec(sd, [], flat)
    return flat, mapping


def _flatten_rec(value: Any, path: list[str], flat: dict[str, Any]) -> dict:
    # Module-level recursion for the same reason as _unflatten_rec: an inner
    # closure would be a cycle pinning every leaf array until cyclic GC.
    if isinstance(value, dict):
        return {
            "kind": "dict",
            "items": {
                str(k): _flatten_rec(v, path + [str(k)], flat)
                for k, v in value.items()
            },
            "key_types": {str(k): _key_type(k) for k in value},
        }
    if isinstance(value, (list, tuple)):
        kind = "list" if isinstance(value, list) else "tuple"
        entry: dict = {
            "kind": kind,
            "items": [
                _flatten_rec(v, path + [str(i)], flat)
                for i, v in enumerate(value)
            ],
        }
        if isinstance(value, tuple) and hasattr(value, "_fields"):
            entry["kind"] = "namedtuple"
            entry["cls"] = f"{type(value).__module__}:{type(value).__qualname__}"
        return entry
    flat_key = _SEP.join(path)
    if flat_key in flat:
        raise ValueError(f"duplicate flattened key {flat_key!r}")
    box = _axis_metadata_box(value)
    if box is not None:
        flat[flat_key] = value.unbox()
        return {"kind": "boxed", "key": flat_key, "box": box}
    flat[flat_key] = value
    return {"kind": "leaf", "key": flat_key}


def _key_type(key: Any) -> str:
    if isinstance(key, int):
        return "int"
    return "str"


def unflatten_state_dict(flat: dict[str, Any], mapping: dict) -> Any:
    # Module-level recursion (not an inner closure): a self-referencing
    # closure is a reference cycle that pins ``flat``'s arrays — including
    # zero-copy SHM views — until the next cyclic GC pass, which defers
    # their release back to the storage volume.
    return _unflatten_rec(mapping, flat)


def _unflatten_rec(entry: dict, flat: dict[str, Any]) -> Any:
    kind = entry["kind"]
    if kind == "leaf":
        return flat[entry["key"]]
    if kind == "boxed":
        return entry["box"].replace_boxed(flat[entry["key"]])
    if kind == "dict":
        key_types = entry.get("key_types", {})
        return {
            (int(k) if key_types.get(k) == "int" else k): _unflatten_rec(v, flat)
            for k, v in entry["items"].items()
        }
    children = [_unflatten_rec(v, flat) for v in entry["items"]]
    if kind == "list":
        return children
    if kind == "tuple":
        return tuple(children)
    if kind == "namedtuple":
        cls = _resolve_class(entry["cls"])
        if cls is None:
            return tuple(children)
        return cls(*children)
    raise ValueError(f"corrupt mapping entry {entry!r}")


def _resolve_class(spec: str):
    mod_name, _, qual = spec.partition(":")
    try:
        import importlib

        obj = importlib.import_module(mod_name)
        for part in qual.split("."):
            obj = getattr(obj, part)
        return obj
    except Exception:
        logger.warning("cannot resolve NamedTuple class %s; using plain tuple", spec)
        return None


# --------------------------------------------------------------------------
# dtype cast
# --------------------------------------------------------------------------


def _is_floating(value: Any) -> bool:
    dtype = getattr(value, "dtype", None)
    if dtype is None:
        return False
    try:
        return np.issubdtype(np.dtype(dtype), np.floating) or "bfloat16" in str(dtype)
    except TypeError:
        return "float" in str(dtype)


def cast_floating_tensors(flat: dict[str, Any], transfer_dtype) -> dict[str, Any]:
    """Cast floating leaves to ``transfer_dtype`` before transfer (reference
    /root/reference/torchstore/state_dict_utils.py:177-189). jax.Arrays cast
    on-device (one fused XLA op per leaf); numpy casts on host."""
    out = {}
    for key, value in flat.items():
        if not _is_floating(value):
            out[key] = value
        elif torch_interop.is_torch_tensor(value):
            out[key] = torch_interop.astype_numpy(value, transfer_dtype)
        else:
            out[key] = value.astype(transfer_dtype)
    return out


# --------------------------------------------------------------------------
# int8 transfer quantization
# --------------------------------------------------------------------------




def quantize_int8(flat: dict[str, Any]) -> tuple[dict[str, Any], dict]:
    """Symmetric per-tensor int8 quantization of floating leaves: each
    becomes round(x/scale) int8 with scale = max|x|/127. Returns
    (quantized_flat, {"fmt", "scales", "dtypes"}) — the metadata rides the
    MAPPING commit marker so readers always find scales alongside a
    complete push. jax leaves quantize on-device (sharding preserved);
    torch leaves through their zero-copy views. 4x fewer wire/store bytes
    than f32, 2x fewer than bf16 — the cross-slice (DCN) weight-sync
    bandwidth optimization."""
    out: dict[str, Any] = {}
    scales: dict[str, float] = {}
    dtypes: dict[str, str] = {}
    converted = {
        key: (
            torch_interop.to_numpy_view(value)
            if torch_interop.is_torch_tensor(value)
            else value
        )
        for key, value in flat.items()
    }
    # Pass 1: ENQUEUE every jax reduction before syncing any (one overlapped
    # dispatch wave instead of a blocking device round trip per leaf).
    device_amax: dict[str, Any] = {}
    for key, value in converted.items():
        if _is_floating(value) and shd.is_jax_array(value):
            if not value.is_fully_addressable:
                # The scale must be GLOBAL and identical on every rank; an
                # eager max over a multi-controller array can't compute it
                # (and per-rank scales would decode inconsistently).
                raise NotImplementedError(
                    f"transfer_quant on non-fully-addressable array "
                    f"{key!r}: compute the quantized int8 array + scale "
                    "inside your jitted step (global max via a collective) "
                    "and push those, or use transfer_dtype instead"
                )
            if value.size:
                import jax.numpy as jnp

                device_amax[key] = jnp.max(
                    jnp.abs(value.astype(jnp.float32))
                )
    # Pass 2: quantize with the (now mostly ready) scales.
    for key, value in converted.items():
        if not _is_floating(value):
            out[key] = value
            continue
        dtypes[key] = str(value.dtype)
        if shd.is_jax_array(value):
            import jax.numpy as jnp

            amax = float(device_amax[key]) if key in device_amax else 0.0
            scale = _checked_scale(key, amax)
            out[key] = jnp.round(
                value.astype(jnp.float32) / scale
            ).astype(jnp.int8)
        else:
            arr = np.asarray(value).astype(np.float32, copy=False)
            amax = float(np.max(np.abs(arr))) if arr.size else 0.0
            scale = _checked_scale(key, amax)
            out[key] = np.round(arr / scale).astype(np.int8)
        scales[key] = scale
    return out, {"fmt": "int8", "scales": scales, "dtypes": dtypes}


def _checked_scale(key: str, amax: float) -> float:
    """max|x|/127 with non-finite inputs rejected LOUDLY: a NaN amax would
    silently fall back to scale=1 (zeroing typical sub-unit weights) and an
    Inf scale would dequantize to all-NaN — exactly the silent corruption a
    weight-sync layer must never pass along."""
    if not np.isfinite(amax):
        raise ValueError(
            f"cannot quantize {key!r}: contains non-finite values "
            f"(max|x| = {amax}); publish unquantized or clean the weights"
        )
    return amax / 127.0 if amax > 0 else 1.0


def _dequantize(q: Any, scale: float, dtype_name: str, target: Any = None):
    """int8 -> original dtype. ``target`` (numpy view of user memory) gets
    the result in place; jax arrays dequantize on-device (elementwise, so a
    resharded fetch keeps its sharding)."""
    if shd.is_jax_array(q):
        import jax.numpy as jnp

        return (q.astype(jnp.float32) * scale).astype(_np_dtype(dtype_name))
    dequant = q.astype(np.float32) * np.float32(scale)
    if target is not None:
        # Native landing path; raises on shape mismatch (no broadcast).
        copy_into(target, dequant.astype(target.dtype))
        return target
    return dequant.astype(_np_dtype(dtype_name))


def _quant_fetch_target(user_leaf: Any) -> Any:
    """Fetch target for a quantized entry: the stored bytes are int8, so
    user arrays can't land in place — jax targets fetch an int8 spec WITH
    their sharding (reshard happens on the quantized bytes, 4x cheaper;
    dequant runs on-device afterwards); everything else fetches plain."""
    if shd.is_jax_array(user_leaf) or shd.is_sharded_spec(user_leaf):
        import jax

        return jax.ShapeDtypeStruct(
            user_leaf.shape, np.int8, sharding=user_leaf.sharding
        )
    return None


def _dequant_result(got: Any, scale: float, dtype_name: str, user_leaf: Any):
    """Dequantize a fetched int8 payload toward the user's leaf: in place
    for numpy/torch targets (their objects are returned), on-device for jax
    targets, plain conversion otherwise."""
    if torch_interop.is_torch_tensor(user_leaf):
        view = torch_interop.to_numpy_view(user_leaf, allow_copy=False)
        _dequantize(np.asarray(got), scale, dtype_name, target=view)
        return user_leaf
    if isinstance(user_leaf, np.ndarray):
        return _dequantize(np.asarray(got), scale, dtype_name, target=user_leaf)
    if shd.is_jax_array(got):
        # Honor the TARGET's dtype like every other branch (a f32 spec over
        # a bf16-sourced push yields f32, the orbax restore idiom).
        want = (
            str(user_leaf.dtype) if hasattr(user_leaf, "dtype") else dtype_name
        )
        return _dequantize(got, scale, want)
    result = _dequantize(np.asarray(got), scale, dtype_name)
    if shd.is_plain_spec(user_leaf):
        import jax.numpy as jnp

        return jnp.asarray(result, dtype=user_leaf.dtype)
    return result


# --------------------------------------------------------------------------
# put / get
# --------------------------------------------------------------------------


def _store_key(key: str, flat_key: str) -> str:
    return f"{key}{_SEP}{flat_key}" if flat_key else key


# --------------------------------------------------------------------------
# iteration-stable transfer plans (client.SyncPlanCache integration)
# --------------------------------------------------------------------------


def _leaf_signature(value: Any) -> tuple:
    """Hashable shape/dtype/sharding signature of one flat leaf — the unit
    the plan cache keys on. Signature equality means the leaf decomposes
    into byte-identical requests, so a cached plan replays exactly."""
    if type(value) is np.ndarray:
        # Exact-type fast path first: plain numpy leaves dominate trainer
        # state dicts, and this runs per leaf per warm iteration — the
        # jax/shard probes below cost more than the whole signature.
        # (.shape is already a tuple; .str is a C attribute.)
        return ("np", value.shape, value.dtype.str)
    sig = shd.plan_signature(value)
    if sig is not None:
        return sig
    from torchstore_tpu.client import Shard

    if isinstance(value, Shard):
        ts = value.tensor_slice
        data_sig = (
            _leaf_signature(value.data) if value.data is not None else None
        )
        return (
            "shard",
            ts.offsets,
            ts.local_shape,
            ts.global_shape,
            ts.coordinates,
            data_sig,
        )
    if torch_interop.is_torch_tensor(value):
        return ("torch", tuple(value.shape), str(value.dtype))
    if isinstance(value, np.ndarray):
        # dtype.str (C attribute), not str(dtype): this runs per leaf per
        # warm iteration, and dtype.__str__'s name derivation was ~2ms per
        # 512-leaf signature on the warm get path. Signatures are opaque
        # cache keys, only ever compared to each other.
        return ("np", tuple(value.shape), value.dtype.str)
    return ("obj",)  # opaque objects re-pickle every iteration anyway


def _flat_signature(flat: dict, *extra) -> tuple:
    return tuple((k, _leaf_signature(v)) for k, v in flat.items()) + extra


def _arena_hint_from_flat(flat: dict, config) -> Optional[dict]:
    """Precompute the small-key arena layout for a flat dict of PLAIN numpy
    leaves (the common trainer-host case). Any leaf whose request fan-out
    this function cannot see exactly (jax shards, torch views, Shards)
    returns None — the transport derives the layout itself and validates
    any hint against the real request set regardless."""
    if config is None or config.arena_max_bytes <= 0:
        return None
    from torchstore_tpu.transport import landing

    sizes: list[int] = []
    for value in flat.values():
        if isinstance(value, np.ndarray):
            if value.nbytes <= config.arena_max_bytes:
                sizes.append(int(value.nbytes))
            continue
        if _is_fetch_target(value):  # jax/torch/Shard: fan-out not 1:1 here
            return None
    if len(sizes) < 2:
        return None
    offsets, total = landing.compute_arena_layout(sizes)
    return {"sizes": tuple(sizes), "offsets": offsets, "total": total}


class _DirectSyncCache:
    """Per-client registry of direct-sync sources/dests keyed by state-dict
    key (the reference's _DirectRDMACache,
    /root/reference/torchstore/state_dict_utils.py:27-45)."""

    def __init__(self) -> None:
        self.sources: dict[str, Any] = {}
        # key -> (dest, all_handles, device_info)
        self.dests: dict[str, tuple[Any, dict, Any]] = {}

    async def close(self) -> None:
        for source in self.sources.values():
            await source.close()
        for entry in self.dests.values():
            await entry[0].close()
        self.sources.clear()
        self.dests.clear()


# Weakly keyed by the client object: a GC'd client cannot hand its cache to
# an unrelated new client via id() reuse.
# Weak client keys cannot survive a fork (children build fresh clients), so
# inherited entries are unreachable garbage at worst, never stale hits.
_direct_caches: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()  # tslint: disable=fork-safety


def _direct_cache(client) -> _DirectSyncCache:
    cache = _direct_caches.get(client)
    if cache is None:
        cache = _DirectSyncCache()
        _direct_caches[client] = cache
    return cache


async def close_direct_caches(client) -> None:
    """Release SHM segments / peer-server sockets held for this client's
    direct-sync sessions (called from shutdown paths)."""
    cache = _direct_caches.pop(client, None)
    if cache is not None:
        await cache.close()


async def _put_state_dict_direct(
    client, key: str, state_dict: Any, transfer_dtype, rank: int, num_ranks: int
) -> None:
    from torchstore_tpu.direct_weight_sync import DirectWeightSyncSource

    # torch-tensor leaves become zero-copy numpy views, so registration and
    # every later refresh read straight out of the trainer's torch storage.
    state_dict = torch_interop.convert_tree(state_dict)
    cache = _direct_cache(client)
    # Keyed by (key, rank): one client may publish as several ranks (tests /
    # colocated trainers); each rank owns its own registration + buffers.
    source = cache.sources.get((key, rank))
    if source is None:
        source = DirectWeightSyncSource(config=getattr(client, "_config", None))
        handles = await source.register(
            state_dict, rank, transfer_dtype, num_ranks=num_ranks
        )
        cache.sources[(key, rank)] = source
        published = {"handles": handles}
        if source.device_info is not None:
            # ICI rung: handles advertise the device transfer server; dests
            # pull device-to-device with zero host staging.
            published["device"] = source.device_info
        await client.put(f"{key}{_SEP}rank_{rank}", published)
        if rank == 0:
            # num_ranks is the direct-mode commit marker: written by rank 0,
            # readers fetch it first (reference :241-247).
            await client.put(f"{key}{_SEP}num_ranks", num_ranks)
    else:
        source.update_sources(state_dict)
        await source.refresh()


async def _resolve_direct_entry(client, key: str):
    """The cached (dest, all_handles, device_infos) for a direct-pushed key,
    fetching published handles and building the dest on first use (shared by
    the pull path and the prewarm preplan path)."""
    from torchstore_tpu.direct_weight_sync import DirectWeightSyncDest

    cache = _direct_cache(client)
    entry = cache.dests.get(key)
    if entry is not None:
        return entry
    try:
        num_ranks = await client.get(f"{key}{_SEP}num_ranks")
    except KeyError as exc:
        raise NoMatchingPush(
            f"no matching direct push for state dict key {key!r}"
        ) from exc
    all_handles: dict[str, list] = {}
    device_infos: list = []
    for rank in range(num_ranks):
        try:
            published = await client.get(f"{key}{_SEP}rank_{rank}")
        except KeyError as exc:
            # num_ranks (written by rank 0) can land before other ranks
            # publish their handles; keep the retry contract intact.
            raise NoMatchingPush(
                f"direct push for {key!r} incomplete: rank {rank} has not "
                "published handles yet"
            ) from exc
        for flat_key, handle_list in published["handles"].items():
            all_handles.setdefault(flat_key, []).extend(handle_list)
        if published.get("device") is not None:
            device_infos.append(published["device"])
    if device_infos and len(device_infos) != num_ranks:
        raise RuntimeError(
            f"direct push {key!r}: {len(device_infos)} of {num_ranks} "
            "ranks published device-path entries — mixed device/host "
            "publication cannot be merged (check ici_enabled agrees "
            "across ranks)"
        )
    entry = (DirectWeightSyncDest(), all_handles, device_infos or None)
    cache.dests[key] = entry
    return entry


async def preplan_direct(client, key: str, user_state_dict: Any) -> dict:
    """ts.prewarm hook for the direct acquire path: resolve the published
    handles, build + cache the transfer plan, pre-dial source connections,
    pre-attach same-host staging segments. The first real
    ``get_state_dict(direct=True)`` then starts at the data movement."""
    converted = torch_interop.convert_tree(user_state_dict, allow_copy=False)
    dest, all_handles, device_infos = await _resolve_direct_entry(client, key)
    # Reports share ts.prewarm's contract shape: "ok"/"errors" always
    # present (callers branch on them regardless of which mode ran).
    if device_infos is not None:
        # Device-path pulls have no host plan to precompute; the engine-side
        # prewarm (transfer server) is handled by the provision orchestrator.
        return {"ok": True, "errors": {}, "plan_ops": 0, "device": True}
    return {"ok": True, "errors": {}, **await dest.preplan(all_handles, converted)}


async def _get_state_dict_direct(
    client,
    key: str,
    user_state_dict: Any,
    _retry: bool = True,
    key_order: Optional[list] = None,
    on_layer=None,
) -> Any:
    from torchstore_tpu.direct_weight_sync import PullRaceError

    if user_state_dict is None:
        raise ValueError("direct get_state_dict requires user_state_dict targets")
    cache = _direct_cache(client)
    entry = await _resolve_direct_entry(client, key)
    dest, all_handles, device_infos = entry
    try:
        if device_infos is not None:
            from torchstore_tpu.transport import device_transfer as _dt

            if not _dt.is_available():
                raise RuntimeError(
                    f"direct push {key!r} rides the device (ICI) path but "
                    "this process's jax build lacks the transfer engine; "
                    "set TORCHSTORE_TPU_ICI_ENABLED=0 on the source to use "
                    "the host path"
                )
            return await dest.pull_device(device_infos, user_state_dict)
        # Ordering kwargs only when requested: plain pulls keep the
        # two-argument call shape (test stubs and subclasses rely on it).
        kwargs = {}
        if key_order is not None:
            kwargs["key_order"] = key_order
        if on_layer is not None:
            kwargs["on_layer"] = on_layer
        return await dest.pull(all_handles, user_state_dict, **kwargs)
    except (ConnectionError, OSError, KeyError, ValueError, PullRaceError):
        # ValueError covers stale-plan shape mismatches after a source
        # republish; PullRaceError covers seqlock settle timeouts / double
        # tears under hot concurrent publishes (ADVICE r3). A successful
        # retry fully overwrites any partial in-place landings.
        if not _retry:
            raise
        # The source may have restarted and re-published fresh handles under
        # the same key — invalidate the cached set and retry once.
        cache.dests.pop(key, None)
        await dest.close()
        return await _get_state_dict_direct(
            client,
            key,
            user_state_dict,
            _retry=False,
            key_order=key_order,
            on_layer=on_layer,
        )


async def put_state_dict(
    client,
    key: str,
    state_dict: Any,
    transfer_dtype=None,
    transfer_quant: Optional[str] = None,
    direct: bool = False,
    rank: int = 0,
    num_ranks: int = 1,
) -> None:
    if transfer_quant is not None:
        if transfer_quant != "int8":
            raise ValueError(
                f"unsupported transfer_quant {transfer_quant!r} (only 'int8')"
            )
        if transfer_dtype is not None:
            raise ValueError(
                "transfer_quant and transfer_dtype are mutually exclusive "
                "(int8 defines the wire format)"
            )
        if direct:
            raise ValueError(
                "transfer_quant is a buffered-path feature (the direct path "
                "serves live staging buffers, not encoded copies)"
            )
    if direct:
        return await _put_state_dict_direct(
            client, key, state_dict, transfer_dtype, rank, num_ranks
        )
    tracker = LatencyTracker(f"put_state_dict[{key}]")
    flat, mapping = flatten_state_dict(state_dict)
    cache = getattr(client, "plan_cache", None)
    plan = None
    signature = None
    if cache is not None:
        signature = _flat_signature(
            flat, ("cast", str(transfer_dtype), transfer_quant)
        )
        if cache.last_put_sig.get(key) != signature:
            # Any publish whose signature this client cannot PROVE is
            # unchanged bumps the epoch: a restructure that only DROPS
            # keys deletes nothing, so the index alone cannot see it and
            # consumers' cached get plans would serve the old structure
            # forever. Covers publisher restarts too (no memory of the
            # previous push -> one bump per key per process).
            await client.bump_placement_epoch()
        cache.last_put_sig[key] = signature
        plan = cache.lookup("put", key, signature)
    else:
        # No publisher-side signature memory at all (plan cache disabled):
        # every push could be an invisible restructure — invalidate
        # consumer plans each time. They fall back to the full (pre-PR)
        # marker-validated path; plan caching across the fleet is only
        # effective when publishers keep their caches on.
        await client.bump_placement_epoch()
    if plan is None:
        if MAPPING_KEY in flat:
            raise ValueError(
                f"{MAPPING_KEY!r} is a reserved top-level state-dict key (it "
                "is the commit marker); rename that entry"
            )
        store_keys = {k: _store_key(key, k) for k in flat}
    else:
        store_keys = plan["store_keys"]
    marker: dict = {"mapping": mapping}
    if transfer_dtype is not None:
        flat = cast_floating_tensors(flat, transfer_dtype)
    if transfer_quant is not None:
        flat, quant_meta = quantize_int8(flat)
        marker["quant"] = quant_meta
    tracker.track_step("flatten")
    if plan is None:
        # Automatic provisioning hint: the first push of a big working set
        # derives a manifest from the flat dict and prewarms pools/dials
        # ahead of the data-plane puts (config.prewarm_auto; once per
        # size-signature per client; never fails the put). Cached-plan
        # iterations skip even this no-op check.
        from torchstore_tpu import provision

        await provision.maybe_auto_prewarm(client, flat)
        tracker.track_step("prewarm_hint")
        arena_hint = None
        if cache is not None:
            config = getattr(client, "_config", None)
            arena_hint = _arena_hint_from_flat(flat, config)
            if arena_hint is not None:
                # Prewarm-seeded layouts (provision handoff) take over when
                # they describe exactly these sizes.
                arena_hint = cache.seeds.get(
                    arena_hint["sizes"], arena_hint
                )
    else:
        arena_hint = plan.get("arena")
    await client.put_batch(
        {store_keys[k]: v for k, v in flat.items()},
        plan_hint={"arena": arena_hint} if arena_hint else None,
    )
    nbytes = sum(getattr(v, "nbytes", 0) for v in flat.values())
    tracker.track_step("put_batch", nbytes)
    # Commit marker LAST: its presence implies every entry above landed
    # (and carries the quantization scales, so readers always see them
    # together with a complete push).
    await client.put(_store_key(key, MAPPING_KEY), marker)
    tracker.track_step("commit_marker")
    if cache is not None and plan is None:
        cache.store(
            "put",
            key,
            signature,
            {"store_keys": store_keys, "arena": arena_hint},
        )
    tracker.log_summary(level=20)  # INFO: weight-sync phases are user-facing


def direct_staging_buffers(client, key: str, rank: int = 0) -> Any:
    """After a direct push of ``key``: the registered staging buffers in the
    original state-dict structure, or None when not applicable (sharded or
    device sources). A trainer that adopts these arrays as its weight
    storage makes every later direct put a pure metadata publish — zero
    source-side copies (registered-memory semantics; the device/ICI path is
    already copy-free)."""
    cache = _direct_cache(client)
    source = cache.sources.get((key, rank))
    if source is None:
        return None
    return source.staging_state_dict()


def stream_state_dict(client, key: str, transfer_dtype=None):
    """Open an incremental (layer-streamed) publish of ``key``: push
    fragments with ``await stream.put(...)`` as tensors become ready, then
    ``await stream.seal()``. See :mod:`torchstore_tpu.stream_sync`."""
    from torchstore_tpu import stream_sync

    return stream_sync.stream_state_dict(
        client, key, transfer_dtype=transfer_dtype
    )


async def get_state_dict(
    client,
    key: str,
    user_state_dict: Any = None,
    direct: bool = False,
    strict: bool = True,
    key_order: Optional[list] = None,
    on_layer=None,
    stream: bool = False,
) -> Any:
    """Fetch a complete state dict. With ``user_state_dict``, its leaves act
    as fetch targets (sharded jax.Arrays reshard on the fly; numpy arrays are
    filled in place) and the stored mapping must match the user structure
    exactly (strict=True parity,
    /root/reference/torchstore/state_dict_utils.py:146-174).

    ``stream=True`` (or any ``key_order``/``on_layer``) acquires layer by
    layer against a streamed publish: each key is served the moment its
    version watermark lands — in ``key_order`` (model-forward) order when
    given — with ``on_layer(flat_key, value)`` invoked per served leaf, so
    forward compute starts before the last layer lands. Falls back to the
    barrier path when the key was never stream-published. On the direct
    path, ``key_order``/``on_layer`` order the one-hop pull instead."""
    if not direct and (stream or key_order is not None or on_layer is not None):
        from torchstore_tpu import stream_sync

        return await stream_sync.get_state_dict_streamed(
            client,
            key,
            user_state_dict=user_state_dict,
            key_order=key_order,
            on_layer=on_layer,
            strict=strict,
        )
    if direct:
        # The direct path naturally pulls exactly the user dict's keys
        # (handles are matched per key), i.e. subset pulls just work —
        # strict=True additionally verifies full coverage below.
        # allow_copy=False: an in-place target whose numpy view would need a
        # copy must fail loudly, not silently fill the copy.
        converted = torch_interop.convert_tree(user_state_dict, allow_copy=False)
        result = await _get_state_dict_direct(
            client, key, converted, key_order=key_order, on_layer=on_layer
        )
        if converted is not user_state_dict:
            result = torch_interop.restore_torch_results(
                user_state_dict, converted, result
            )
        if strict:
            cache = _direct_cache(client)
            entry = cache.dests.get(key)
            if entry is not None:
                user_flat, _ = flatten_state_dict(user_state_dict)
                if entry[2] is not None:
                    published_keys = set()
                    for info in entry[2]:
                        published_keys |= set(info["keys"])
                else:
                    published_keys = set(entry[1])
                missing = published_keys - set(user_flat)
                if missing:
                    raise ValueError(
                        f"state dict structure mismatch for {key!r}: missing "
                        f"in user dict: {sorted(missing)[:5]} (pass "
                        "strict=False to pull a subset)"
                    )
        return result
    tracker = LatencyTracker(f"get_state_dict[{key}]")
    cache = getattr(client, "plan_cache", None)
    user_flat = user_mapping = None
    if user_state_dict is not None:
        user_flat, user_mapping = flatten_state_dict(user_state_dict)
    signature = None
    epoch_at_build = None
    if cache is not None:
        signature = (
            _flat_signature(user_flat) if user_flat is not None else ("none",)
        )
        peeked = cache.peek("get", key, signature)
        if peeked is not None:
            # ONE epoch RPC validates the whole cached plan (instead of a
            # commit-marker fetch + per-key structure checks); a bumped
            # epoch invalidates it right here and falls through to the
            # full path. Skipped entirely when every target is covered by
            # a one-sided plan (same rule as get_batch seeding): the
            # per-entry stamps self-validate, so the warm sync iteration
            # makes ZERO RPCs.
            covers = getattr(client, "one_sided_covers_items", None)
            if covers is None or not covers(
                [
                    (sk, user_flat is not None and fetch)
                    for _, sk, fetch in peeked.get("targets", ())
                ]
            ):
                await client.placement_epoch()
            plan = cache.lookup("get", key, signature)
            if plan is not None:
                return await _get_with_plan(
                    client, plan, user_flat, user_mapping, tracker
                )
        if cache.epoch is None:
            await client.placement_epoch()  # once per consumer client
        # Capture the epoch BEFORE fetching the marker: a structural change
        # that lands mid-build must leave the stored plan already stale
        # (stamping a later-observed epoch would validate it forever).
        epoch_at_build = cache.epoch
    try:
        marker = await client.get(_store_key(key, MAPPING_KEY))
    except KeyError as exc:
        raise NoMatchingPush(
            f"no matching push for state dict key {key!r} (commit marker "
            "absent: either never pushed or push still in flight)"
        ) from exc
    mapping = marker["mapping"]
    quant = marker.get("quant")
    scales = quant["scales"] if quant else {}
    tracker.track_step("mapping")

    if user_state_dict is not None:
        stored_keys = _leaf_keys(mapping)
        # Unknown keys always fail; missing keys fail only in strict mode
        # (strict=False pulls a subset, e.g. just the lm_head).
        extra = set(user_flat) - stored_keys
        if extra:
            raise ValueError(
                f"user dict keys not present in push {key!r}: {sorted(extra)[:5]}"
            )
        missing = stored_keys - set(user_flat)
        if strict and missing:
            raise ValueError(
                f"state dict structure mismatch for {key!r}: missing in "
                f"user dict: {sorted(missing)[:5]} (pass strict=False to "
                "pull a subset)"
            )
        targets = {}
        for k, v in user_flat.items():
            if k in scales:
                targets[_store_key(key, k)] = _quant_fetch_target(v)
            else:
                targets[_store_key(key, k)] = v if _is_fetch_target(v) else None
        # _seed_plan=False: this op owns its SyncPlanCache entry (op="get")
        # and already validated the epoch above — the batch-level seeding
        # inside get_batch would double-book both.
        fetched = await client.get_batch(targets, _seed_plan=False)
        flat = {}
        for k, v in user_flat.items():
            got = fetched[_store_key(key, k)]
            if k in scales:
                got = _dequant_result(got, scales[k], quant["dtypes"][k], v)
            flat[k] = got
        mapping = user_mapping
    else:
        leaf_keys = sorted(_leaf_keys(mapping))
        fetched = await client.get_batch(
            {_store_key(key, k): None for k in leaf_keys}, _seed_plan=False
        )
        flat = {}
        for k in leaf_keys:
            got = fetched[_store_key(key, k)]
            if k in scales:
                got = _dequantize(
                    np.asarray(got), scales[k], quant["dtypes"][k]
                )
            flat[k] = got
    nbytes = sum(getattr(v, "nbytes", 0) for v in flat.values())
    tracker.track_step("get_batch", nbytes)
    result = unflatten_state_dict(flat, mapping)
    tracker.track_step("unflatten")
    if cache is not None and quant is None:
        # Quantized pushes are NOT plan-cached: the scales ride the commit
        # marker and change every publish, so the marker fetch stays on
        # the hot path for them.
        if user_flat is not None:
            targets_spec = [
                (k, _store_key(key, k), _is_fetch_target(v))
                for k, v in user_flat.items()
            ]
        else:
            targets_spec = [
                (k, _store_key(key, k), False)
                for k in sorted(_leaf_keys(mapping))
            ]
        cache.store(
            "get",
            key,
            signature,
            {
                "targets": targets_spec,
                # The stored mapping is needed to rebuild structure only
                # when the caller passes no user dict.
                "mapping": mapping if user_flat is None else None,
            },
            epoch=epoch_at_build,
        )
    tracker.log_summary(level=20)
    return result


async def _get_with_plan(client, plan, user_flat, user_mapping, tracker):
    """Plan-cache hit: the placement epoch validated the whole plan, so the
    commit-marker fetch and structure validation are skipped and the
    iteration goes straight to the data plane (locations are already warm
    in the client's location cache for the same reason)."""
    targets = {
        sk: (user_flat[k] if fetch and user_flat is not None else None)
        for k, sk, fetch in plan["targets"]
    }
    fetched = await client.get_batch(targets, _seed_plan=False)
    flat = {k: fetched[sk] for k, sk, _ in plan["targets"]}
    nbytes = sum(getattr(v, "nbytes", 0) for v in flat.values())
    tracker.track_step("get_batch_planned", nbytes)
    mapping = user_mapping if user_flat is not None else plan["mapping"]
    result = unflatten_state_dict(flat, mapping)
    tracker.track_step("unflatten")
    tracker.log_summary(level=20)
    return result


def _leaf_keys(mapping: dict) -> set[str]:
    out: set[str] = set()

    def rec(entry: dict) -> None:
        if entry["kind"] in ("leaf", "boxed"):
            out.add(entry["key"])
        elif entry["kind"] == "dict":
            for v in entry["items"].values():
                rec(v)
        else:
            for v in entry["items"]:
                rec(v)

    rec(mapping)
    return out


def _is_fetch_target(value: Any) -> bool:
    return (
        isinstance(value, np.ndarray)
        or torch_interop.is_torch_tensor(value)
        or shd.is_jax_array(value)
        or shd.is_sharded_spec(value)
        or shd.is_plain_spec(value)
    )
