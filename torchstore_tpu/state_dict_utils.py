"""state_dict sync layer: flatten / commit-marker / dtype-cast / unflatten.

TPU-native equivalent of /root/reference/torchstore/state_dict_utils.py:27-275.
Protocol (invariant 3, SURVEY §2.2): all tensor entries are put under
``key/<flat_path>`` first, then ``key/MAPPING`` is written LAST as the commit
marker — its presence implies a complete state dict; readers fetch it first
and fail with "no matching push" when absent.

Flattening is dependency-free (dict / list / tuple / NamedTuple recursion)
so it handles flax param trees, optax optimizer states and plain nested
dicts without importing jax; leaves may be jax.Arrays (sharded puts/gets go
through the normal resharding pipeline), numpy arrays, or arbitrary objects.
"""

from __future__ import annotations

import struct
import weakref
from typing import Any, Optional

import numpy as np

from torchstore_tpu import faults
from torchstore_tpu import sharding as shd
from torchstore_tpu import torch_interop
from torchstore_tpu.logging import LatencyTracker, get_logger
from torchstore_tpu.native import copy_into
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.transport.types import _np_dtype  # bf16-aware name->dtype

logger = get_logger("torchstore_tpu.state_dict")

MAPPING_KEY = "MAPPING"
_SEP = "/"


class NoMatchingPush(KeyError):
    pass


# --------------------------------------------------------------------------
# flatten / unflatten
# --------------------------------------------------------------------------


def _is_leaf(value: Any) -> bool:
    if isinstance(value, dict):
        return False
    if isinstance(value, (list, tuple)):
        return False
    return True


def _axis_metadata_box(value: Any):
    """The flax AxisMetadata box wrapping ``value``, or None. Trees straight
    out of ``model.init`` with ``nn.with_logical_partitioning`` carry
    LogicallyPartitioned/Partitioned leaves; stored boxed, their jax arrays
    would ride the opaque object path (no resharding, full-serialize puts).
    Flatten unboxes them — the array takes the tensor path — and records the
    empty box in the mapping so unflatten restores the exact structure."""
    try:
        from flax.core import meta as flax_meta
    except ImportError:  # pragma: no cover - flax is in this image
        return None
    if isinstance(value, flax_meta.AxisMetadata):
        return value.replace_boxed(None)
    return None


def flatten_state_dict(sd: Any) -> tuple[dict[str, Any], dict]:
    """Returns ({flat_path: leaf}, mapping). ``mapping`` is a picklable
    template that records the container structure (incl. NamedTuple types by
    import path) for exact reconstruction — the role DCP's
    ``flatten_state_dict`` plays in the reference."""
    flat: dict[str, Any] = {}
    mapping = _flatten_rec(sd, [], flat)
    return flat, mapping


def _flatten_rec(value: Any, path: list[str], flat: dict[str, Any]) -> dict:
    # Module-level recursion for the same reason as _unflatten_rec: an inner
    # closure would be a cycle pinning every leaf array until cyclic GC.
    if isinstance(value, dict):
        return {
            "kind": "dict",
            "items": {
                str(k): _flatten_rec(v, path + [str(k)], flat)
                for k, v in value.items()
            },
            "key_types": {str(k): _key_type(k) for k in value},
        }
    if isinstance(value, (list, tuple)):
        kind = "list" if isinstance(value, list) else "tuple"
        entry: dict = {
            "kind": kind,
            "items": [
                _flatten_rec(v, path + [str(i)], flat)
                for i, v in enumerate(value)
            ],
        }
        if isinstance(value, tuple) and hasattr(value, "_fields"):
            entry["kind"] = "namedtuple"
            entry["cls"] = f"{type(value).__module__}:{type(value).__qualname__}"
        return entry
    flat_key = _SEP.join(path)
    if flat_key in flat:
        raise ValueError(f"duplicate flattened key {flat_key!r}")
    box = _axis_metadata_box(value)
    if box is not None:
        flat[flat_key] = value.unbox()
        return {"kind": "boxed", "key": flat_key, "box": box}
    flat[flat_key] = value
    return {"kind": "leaf", "key": flat_key}


def _key_type(key: Any) -> str:
    if isinstance(key, int):
        return "int"
    return "str"


def unflatten_state_dict(flat: dict[str, Any], mapping: dict) -> Any:
    # Module-level recursion (not an inner closure): a self-referencing
    # closure is a reference cycle that pins ``flat``'s arrays — including
    # zero-copy SHM views — until the next cyclic GC pass, which defers
    # their release back to the storage volume.
    return _unflatten_rec(mapping, flat)


def _unflatten_rec(entry: dict, flat: dict[str, Any]) -> Any:
    kind = entry["kind"]
    if kind == "leaf":
        return flat[entry["key"]]
    if kind == "boxed":
        return entry["box"].replace_boxed(flat[entry["key"]])
    if kind == "dict":
        key_types = entry.get("key_types", {})
        return {
            (int(k) if key_types.get(k) == "int" else k): _unflatten_rec(v, flat)
            for k, v in entry["items"].items()
        }
    children = [_unflatten_rec(v, flat) for v in entry["items"]]
    if kind == "list":
        return children
    if kind == "tuple":
        return tuple(children)
    if kind == "namedtuple":
        cls = _resolve_class(entry["cls"])
        if cls is None:
            return tuple(children)
        return cls(*children)
    raise ValueError(f"corrupt mapping entry {entry!r}")


def _resolve_class(spec: str):
    mod_name, _, qual = spec.partition(":")
    try:
        import importlib

        obj = importlib.import_module(mod_name)
        for part in qual.split("."):
            obj = getattr(obj, part)
        return obj
    except Exception:
        logger.warning("cannot resolve NamedTuple class %s; using plain tuple", spec)
        return None


# --------------------------------------------------------------------------
# dtype cast
# --------------------------------------------------------------------------


def _is_floating(value: Any) -> bool:
    dtype = getattr(value, "dtype", None)
    if dtype is None:
        return False
    try:
        return np.issubdtype(np.dtype(dtype), np.floating) or "bfloat16" in str(dtype)
    except TypeError:
        return "float" in str(dtype)


def cast_floating_tensors(flat: dict[str, Any], transfer_dtype) -> dict[str, Any]:
    """Cast floating leaves to ``transfer_dtype`` before transfer (reference
    /root/reference/torchstore/state_dict_utils.py:177-189). jax.Arrays cast
    on-device (one fused XLA op per leaf); numpy casts on host."""
    out = {}
    for key, value in flat.items():
        if not _is_floating(value):
            out[key] = value
        elif torch_interop.is_torch_tensor(value):
            out[key] = torch_interop.astype_numpy(value, transfer_dtype)
        else:
            out[key] = value.astype(transfer_dtype)
    return out


# --------------------------------------------------------------------------
# transfer quantization: blockwise int8/int4 fused blobs + delta tier
# --------------------------------------------------------------------------
#
# Every quantized floating leaf crosses the wire (and sits in the store) as
# ONE self-describing uint8 blob: [header+shape | changed-block bitmap |
# packed codes | f32 scale table]. The scale slot is laid out by
# transport.landing.quant_blob_layout (compute_arena_layout's scale-slot
# mode), so scales provably share a segment with the payload they decode —
# one handshake, one segment, never a separate RPC. Because the blob is an
# ordinary byte tensor, arena packing, bulk framing, doorbells, one-sided
# stamped reads, and the plan cache all carry it unchanged; the MAPPING
# marker only records WHICH keys are quantized (iteration-stable metadata),
# so quantized publishes are plan-cacheable like everything else.
#
# Modes (``TORCHSTORE_TPU_TRANSFER_QUANT`` / ``transfer_quant=``):
#   int8        symmetric per-tensor int8 (one block spanning the tensor)
#   int8_block  symmetric per-block int8, TORCHSTORE_TPU_TRANSFER_QUANT_BLOCK
#               elements per block (finer scales: better accuracy at ~1.6%
#               extra wire bytes at the default block of 256)
#   int4_block  two 4-bit codes per byte, per-block scales (8x vs f32)
#
# Delta tier (weight_channel versions only — a delta blob is NOT
# self-contained, so it never rides a same-key overwrite): the publisher's
# DeltaEncoder keeps the last-shipped dequantized baseline per key and
# ships quantized ``w_t - w_{t-1}`` with a per-block changed bitmap;
# near-zero blocks are skipped entirely, fully-unchanged keys publish NO
# bytes (an unchanged-watermark alias to the v_{t-1} store key). Readers
# accumulate through DeltaDecoder with the IDENTICAL f32 arithmetic, so
# reader state is bit-identical to the publisher baseline; a full keyframe
# every TORCHSTORE_TPU_DELTA_KEYFRAME versions bounds the chain a joiner
# must walk (and the publisher enforces keep >= keyframe cadence so the
# chain is always retained).

QUANT_MODES = ("int8", "int8_block", "int4_block")
_QUANT_MAGIC = 0x42515354  # "TSQB" little-endian
_QUANT_CODEC = 1
# Wire packing code: 1 = one int8 code per element, 2 = packed int4 pairs.
_FMT_CODES = {"int8": 1, "int8_block": 1, "int4_block": 2}
_QMAX = {"int8": 127, "int8_block": 127, "int4_block": 7}
_FLAG_DELTA = 1
_FLAG_KEYFRAME = 2

_QUANT_BYTES_IN = obs_metrics.counter(
    "ts_quant_bytes_in_total",
    "Full-precision bytes entering the transfer-quantization tier, by fmt",
)
_QUANT_BYTES_WIRE = obs_metrics.counter(
    "ts_quant_bytes_wire_total",
    "Fused quant-blob bytes actually shipped (payload + scales), by fmt",
)
_DELTA_SKIPPED = obs_metrics.counter(
    "ts_delta_skipped_blocks_total",
    "Near-zero residual blocks a delta publish skipped entirely",
)
_DELTA_KEYFRAMES = obs_metrics.counter(
    "ts_delta_keyframes_total",
    "Full keyframes published by the delta tier (cadence + restructures)",
)
_DELTA_UNCHANGED = obs_metrics.counter(
    "ts_delta_unchanged_keys_total",
    "Delta publishes of a fully-unchanged key (alias, zero bytes shipped)",
)
_DELTA_UNCHANGED_SERVED = obs_metrics.counter(
    "ts_delta_unchanged_served_total",
    "Unchanged-key reads served from this reader's accumulated v-1 state "
    "with zero re-transfer",
)


def _checked_scale(
    key: str, amax: float, qmax: float = 127.0, block: Optional[int] = None
) -> float:
    """max|x|/qmax with non-finite inputs rejected LOUDLY: a NaN amax would
    silently fall back to scale=1 (zeroing typical sub-unit weights) and an
    Inf scale would dequantize to all-NaN — exactly the silent corruption a
    weight-sync layer must never pass along. ``block`` names the offending
    block in the blockwise path, so one NaN block is findable in a
    thousand-block tensor."""
    if not np.isfinite(amax):
        where = f"{key!r}" if block is None else f"{key!r} (block {block})"
        raise ValueError(
            f"cannot quantize {where}: contains non-finite values "
            f"(max|x| = {amax}); publish unquantized or clean the weights"
        )
    return amax / qmax if amax > 0 else 1.0


def _block_scales(key: str, amax: np.ndarray, qmax: int) -> np.ndarray:
    """Per-block scales (f32) with the non-finite check applied per block —
    the raise names key AND block index via :func:`_checked_scale`."""
    finite = np.isfinite(amax)
    if not finite.all():
        idx = int(np.argmax(~finite))
        _checked_scale(key, float(amax[idx]), qmax, block=idx)
    scales = (amax / qmax).astype(np.float32)
    scales[scales == 0.0] = np.float32(1.0)
    return scales


def _dequant_codes(codes: Any, scales: Any):
    """THE dequantization arithmetic — f32(codes) * f32(scales) — shared by
    the scalar helper, the blockwise codec, and both array backends. np and
    jax-cpu produce bit-identical bytes through this one path (the
    cross-backend equivalence test pins it), so publisher baselines and
    reader accumulations can never drift."""
    if shd.is_jax_array(codes):
        import jax.numpy as jnp

        return codes.astype(jnp.float32) * jnp.asarray(
            np.asarray(scales, dtype=np.float32)
        )
    # One fused pass (cast + multiply in f32): bit-identical to the
    # two-step astype(f32) * f32 — int8 -> f32 is exact and the product is
    # the same IEEE f32 multiply (the cross-backend test pins this).
    return np.multiply(
        codes, np.asarray(scales, dtype=np.float32), dtype=np.float32
    )


def _dequantize(q: Any, scale: float, dtype_name: str, target: Any = None):
    """codes -> original dtype through the one blessed :func:`_dequant_codes`
    path (both backends dequantize in f32 with an f32 scale — no more
    numpy-rounds-the-scale-but-jax-does-not seam). ``target`` (numpy view of
    user memory) gets the result in place."""
    dequant = _dequant_codes(q, scale)
    if shd.is_jax_array(dequant):
        return dequant.astype(_np_dtype(dtype_name))
    if target is not None:
        # Native landing path; raises on shape mismatch (no broadcast).
        copy_into(target, dequant.astype(target.dtype))
        return target
    return dequant.astype(_np_dtype(dtype_name))


def _as_blocks(flat_f32: np.ndarray, block: int) -> np.ndarray:
    """1-D f32 -> (nblocks, block), zero-padding the tail block. Always at
    least one block so empty tensors stay representable."""
    n = flat_f32.shape[0]
    nblocks = max(1, -(-n // block))
    if n == nblocks * block:
        return flat_f32.reshape(nblocks, block)
    padded = np.zeros(nblocks * block, np.float32)
    padded[:n] = flat_f32
    return padded.reshape(nblocks, block)


def _pack_codes(codes: np.ndarray, fmt_code: int) -> np.ndarray:
    if fmt_code == 1:
        return np.ascontiguousarray(codes).reshape(-1).view(np.uint8)
    u = (codes & 0x0F).astype(np.uint8)
    if u.shape[1] % 2:
        u = np.concatenate(
            [u, np.zeros((u.shape[0], 1), np.uint8)], axis=1
        )
    return np.ascontiguousarray(u[:, 0::2] | (u[:, 1::2] << 4)).reshape(-1)


def _unpack_codes(
    packed: np.ndarray, fmt_code: int, changed: int, block: int
) -> np.ndarray:
    if fmt_code == 1:
        return packed.view(np.int8).reshape(changed, block)
    pb = packed.reshape(changed, (block + 1) // 2)
    u = np.empty((changed, 2 * pb.shape[1]), np.uint8)
    u[:, 0::2] = pb & 0x0F
    u[:, 1::2] = pb >> 4
    codes = u[:, :block].astype(np.int8)
    codes[codes > 7] -= 16  # sign-extend 4-bit two's complement
    return codes


def _build_quant_blob(
    fmt: str,
    block: int,
    shape: tuple,
    dtype_name: str,
    nblocks: int,
    changed_mask: np.ndarray,
    codes: np.ndarray,
    scales: np.ndarray,
    flags: int,
    version: int,
    base_version: int,
) -> np.ndarray:
    """Assemble one fused wire blob. ``codes``: (changed, block) int8;
    ``scales``: (changed,) f32 — the scale slot offset comes from the
    arena-layout module, so scales land in the same segment as the codes."""
    from torchstore_tpu.transport import landing

    fmt_code = _FMT_CODES[fmt]
    rank = len(shape)
    changed = int(codes.shape[0])
    layout = landing.quant_blob_layout(rank, nblocks, changed, fmt, block)
    blob = np.zeros(layout["total"], np.uint8)
    struct.pack_into(
        "<IHBBIII", blob, 0,
        _QUANT_MAGIC, _QUANT_CODEC, fmt_code, flags,
        int(block), int(nblocks), changed,
    )
    blob[20] = rank
    dt = dtype_name.encode("utf-8")[:16]
    if dt:
        blob[21:21 + len(dt)] = np.frombuffer(dt, np.uint8)
    nelems = int(np.prod(shape)) if rank else 1
    struct.pack_into("<Q", blob, 40, nelems)
    struct.pack_into("<qq", blob, 48, int(base_version), int(version))
    if rank:
        blob[64:64 + 8 * rank] = np.frombuffer(
            np.asarray(shape, dtype="<u8").tobytes(), np.uint8
        )
    bm = np.packbits(
        np.asarray(changed_mask, np.uint8), bitorder="little"
    )
    blob[layout["bitmap"]:layout["bitmap"] + bm.nbytes] = bm
    payload = _pack_codes(codes, fmt_code)
    if payload.nbytes:
        blob[layout["payload"]:layout["payload"] + payload.nbytes] = payload
    sc = np.ascontiguousarray(scales, dtype="<f4").view(np.uint8)
    if sc.nbytes:
        blob[layout["scales"]:layout["scales"] + sc.nbytes] = sc
    return blob


def parse_quant_blob(value: Any) -> Optional[dict]:
    """Parse one fused quant blob into its sections (views where possible);
    None when ``value`` is not a blob (wrong dtype/shape/magic) — the
    streamed path uses this to pass raw non-floating leaves through."""
    from torchstore_tpu.transport import landing

    blob = np.asarray(value)
    if (
        blob.dtype != np.uint8
        or blob.ndim != 1
        or blob.nbytes < landing.QUANT_HEADER_BYTES
    ):
        return None
    blob = np.ascontiguousarray(blob)
    magic, codec, fmt_code, flags, block, nblocks, changed = (
        struct.unpack_from("<IHBBIII", blob, 0)
    )
    if magic != _QUANT_MAGIC or codec != _QUANT_CODEC:
        return None
    rank = int(blob[20])
    dtype_name = bytes(blob[21:37]).split(b"\0", 1)[0].decode("utf-8")
    (nelems,) = struct.unpack_from("<Q", blob, 40)
    base_version, version = struct.unpack_from("<qq", blob, 48)
    shape = (
        tuple(
            int(x)
            for x in np.frombuffer(blob[64:64 + 8 * rank].tobytes(), "<u8")
        )
        if rank
        else ()
    )
    fmt = "int4_block" if fmt_code == 2 else "int8_block"
    layout = landing.quant_blob_layout(rank, nblocks, changed, fmt, block)
    bitmap_bytes = (nblocks + 7) // 8
    mask = (
        np.unpackbits(
            blob[layout["bitmap"]:layout["bitmap"] + bitmap_bytes],
            bitorder="little",
        )[:nblocks].astype(bool)
    )
    payload = blob[
        layout["payload"]:layout["payload"]
        + landing.quant_payload_nbytes(fmt, block, changed)
    ]
    codes = _unpack_codes(payload, fmt_code, changed, block)
    scales = np.frombuffer(
        blob[layout["scales"]:layout["scales"] + 4 * changed].tobytes(),
        "<f4",
    )
    return {
        "fmt": fmt,
        "flags": flags,
        "block": int(block),
        "nblocks": int(nblocks),
        "mask": mask,
        "codes": codes,
        "scales": scales,
        "shape": shape,
        "dtype": dtype_name,
        "nelems": int(nelems),
        "base_version": int(base_version),
        "version": int(version),
    }


def _leaf_f32_blocks(value: Any, block: int) -> tuple[np.ndarray, np.ndarray]:
    arr = np.asarray(value)
    flat32 = np.ascontiguousarray(arr).reshape(-1).astype(
        np.float32, copy=False
    )
    return arr, _as_blocks(flat32, block)


def _encode_keyframe_from_blocks(
    key: str,
    xb: np.ndarray,
    shape: tuple,
    dtype_name: str,
    fmt: str,
    block: int,
    version: int = -1,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Quantize pre-blocked f32 data: (blob, codes, scales). Pure math —
    safe to run on a landing-pool thread."""
    qmax = _QMAX[fmt]
    # Two reductions instead of abs() (a full-tensor temp): max|x| =
    # max(max(x), -min(x)).
    amax = np.maximum(xb.max(axis=1), -xb.min(axis=1))
    scales = _block_scales(key, amax, qmax)
    q = np.multiply(xb, (1.0 / scales)[:, None].astype(np.float32))
    np.rint(q, out=q)
    np.clip(q, -qmax, qmax, out=q)
    codes = q.astype(np.int8)
    blob = _build_quant_blob(
        fmt, block, shape, dtype_name, xb.shape[0],
        np.ones(xb.shape[0], bool), codes, scales,
        _FLAG_KEYFRAME, version, version,
    )
    return blob, codes, scales


def _encode_keyframe_blob(
    key: str, value: Any, fmt: str, block: int, version: int = -1
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Quantize one whole leaf: (blob, xb, codes, scales). The per-tensor
    ``int8`` mode is the degenerate one-block-per-tensor case."""
    arr, xb = _leaf_f32_blocks(value, block)
    blob, codes, scales = _encode_keyframe_from_blocks(
        key, xb, arr.shape, str(value.dtype), fmt, block, version
    )
    return blob, xb, codes, scales


def _quant_leaf_block(fmt: str, block: int, value: Any) -> int:
    """Effective block size for one leaf: per-tensor ``int8`` spans the
    whole tensor with one block; blockwise modes use the configured size."""
    if fmt != "int8":
        return block
    shape = tuple(getattr(value, "shape", ()) or ())
    nelems = int(np.prod(shape)) if shape else 1
    return max(1, nelems)


def _guard_quantizable(key: str, value: Any) -> None:
    if shd.is_jax_array(value) and not value.is_fully_addressable:
        # The scale must be GLOBAL and identical on every rank; an eager
        # max over a multi-controller array can't compute it (and per-rank
        # scales would decode inconsistently).
        raise NotImplementedError(
            f"transfer_quant on non-fully-addressable array "
            f"{key!r}: compute the quantized array + scales inside your "
            "jitted step (global max via a collective) and push those, "
            "or use transfer_dtype instead"
        )


def quantize_transfer(
    flat: dict[str, Any], fmt: str, block: int
) -> tuple[dict[str, Any], dict]:
    """Quantize every floating leaf of ``flat`` into a self-contained
    keyframe blob. Returns (out_flat, marker_meta) — the marker records
    only WHICH keys are quantized (iteration-stable), the scales ride the
    blobs themselves. Non-floating leaves pass through untouched."""
    out: dict[str, Any] = {}
    dtypes: dict[str, str] = {}
    qkeys: list[str] = []
    for key, value in flat.items():
        if torch_interop.is_torch_tensor(value):
            value = torch_interop.to_numpy_view(value)
        if not _is_floating(value):
            out[key] = value
            continue
        _guard_quantizable(key, value)
        blob, _, _, _ = _encode_keyframe_blob(
            key, value, fmt, _quant_leaf_block(fmt, block, value)
        )
        out[key] = blob
        qkeys.append(key)
        dtypes[key] = str(value.dtype)
        _record_quant_bytes(fmt, getattr(value, "nbytes", 0), blob.nbytes)
    return out, {
        "fmt": fmt,
        "block": block,
        "keys": qkeys,
        "dtypes": dtypes,
    }


def quantize_int8(flat: dict[str, Any]) -> tuple[dict[str, Any], dict]:
    """Per-tensor symmetric int8 (the classic mode) over the fused-blob
    wire format: one block spans each tensor, scale = max|x|/127 rides the
    blob's scale slot instead of the commit marker."""
    return quantize_transfer(flat, "int8", 0)


async def quantize_transfer_async(
    flat: dict[str, Any], fmt: str, block: int, config=None
) -> tuple[dict[str, Any], dict]:
    """:func:`quantize_transfer` with per-leaf encodes fanned out across
    the shared landing pool (numpy ufuncs release the GIL, so leaves
    encode in parallel instead of serially blocking the event loop) —
    the put hot path's entry."""
    import asyncio

    from torchstore_tpu.transport import landing

    out: dict[str, Any] = {}
    dtypes: dict[str, str] = {}
    qkeys: list[str] = []
    jobs: list[tuple[str, Any]] = []
    for key, value in flat.items():
        if torch_interop.is_torch_tensor(value):
            value = torch_interop.to_numpy_view(value)
        if not _is_floating(value):
            out[key] = value
            continue
        _guard_quantizable(key, value)
        if shd.is_jax_array(value):
            value = np.asarray(value)  # one D2H here, off the pool threads
        qkeys.append(key)
        dtypes[key] = str(value.dtype)
        jobs.append((key, value))

    async def _enc(key: str, value: Any) -> None:
        blob, _, _, _ = await landing.run_in_pool(
            _encode_keyframe_blob,
            key,
            value,
            fmt,
            _quant_leaf_block(fmt, block, value),
            config=config,
        )
        _record_quant_bytes(fmt, getattr(value, "nbytes", 0), blob.nbytes)
        out[key] = blob

    if jobs:
        await asyncio.gather(*(_enc(k, v) for k, v in jobs))
    return out, {
        "fmt": fmt,
        "block": block,
        "keys": qkeys,
        "dtypes": dtypes,
    }


def _record_quant_bytes(fmt: str, bytes_in: int, bytes_wire: int) -> None:
    """Count the tier's effect at its one choke point: full-precision bytes
    in, fused blob bytes out — both as metrics and as ledger cells so
    ``ts.traffic_matrix()["quant"]`` carries the effective compression
    ratio next to the wire edges the savings apply to."""
    from torchstore_tpu.observability import ledger as obs_ledger

    _QUANT_BYTES_IN.inc(int(bytes_in), fmt=fmt)
    _QUANT_BYTES_WIRE.inc(int(bytes_wire), fmt=fmt)
    obs_ledger.record(obs_ledger.QUANT, "logical", int(bytes_in))
    obs_ledger.record(obs_ledger.QUANT, "wire", int(bytes_wire))


def _delta_version_key(channel: str, version: int) -> str:
    """The state-dict key of one channel version — mirrors
    weight_channel._version_key (the delta chain walks versions by name)."""
    return f"{channel}/v{int(version)}"


async def _delta_encode_flat(
    flat: dict[str, Any], fmt: str, block: int, delta_ctx: dict
) -> tuple[dict[str, Any], dict, dict[str, int]]:
    """Delta-encode one version's flat dict through the publisher's codec.
    Returns (flat_to_put, marker_quant_meta, unchanged_aliases) — unchanged
    keys are ABSENT from the put flat (zero bytes ship) and recorded as
    {flat_key: base_version} aliases in the marker meta."""
    codec: DeltaEncoder = delta_ctx["codec"]
    if codec.fmt != fmt:
        raise ValueError(
            f"delta codec fmt {codec.fmt!r} != transfer_quant {fmt!r}"
        )
    import asyncio

    version = int(delta_ctx["version"])
    out: dict[str, Any] = {}
    dtypes: dict[str, str] = {}
    qkeys: list[str] = []
    aliases: dict[str, int] = {}
    jobs: list[tuple[str, Any]] = []
    for key, value in flat.items():
        if torch_interop.is_torch_tensor(value):
            value = torch_interop.to_numpy_view(value)
        if not _is_floating(value):
            out[key] = value
            continue
        _guard_quantizable(key, value)
        qkeys.append(key)
        dtypes[key] = str(value.dtype)
        jobs.append((key, value))

    async def _enc(key: str, value: Any) -> None:
        # Distinct keys touch distinct codec entries, and the heavy math
        # runs on the landing pool inside encode() — per-key fan-out
        # parallelizes the delta encode like quantize_transfer_async.
        blob, base = await codec.encode(key, value, version)
        if blob is None:
            aliases[key] = int(base)
        else:
            out[key] = blob

    if jobs:
        await asyncio.gather(*(_enc(k, v) for k, v in jobs))
    meta = {
        "fmt": fmt,
        "block": codec.block,
        "keys": qkeys,
        "dtypes": dtypes,
        "delta": {
            "channel": delta_ctx["channel"],
            "version": version,
            "aliases": aliases,
        },
    }
    return out, meta, aliases


class DeltaEncoder:
    """Publisher-side state of the delta wire tier: per-key dequantized f32
    baselines tracking exactly what readers reconstruct (identical
    arithmetic through :func:`_dequant_codes`, so baseline and reader state
    are bit-identical — zero drift, keyframes only bound the chain length).

    Per key and version the encoder emits one of: a KEYFRAME blob (first
    publish, restructure, or cadence), a DELTA blob carrying only changed
    blocks (per-block bitmap), or ``None`` — the key is fully unchanged
    and the publish aliases the previous version's bytes
    (unchanged-watermark protocol).

    A block is "unchanged" when its residual max|w_t − baseline| sits at
    or below the block's quantization NOISE FLOOR — half the scale step it
    had at its last keyframe, plus ``skip_eps`` absolute slack. The
    residual is always measured against the live ``w_t`` (never against a
    previous residual), so skipped error never compounds: at any version
    the served weights are within ~half a keyframe step of the true ones,
    exactly the precision a plain quantized publish has, and the next
    keyframe re-centers everything."""

    def __init__(
        self,
        fmt: str,
        block: int,
        keyframe_every: int,
        skip_eps: float = 0.0,
    ) -> None:
        if fmt not in ("int8_block", "int4_block"):
            raise ValueError(
                f"delta encoding requires a blockwise mode, not {fmt!r}"
            )
        self.fmt = fmt
        self.block = max(1, int(block))
        self.keyframe_every = max(1, int(keyframe_every))
        self.skip_eps = float(skip_eps)
        # flat key -> {"sig", "baseline" (nblocks, block) f32,
        #              "base_version" (last shipped), "keyframe_version"}
        self.entries: dict[str, dict] = {}

    def drop(self, key: Optional[str] = None) -> None:
        """Evict baseline state (tests / memory pressure): the next publish
        of the dropped key(s) re-keyframes from fresh bytes — never from a
        stale baseline."""
        if key is None:
            self.entries.clear()
        else:
            self.entries.pop(key, None)

    def _delta_math(
        self,
        key: str,
        xb: np.ndarray,
        entry: dict,
        shape: tuple,
        dtype_name: str,
        version: int,
    ):
        """The residual/quantize/blob math of one delta step — PURE with
        respect to shared state (reads the baseline, mutates nothing), so
        it runs on a landing-pool thread. Returns None for a fully
        unchanged key, else (blob, changed_mask, dequantized_delta) for
        the caller to fold into the baseline on the event loop."""
        qmax = _QMAX[self.fmt]
        resid = xb - entry["baseline"]
        amax = np.max(np.abs(resid), axis=1)
        scales_full = _block_scales(key, amax, qmax)
        changed = amax > (
            np.float32(0.5) * entry["kf_scales"] + np.float32(self.skip_eps)
        )
        nchanged = int(np.count_nonzero(changed))
        skipped = int(xb.shape[0]) - nchanged
        if nchanged == 0:
            return None
        scales = scales_full[changed]
        codes = np.clip(
            np.rint(resid[changed] / scales[:, None]), -qmax, qmax
        ).astype(np.int8)
        blob = _build_quant_blob(
            self.fmt, self.block, shape, dtype_name,
            xb.shape[0], changed, codes, scales,
            _FLAG_DELTA, version, entry["base_version"],
        )
        return blob, changed, _dequant_codes(codes, scales[:, None]), skipped

    async def encode(
        self, key: str, value: Any, version: int
    ) -> tuple[Optional[np.ndarray], Optional[int]]:
        """(blob, None) to ship, or (None, base_version) when the key is
        fully unchanged and should alias version ``base_version``'s
        bytes. The heavy math runs on the landing pool (numpy releases the
        GIL), so concurrent per-key encodes parallelize and the event loop
        stays responsive; all entry mutation happens HERE, on the loop."""
        from torchstore_tpu.transport import landing

        version = int(version)
        arr, xb = _leaf_f32_blocks(value, self.block)
        sig = (xb.shape, tuple(int(s) for s in arr.shape), str(value.dtype))
        dtype_name = str(value.dtype)
        entry = self.entries.get(key)
        if entry is not None:
            # Faultpoint: chaos schedules inject baseline loss/corruption
            # here — a raise surfaces loudly instead of any silent
            # delta-over-stale-bytes encode.
            await faults.afire("channel.delta_baseline")
            if entry["sig"] != sig:
                entry = None  # restructure: the baseline is meaningless
            elif entry["base_version"] >= version:
                raise RuntimeError(
                    f"delta baseline for {key!r} is at "
                    f"v{entry['base_version']} but v{version} is being "
                    "encoded: version numbering moved backwards — refusing "
                    "to delta over a stale baseline (drop() the key to "
                    "re-keyframe)"
                )
        if (
            entry is None
            or (version - entry["keyframe_version"]) >= self.keyframe_every
        ):
            blob, codes, scales = await landing.run_in_pool(
                _encode_keyframe_from_blocks,
                key, xb, arr.shape, dtype_name, self.fmt, self.block,
                version,
            )
            self.entries[key] = {
                "sig": sig,
                "baseline": _dequant_codes(codes, scales[:, None]),
                # The keyframe's per-block scales ARE the noise floor the
                # skip rule measures against until the next keyframe.
                "kf_scales": scales,
                "base_version": version,
                "keyframe_version": version,
            }
            _DELTA_KEYFRAMES.inc()
            _record_quant_bytes(self.fmt, arr.nbytes, blob.nbytes)
            return blob, None
        res = await landing.run_in_pool(
            self._delta_math, key, xb, entry, arr.shape, dtype_name, version
        )
        if res is None:
            _DELTA_SKIPPED.inc(int(xb.shape[0]))
            _DELTA_UNCHANGED.inc()
            _record_quant_bytes(self.fmt, arr.nbytes, 0)
            return None, entry["base_version"]
        blob, changed, dq, skipped = res
        _DELTA_SKIPPED.inc(skipped)
        # Baseline advances by the DEQUANTIZED delta (what readers apply),
        # not the raw residual — publisher and reader stay bit-identical.
        entry["baseline"][changed] += dq
        entry["base_version"] = version
        _record_quant_bytes(self.fmt, arr.nbytes, blob.nbytes)
        return blob, None


class DeltaDecoder:
    """Reader-side accumulated f32 state, one entry per flat key. Applying
    a keyframe replaces the state; applying a delta requires the state to
    be at the blob's ``base_version`` — when it is not (fresh joiner,
    lagged reader), the decoder chain-fetches base blobs back to the
    nearest keyframe via ``fetch_base``; a broken chain (base evicted/GC'd)
    raises loudly, never silently serves stale accumulations."""

    def __init__(self) -> None:
        # flat key -> {"version", "blocks", "shape", "dtype", "nelems"}
        self.state: dict[str, dict] = {}

    def drop(self, key: Optional[str] = None) -> None:
        if key is None:
            self.state.clear()
        else:
            self.state.pop(key, None)

    def serve_unchanged(self, flat_key: str, base_version: int):
        """The accumulated state entry when it already holds the aliased
        base version's content (zero re-transfer), else None — the caller
        falls back to fetching the base bytes."""
        st = self.state.get(flat_key)
        if st is None or st["version"] != int(base_version):
            return None
        _DELTA_UNCHANGED_SERVED.inc()
        return st

    async def decode(
        self, flat_key: str, blob: Any, fetch_base=None, _depth: int = 0
    ) -> dict:
        """Apply one blob (raw bytes or a pre-parsed dict); returns the
        state entry. ``fetch_base(version)`` resolves missing baselines by
        fetching that version's blob for this key."""
        info = blob if isinstance(blob, dict) else parse_quant_blob(blob)
        if info is None:
            raise ValueError(
                f"{flat_key!r}: fetched value is not a quant blob (marker "
                "and bytes disagree about quantization)"
            )
        if _depth > 1024:
            raise RuntimeError(
                f"delta chain for {flat_key!r} exceeds 1024 hops — "
                "keyframe cadence is broken"
            )
        if info["flags"] & _FLAG_DELTA:
            base = info["base_version"]
            st = self.state.get(flat_key)
            if (
                st is None
                or st["version"] != base
                or st["shape"] != info["shape"]
            ):
                held = f"v{st['version']}" if st else "no baseline"
                if fetch_base is None:
                    raise RuntimeError(
                        f"delta blob for {flat_key!r} (v{info['version']}) "
                        f"applies on v{base} but this reader holds {held} "
                        "and has no chain context to re-fetch it"
                    )
                try:
                    base_blob = await fetch_base(base)
                except KeyError as exc:
                    raise RuntimeError(
                        f"delta chain broken for {flat_key!r}: baseline "
                        f"v{base} was evicted/GC'd before this reader "
                        f"(holding {held}) accumulated it — refusing to "
                        "serve a drifted state; raise the channel's keep "
                        "or lower the keyframe cadence"
                    ) from exc
                await self.decode(
                    flat_key, base_blob, fetch_base=fetch_base,
                    _depth=_depth + 1,
                )
                st = self.state[flat_key]
                if st["version"] != base:
                    raise RuntimeError(
                        f"delta chain for {flat_key!r} resolved to "
                        f"v{st['version']}, expected v{base}"
                    )
            # Faultpoint: the chaos schedule injects here to prove a lost/
            # corrupt baseline fails loudly rather than accumulating onto
            # stale state.
            await faults.afire("channel.delta_baseline")
            st["blocks"][info["mask"]] += _dequant_codes(
                info["codes"], info["scales"][:, None]
            )
            st["version"] = info["version"]
            st["dtype"] = info["dtype"] or st["dtype"]
            return st
        if info["codes"].shape[0] == info["nblocks"]:
            # Full keyframe (the only kind the encoder emits): dequantize
            # straight into the state array — no zeros memset, no
            # boolean-mask scatter over the whole tensor.
            blocks = np.ascontiguousarray(
                _dequant_codes(info["codes"], info["scales"][:, None])
            )
        else:
            blocks = np.zeros((info["nblocks"], info["block"]), np.float32)
            if info["codes"].size:
                blocks[info["mask"]] = _dequant_codes(
                    info["codes"], info["scales"][:, None]
                )
        st = {
            "version": info["version"],
            "blocks": blocks,
            "shape": info["shape"],
            "dtype": info["dtype"],
            "nelems": info["nelems"],
        }
        self.state[flat_key] = st
        return st


def _quant_result(st: dict, user_leaf: Any, dtype_name: Optional[str] = None):
    """Materialize one decoded state entry toward the user's leaf: in place
    for numpy/torch targets, device_put (with the target's sharding) for
    jax targets, a fresh array otherwise. Always COPIES out of the decoder
    state so callers can never mutate the accumulation."""
    want = dtype_name or st["dtype"] or "float32"
    flat = st["blocks"].reshape(-1)[: st["nelems"]]
    arr = flat.reshape(st["shape"])
    if user_leaf is None:
        return arr.astype(_np_dtype(want))  # astype always copies here
    if torch_interop.is_torch_tensor(user_leaf):
        view = torch_interop.to_numpy_view(user_leaf, allow_copy=False)
        copy_into(view, arr if view.dtype == arr.dtype else arr.astype(view.dtype))
        return user_leaf
    if isinstance(user_leaf, np.ndarray):
        # Same-dtype (the common f32 target): one native copy straight out
        # of the decoder state, no intermediate astype copy.
        copy_into(
            user_leaf,
            arr if user_leaf.dtype == arr.dtype else arr.astype(user_leaf.dtype),
        )
        return user_leaf
    if (
        shd.is_jax_array(user_leaf)
        or shd.is_sharded_spec(user_leaf)
        or shd.is_plain_spec(user_leaf)
    ):
        import jax
        import jax.numpy as jnp

        host = arr.astype(np.dtype(user_leaf.dtype))
        sharding = getattr(user_leaf, "sharding", None)
        if sharding is not None:
            return jax.device_put(host, sharding)
        return jnp.asarray(host)
    return arr.astype(_np_dtype(want))


def resolve_transfer_quant(
    transfer_quant: Optional[str], transfer_dtype, config
) -> Optional[str]:
    """The effective quant mode for one publish: an explicit argument wins;
    otherwise the TORCHSTORE_TPU_TRANSFER_QUANT default applies — but never
    on top of an explicit transfer_dtype (the caller chose a wire format
    already)."""
    if transfer_quant is None:
        if transfer_dtype is not None or config is None:
            return None
        transfer_quant = getattr(config, "transfer_quant", "none")
    if transfer_quant in (None, "none", ""):
        return None
    if transfer_quant not in QUANT_MODES:
        raise ValueError(
            f"unsupported transfer_quant {transfer_quant!r} (choose from "
            f"none|{'|'.join(QUANT_MODES)})"
        )
    return transfer_quant


# --------------------------------------------------------------------------
# put / get
# --------------------------------------------------------------------------


def _store_key(key: str, flat_key: str) -> str:
    return f"{key}{_SEP}{flat_key}" if flat_key else key


# --------------------------------------------------------------------------
# iteration-stable transfer plans (client.SyncPlanCache integration)
# --------------------------------------------------------------------------


def _leaf_signature(value: Any) -> tuple:
    """Hashable shape/dtype/sharding signature of one flat leaf — the unit
    the plan cache keys on. Signature equality means the leaf decomposes
    into byte-identical requests, so a cached plan replays exactly."""
    if type(value) is np.ndarray:
        # Exact-type fast path first: plain numpy leaves dominate trainer
        # state dicts, and this runs per leaf per warm iteration — the
        # jax/shard probes below cost more than the whole signature.
        # (.shape is already a tuple; .str is a C attribute.)
        return ("np", value.shape, value.dtype.str)
    sig = shd.plan_signature(value)
    if sig is not None:
        return sig
    from torchstore_tpu.client import Shard

    if isinstance(value, Shard):
        ts = value.tensor_slice
        data_sig = (
            _leaf_signature(value.data) if value.data is not None else None
        )
        return (
            "shard",
            ts.offsets,
            ts.local_shape,
            ts.global_shape,
            ts.coordinates,
            data_sig,
        )
    if torch_interop.is_torch_tensor(value):
        return ("torch", tuple(value.shape), str(value.dtype))
    if isinstance(value, np.ndarray):
        # dtype.str (C attribute), not str(dtype): this runs per leaf per
        # warm iteration, and dtype.__str__'s name derivation was ~2ms per
        # 512-leaf signature on the warm get path. Signatures are opaque
        # cache keys, only ever compared to each other.
        return ("np", tuple(value.shape), value.dtype.str)
    return ("obj",)  # opaque objects re-pickle every iteration anyway


def _flat_signature(flat: dict, *extra) -> tuple:
    return tuple((k, _leaf_signature(v)) for k, v in flat.items()) + extra


def _arena_hint_from_flat(flat: dict, config) -> Optional[dict]:
    """Precompute the small-key arena layout for a flat dict of PLAIN numpy
    leaves (the common trainer-host case). Any leaf whose request fan-out
    this function cannot see exactly (jax shards, torch views, Shards)
    returns None — the transport derives the layout itself and validates
    any hint against the real request set regardless."""
    if config is None or config.arena_max_bytes <= 0:
        return None
    from torchstore_tpu.transport import landing

    sizes: list[int] = []
    for value in flat.values():
        if isinstance(value, np.ndarray):
            if value.nbytes <= config.arena_max_bytes:
                sizes.append(int(value.nbytes))
            continue
        if _is_fetch_target(value):  # jax/torch/Shard: fan-out not 1:1 here
            return None
    if len(sizes) < 2:
        return None
    offsets, total = landing.compute_arena_layout(sizes)
    return {"sizes": tuple(sizes), "offsets": offsets, "total": total}


class _DirectSyncCache:
    """Per-client registry of direct-sync sources/dests keyed by state-dict
    key (the reference's _DirectRDMACache,
    /root/reference/torchstore/state_dict_utils.py:27-45)."""

    def __init__(self) -> None:
        self.sources: dict[str, Any] = {}
        # key -> (dest, all_handles, device_info)
        self.dests: dict[str, tuple[Any, dict, Any]] = {}

    async def close(self) -> None:
        for source in self.sources.values():
            await source.close()
        for entry in self.dests.values():
            await entry[0].close()
        self.sources.clear()
        self.dests.clear()


# Weakly keyed by the client object: a GC'd client cannot hand its cache to
# an unrelated new client via id() reuse.
# Weak client keys cannot survive a fork (children build fresh clients), so
# inherited entries are unreachable garbage at worst, never stale hits.
_direct_caches: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()  # tslint: disable=fork-safety


def _direct_cache(client) -> _DirectSyncCache:
    cache = _direct_caches.get(client)
    if cache is None:
        cache = _DirectSyncCache()
        _direct_caches[client] = cache
    return cache


async def close_direct_caches(client) -> None:
    """Release SHM segments / peer-server sockets held for this client's
    direct-sync sessions (called from shutdown paths)."""
    cache = _direct_caches.pop(client, None)
    if cache is not None:
        await cache.close()


async def _put_state_dict_direct(
    client, key: str, state_dict: Any, transfer_dtype, rank: int, num_ranks: int
) -> None:
    from torchstore_tpu.direct_weight_sync import DirectWeightSyncSource

    # torch-tensor leaves become zero-copy numpy views, so registration and
    # every later refresh read straight out of the trainer's torch storage.
    state_dict = torch_interop.convert_tree(state_dict)
    cache = _direct_cache(client)
    # Keyed by (key, rank): one client may publish as several ranks (tests /
    # colocated trainers); each rank owns its own registration + buffers.
    source = cache.sources.get((key, rank))
    if source is None:
        source = DirectWeightSyncSource(config=getattr(client, "_config", None))
        handles = await source.register(
            state_dict, rank, transfer_dtype, num_ranks=num_ranks
        )
        cache.sources[(key, rank)] = source
        published = {"handles": handles}
        if source.device_info is not None:
            # ICI rung: handles advertise the device transfer server; dests
            # pull device-to-device with zero host staging.
            published["device"] = source.device_info
        await client.put(f"{key}{_SEP}rank_{rank}", published)
        if rank == 0:
            # num_ranks is the direct-mode commit marker: written by rank 0,
            # readers fetch it first (reference :241-247).
            await client.put(f"{key}{_SEP}num_ranks", num_ranks)
    else:
        source.update_sources(state_dict)
        await source.refresh()


async def _resolve_direct_entry(client, key: str):
    """The cached (dest, all_handles, device_infos) for a direct-pushed key,
    fetching published handles and building the dest on first use (shared by
    the pull path and the prewarm preplan path)."""
    from torchstore_tpu.direct_weight_sync import DirectWeightSyncDest

    cache = _direct_cache(client)
    entry = cache.dests.get(key)
    if entry is not None:
        return entry
    try:
        num_ranks = await client.get(f"{key}{_SEP}num_ranks")
    except KeyError as exc:
        raise NoMatchingPush(
            f"no matching direct push for state dict key {key!r}"
        ) from exc
    all_handles: dict[str, list] = {}
    device_infos: list = []
    for rank in range(num_ranks):
        try:
            published = await client.get(f"{key}{_SEP}rank_{rank}")
        except KeyError as exc:
            # num_ranks (written by rank 0) can land before other ranks
            # publish their handles; keep the retry contract intact.
            raise NoMatchingPush(
                f"direct push for {key!r} incomplete: rank {rank} has not "
                "published handles yet"
            ) from exc
        for flat_key, handle_list in published["handles"].items():
            all_handles.setdefault(flat_key, []).extend(handle_list)
        if published.get("device") is not None:
            device_infos.append(published["device"])
    if device_infos and len(device_infos) != num_ranks:
        raise RuntimeError(
            f"direct push {key!r}: {len(device_infos)} of {num_ranks} "
            "ranks published device-path entries — mixed device/host "
            "publication cannot be merged (check ici_enabled agrees "
            "across ranks)"
        )
    entry = (DirectWeightSyncDest(), all_handles, device_infos or None)
    cache.dests[key] = entry
    return entry


async def preplan_direct(client, key: str, user_state_dict: Any) -> dict:
    """ts.prewarm hook for the direct acquire path: resolve the published
    handles, build + cache the transfer plan, pre-dial source connections,
    pre-attach same-host staging segments. The first real
    ``get_state_dict(direct=True)`` then starts at the data movement."""
    converted = torch_interop.convert_tree(user_state_dict, allow_copy=False)
    dest, all_handles, device_infos = await _resolve_direct_entry(client, key)
    # Reports share ts.prewarm's contract shape: "ok"/"errors" always
    # present (callers branch on them regardless of which mode ran).
    if device_infos is not None:
        # Device-path pulls have no host plan to precompute; the engine-side
        # prewarm (transfer server) is handled by the provision orchestrator.
        return {"ok": True, "errors": {}, "plan_ops": 0, "device": True}
    return {"ok": True, "errors": {}, **await dest.preplan(all_handles, converted)}


async def _get_state_dict_direct(
    client,
    key: str,
    user_state_dict: Any,
    _retry: bool = True,
    key_order: Optional[list] = None,
    on_layer=None,
) -> Any:
    from torchstore_tpu.direct_weight_sync import PullRaceError

    if user_state_dict is None:
        raise ValueError("direct get_state_dict requires user_state_dict targets")
    cache = _direct_cache(client)
    entry = await _resolve_direct_entry(client, key)
    dest, all_handles, device_infos = entry
    try:
        if device_infos is not None:
            from torchstore_tpu.transport import device_transfer as _dt

            if not _dt.is_available():
                raise RuntimeError(
                    f"direct push {key!r} rides the device (ICI) path but "
                    "this process's jax build lacks the transfer engine; "
                    "set TORCHSTORE_TPU_ICI_ENABLED=0 on the source to use "
                    "the host path"
                )
            return await dest.pull_device(device_infos, user_state_dict)
        # Ordering kwargs only when requested: plain pulls keep the
        # two-argument call shape (test stubs and subclasses rely on it).
        kwargs = {}
        if key_order is not None:
            kwargs["key_order"] = key_order
        if on_layer is not None:
            kwargs["on_layer"] = on_layer
        return await dest.pull(all_handles, user_state_dict, **kwargs)
    except (ConnectionError, OSError, KeyError, ValueError, PullRaceError):
        # ValueError covers stale-plan shape mismatches after a source
        # republish; PullRaceError covers seqlock settle timeouts / double
        # tears under hot concurrent publishes (ADVICE r3). A successful
        # retry fully overwrites any partial in-place landings.
        if not _retry:
            raise
        # The source may have restarted and re-published fresh handles under
        # the same key — invalidate the cached set and retry once.
        cache.dests.pop(key, None)
        await dest.close()
        return await _get_state_dict_direct(
            client,
            key,
            user_state_dict,
            _retry=False,
            key_order=key_order,
            on_layer=on_layer,
        )


async def put_state_dict(
    client,
    key: str,
    state_dict: Any,
    transfer_dtype=None,
    transfer_quant: Optional[str] = None,
    direct: bool = False,
    rank: int = 0,
    num_ranks: int = 1,
    delta_ctx: Optional[dict] = None,
) -> None:
    config = getattr(client, "_config", None)
    # The env default never applies to direct publishes (the direct path
    # serves live staging buffers); an EXPLICIT transfer_quant still
    # raises below.
    transfer_quant = resolve_transfer_quant(
        transfer_quant, transfer_dtype, None if direct else config
    )
    if transfer_quant is not None:
        if transfer_dtype is not None:
            raise ValueError(
                "transfer_quant and transfer_dtype are mutually exclusive "
                "(quantization defines the wire format)"
            )
        if direct:
            raise ValueError(
                "transfer_quant is a buffered-path feature (the direct path "
                "serves live staging buffers, not encoded copies)"
            )
    if delta_ctx is not None and transfer_quant not in (
        "int8_block", "int4_block"
    ):
        raise ValueError(
            "delta publishing requires transfer_quant int8_block/int4_block "
            f"(got {transfer_quant!r})"
        )
    quant_block = getattr(config, "quant_block", 256) if config else 256
    if direct:
        return await _put_state_dict_direct(
            client, key, state_dict, transfer_dtype, rank, num_ranks
        )
    tracker = LatencyTracker(f"put_state_dict[{key}]")
    flat, mapping = flatten_state_dict(state_dict)
    cache = getattr(client, "plan_cache", None)
    plan = None
    signature = None
    if cache is not None:
        # The quant mode AND block size are part of the signature: the
        # block size determines the scale-slot layout of every blob, so a
        # knob change is a restructure (epoch bump) like any other.
        signature = _flat_signature(
            flat, ("cast", str(transfer_dtype), transfer_quant, quant_block)
        )
        if cache.last_put_sig.get(key) != signature:
            # Any publish whose signature this client cannot PROVE is
            # unchanged bumps the epoch: a restructure that only DROPS
            # keys deletes nothing, so the index alone cannot see it and
            # consumers' cached get plans would serve the old structure
            # forever. Covers publisher restarts too (no memory of the
            # previous push -> one bump per key per process).
            await client.bump_placement_epoch()
        cache.last_put_sig[key] = signature
        plan = cache.lookup("put", key, signature)
    else:
        # No publisher-side signature memory at all (plan cache disabled):
        # every push could be an invisible restructure — invalidate
        # consumer plans each time. They fall back to the full (pre-PR)
        # marker-validated path; plan caching across the fleet is only
        # effective when publishers keep their caches on.
        await client.bump_placement_epoch()
    if plan is None:
        if MAPPING_KEY in flat:
            raise ValueError(
                f"{MAPPING_KEY!r} is a reserved top-level state-dict key (it "
                "is the commit marker); rename that entry"
            )
        store_keys = {k: _store_key(key, k) for k in flat}
    else:
        store_keys = plan["store_keys"]
    marker: dict = {"mapping": mapping}
    unchanged_aliases: dict[str, int] = {}
    if transfer_dtype is not None:
        flat = cast_floating_tensors(flat, transfer_dtype)
    if transfer_quant is not None:
        if delta_ctx is not None:
            flat, quant_meta, unchanged_aliases = await _delta_encode_flat(
                flat, transfer_quant, quant_block, delta_ctx
            )
        else:
            flat, quant_meta = await quantize_transfer_async(
                flat, transfer_quant, quant_block, config=config
            )
        marker["quant"] = quant_meta
    tracker.track_step("flatten")
    if plan is None:
        # Automatic provisioning hint: the first push of a big working set
        # derives a manifest from the flat dict and prewarms pools/dials
        # ahead of the data-plane puts (config.prewarm_auto; once per
        # size-signature per client; never fails the put). Cached-plan
        # iterations skip even this no-op check.
        from torchstore_tpu import provision

        await provision.maybe_auto_prewarm(client, flat)
        tracker.track_step("prewarm_hint")
        arena_hint = None
        if cache is not None:
            config = getattr(client, "_config", None)
            arena_hint = _arena_hint_from_flat(flat, config)
            if arena_hint is not None:
                # Prewarm-seeded layouts (provision handoff) take over when
                # they describe exactly these sizes.
                arena_hint = cache.seeds.get(
                    arena_hint["sizes"], arena_hint
                )
    else:
        arena_hint = plan.get("arena")
    if flat:
        # Unchanged-alias keys (delta tier) are absent from ``flat`` — an
        # all-unchanged publish ships the marker alone.
        await client.put_batch(
            {store_keys[k]: v for k, v in flat.items()},
            plan_hint={"arena": arena_hint} if arena_hint else None,
        )
    nbytes = sum(getattr(v, "nbytes", 0) for v in flat.values())
    tracker.track_step("put_batch", nbytes)
    # Commit marker LAST: its presence implies every entry above landed
    # (and carries the quantization scales, so readers always see them
    # together with a complete push).
    await client.put(_store_key(key, MAPPING_KEY), marker)
    tracker.track_step("commit_marker")
    if cache is not None and plan is None and delta_ctx is None:
        # Delta publishes are per-version keys that are never revisited —
        # storing their plans would only churn the cache. Plain quantized
        # publishes cache exactly like unquantized ones (the scales ride
        # the blobs, not the marker, so the plan stays valid).
        cache.store(
            "put",
            key,
            signature,
            {"store_keys": store_keys, "arena": arena_hint},
        )
    tracker.log_summary(level=20)  # INFO: weight-sync phases are user-facing


def direct_staging_buffers(client, key: str, rank: int = 0) -> Any:
    """After a direct push of ``key``: the registered staging buffers in the
    original state-dict structure, or None when not applicable (sharded or
    device sources). A trainer that adopts these arrays as its weight
    storage makes every later direct put a pure metadata publish — zero
    source-side copies (registered-memory semantics; the device/ICI path is
    already copy-free)."""
    cache = _direct_cache(client)
    source = cache.sources.get((key, rank))
    if source is None:
        return None
    return source.staging_state_dict()


def stream_state_dict(
    client, key: str, transfer_dtype=None, transfer_quant: Optional[str] = None
):
    """Open an incremental (layer-streamed) publish of ``key``: push
    fragments with ``await stream.put(...)`` as tensors become ready, then
    ``await stream.seal()``. See :mod:`torchstore_tpu.stream_sync`."""
    from torchstore_tpu import stream_sync

    return stream_sync.stream_state_dict(
        client, key, transfer_dtype=transfer_dtype,
        transfer_quant=transfer_quant,
    )


async def get_state_dict(
    client,
    key: str,
    user_state_dict: Any = None,
    direct: bool = False,
    strict: bool = True,
    key_order: Optional[list] = None,
    on_layer=None,
    stream: bool = False,
    delta_state: Optional["DeltaDecoder"] = None,
) -> Any:
    """Fetch a complete state dict. With ``user_state_dict``, its leaves act
    as fetch targets (sharded jax.Arrays reshard on the fly; numpy arrays are
    filled in place) and the stored mapping must match the user structure
    exactly (strict=True parity,
    /root/reference/torchstore/state_dict_utils.py:146-174).

    ``stream=True`` (or any ``key_order``/``on_layer``) acquires layer by
    layer against a streamed publish: each key is served the moment its
    version watermark lands — in ``key_order`` (model-forward) order when
    given — with ``on_layer(flat_key, value)`` invoked per served leaf, so
    forward compute starts before the last layer lands. Falls back to the
    barrier path when the key was never stream-published. On the direct
    path, ``key_order``/``on_layer`` order the one-hop pull instead."""
    if not direct and (stream or key_order is not None or on_layer is not None):
        from torchstore_tpu import stream_sync

        return await stream_sync.get_state_dict_streamed(
            client,
            key,
            user_state_dict=user_state_dict,
            key_order=key_order,
            on_layer=on_layer,
            strict=strict,
            delta_state=delta_state,
        )
    if direct:
        # The direct path naturally pulls exactly the user dict's keys
        # (handles are matched per key), i.e. subset pulls just work —
        # strict=True additionally verifies full coverage below.
        # allow_copy=False: an in-place target whose numpy view would need a
        # copy must fail loudly, not silently fill the copy.
        converted = torch_interop.convert_tree(user_state_dict, allow_copy=False)
        result = await _get_state_dict_direct(
            client, key, converted, key_order=key_order, on_layer=on_layer
        )
        if converted is not user_state_dict:
            result = torch_interop.restore_torch_results(
                user_state_dict, converted, result
            )
        if strict:
            cache = _direct_cache(client)
            entry = cache.dests.get(key)
            if entry is not None:
                user_flat, _ = flatten_state_dict(user_state_dict)
                if entry[2] is not None:
                    published_keys = set()
                    for info in entry[2]:
                        published_keys |= set(info["keys"])
                else:
                    published_keys = set(entry[1])
                missing = published_keys - set(user_flat)
                if missing:
                    raise ValueError(
                        f"state dict structure mismatch for {key!r}: missing "
                        f"in user dict: {sorted(missing)[:5]} (pass "
                        "strict=False to pull a subset)"
                    )
        return result
    tracker = LatencyTracker(f"get_state_dict[{key}]")
    cache = getattr(client, "plan_cache", None)
    user_flat = user_mapping = None
    if user_state_dict is not None:
        user_flat, user_mapping = flatten_state_dict(user_state_dict)
    signature = None
    epoch_at_build = None
    if cache is not None:
        signature = (
            _flat_signature(user_flat) if user_flat is not None else ("none",)
        )
        peeked = cache.peek("get", key, signature)
        if peeked is not None:
            # ONE epoch RPC validates the whole cached plan (instead of a
            # commit-marker fetch + per-key structure checks); a bumped
            # epoch invalidates it right here and falls through to the
            # full path. Skipped entirely when every target is covered by
            # a one-sided plan (same rule as get_batch seeding): the
            # per-entry stamps self-validate, so the warm sync iteration
            # makes ZERO RPCs.
            covers = getattr(client, "one_sided_covers_items", None)
            if covers is None or not covers(
                [
                    (sk, user_flat is not None and fetch)
                    for _, sk, fetch in peeked.get("targets", ())
                ]
            ):
                await client.placement_epoch()
            plan = cache.lookup("get", key, signature)
            if plan is not None:
                return await _get_with_plan(
                    client, key, plan, user_flat, user_mapping, tracker,
                    delta_state=delta_state,
                )
        if cache.epoch is None:
            await client.placement_epoch()  # once per consumer client
        # Capture the epoch BEFORE fetching the marker: a structural change
        # that lands mid-build must leave the stored plan already stale
        # (stamping a later-observed epoch would validate it forever).
        epoch_at_build = cache.epoch
    try:
        marker = await client.get(_store_key(key, MAPPING_KEY))
    except KeyError as exc:
        raise NoMatchingPush(
            f"no matching push for state dict key {key!r} (commit marker "
            "absent: either never pushed or push still in flight)"
        ) from exc
    mapping = marker["mapping"]
    quant = marker.get("quant")
    if quant is not None and "keys" not in quant:
        raise ValueError(
            f"push {key!r} carries a legacy quantization marker (scales on "
            "the commit marker); republish with this build's fused-blob "
            "wire tier"
        )
    tracker.track_step("mapping")

    if user_state_dict is not None:
        stored_keys = _leaf_keys(mapping)
        # Unknown keys always fail; missing keys fail only in strict mode
        # (strict=False pulls a subset, e.g. just the lm_head).
        extra = set(user_flat) - stored_keys
        if extra:
            raise ValueError(
                f"user dict keys not present in push {key!r}: {sorted(extra)[:5]}"
            )
        missing = stored_keys - set(user_flat)
        if strict and missing:
            raise ValueError(
                f"state dict structure mismatch for {key!r}: missing in "
                f"user dict: {sorted(missing)[:5]} (pass strict=False to "
                "pull a subset)"
            )
        pairs = [
            (k, _store_key(key, k), _is_fetch_target(v))
            for k, v in user_flat.items()
        ]
        flat = await _fetch_quant_aware(
            client, key, quant, pairs, user_flat, delta_state
        )
        mapping = user_mapping
    else:
        pairs = [
            (k, _store_key(key, k), False)
            for k in sorted(_leaf_keys(mapping))
        ]
        flat = await _fetch_quant_aware(
            client, key, quant, pairs, None, delta_state
        )
    nbytes = sum(getattr(v, "nbytes", 0) for v in flat.values())
    tracker.track_step("get_batch", nbytes)
    result = unflatten_state_dict(flat, mapping)
    tracker.track_step("unflatten")
    if cache is not None:
        # Quantized pushes plan-cache like everything else now: scales ride
        # the payload blobs (not the marker), so a cached plan carrying the
        # static quant meta can skip the marker fetch entirely on warm
        # iterations.
        if user_flat is not None:
            targets_spec = [
                (k, _store_key(key, k), _is_fetch_target(v))
                for k, v in user_flat.items()
            ]
        else:
            targets_spec = [
                (k, _store_key(key, k), False)
                for k in sorted(_leaf_keys(mapping))
            ]
        cache.store(
            "get",
            key,
            signature,
            {
                "targets": targets_spec,
                # The stored mapping is needed to rebuild structure only
                # when the caller passes no user dict.
                "mapping": mapping if user_flat is None else None,
                "quant": quant,
            },
            epoch=epoch_at_build,
        )
    tracker.log_summary(level=20)
    return result


async def _fetch_quant_aware(
    client,
    key: str,
    quant: Optional[dict],
    pairs: list[tuple],
    user_flat: Optional[dict],
    delta_state: Optional[DeltaDecoder],
    prefer_volume: Optional[str] = None,
) -> dict[str, Any]:
    """Fetch + decode one state dict's leaves. ``pairs`` is
    ``[(flat_key, store_key, in_place_fetch)]`` covering every leaf.
    Quantized keys fetch raw blobs (no in-place landing of encoded bytes)
    and decode toward the user's leaf; unchanged-alias keys resolve to the
    base version's store key — or to the reader's accumulated state with
    ZERO re-transfer when ``delta_state`` already holds the base
    content."""
    if quant is None:
        targets = {
            sk: (user_flat[fk] if fetch and user_flat is not None else None)
            for fk, sk, fetch in pairs
        }
        # _seed_plan=False: state-dict ops own their SyncPlanCache entries
        # (op="get"/"put") — batch-level seeding would double-book.
        fetched = await client.get_batch(
            targets, _seed_plan=False, prefer_volume=prefer_volume
        )
        return {fk: fetched[sk] for fk, sk, _ in pairs}
    qkeys = set(quant["keys"])
    delta = quant.get("delta") or {}
    aliases = delta.get("aliases") or {}
    channel = delta.get("channel")
    decoder = delta_state if delta_state is not None else DeltaDecoder()
    local: dict[str, dict] = {}
    targets: dict[str, Any] = {}
    fetch_sk: dict[str, str] = {}
    for fk, sk, fetch in pairs:
        if fk in qkeys:
            if fk in aliases:
                st = decoder.serve_unchanged(fk, aliases[fk])
                if st is not None:
                    local[fk] = st
                    continue
                sk = _store_key(_delta_version_key(channel, aliases[fk]), fk)
            targets[sk] = None
        else:
            targets[sk] = (
                user_flat[fk] if fetch and user_flat is not None else None
            )
        fetch_sk[fk] = sk
    fetched = (
        await client.get_batch(
            targets, _seed_plan=False, prefer_volume=prefer_volume
        )
        if targets
        else {}
    )
    flat: dict[str, Any] = {}
    for fk, _, fetch in pairs:
        if fk not in qkeys:
            flat[fk] = fetched[fetch_sk[fk]]
            continue
        st = local.get(fk)
        if st is None:
            st = await decoder.decode(
                fk,
                fetched[fetch_sk[fk]],
                fetch_base=_chain_fetcher(client, channel, fk),
            )
        user_leaf = user_flat.get(fk) if user_flat is not None else None
        flat[fk] = _quant_result(
            st,
            user_leaf if _is_fetch_target(user_leaf) else None,
            quant["dtypes"].get(fk),
        )
    return flat


def _chain_fetcher(client, channel: Optional[str], flat_key: str):
    """Base-blob fetcher for the delta chain walk, or None for non-delta
    markers (keyframe blobs never need a baseline)."""
    if channel is None:
        return None

    async def fetch_base(version: int):
        return await client.get(
            _store_key(_delta_version_key(channel, version), flat_key)
        )

    return fetch_base


async def _get_with_plan(
    client, key, plan, user_flat, user_mapping, tracker, delta_state=None
):
    """Plan-cache hit: the placement epoch validated the whole plan, so the
    commit-marker fetch and structure validation are skipped and the
    iteration goes straight to the data plane (locations are already warm
    in the client's location cache for the same reason). Quantized plans
    carry the static quant meta, so decode needs no marker either."""
    flat = await _fetch_quant_aware(
        client, key, plan.get("quant"), plan["targets"], user_flat,
        delta_state,
    )
    nbytes = sum(getattr(v, "nbytes", 0) for v in flat.values())
    tracker.track_step("get_batch_planned", nbytes)
    mapping = user_mapping if user_flat is not None else plan["mapping"]
    result = unflatten_state_dict(flat, mapping)
    tracker.track_step("unflatten")
    tracker.log_summary(level=20)
    return result


def _leaf_keys(mapping: dict) -> set[str]:
    out: set[str] = set()

    def rec(entry: dict) -> None:
        if entry["kind"] in ("leaf", "boxed"):
            out.add(entry["key"])
        elif entry["kind"] == "dict":
            for v in entry["items"].values():
                rec(v)
        else:
            for v in entry["items"]:
                rec(v)

    rec(mapping)
    return out


def _is_fetch_target(value: Any) -> bool:
    return (
        isinstance(value, np.ndarray)
        or torch_interop.is_torch_tensor(value)
        or shd.is_jax_array(value)
        or shd.is_sharded_spec(value)
        or shd.is_plain_spec(value)
    )
