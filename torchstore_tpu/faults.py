"""Deterministic fault injection: named faultpoints compiled into hot paths.

The fault-handling layers (health supervisor, retry/failover, reclaim) are
only credible if their failure modes can be reproduced ON DEMAND, inside the
real process topology — not by monkeypatching client-side helpers in the
test process (the old ``tests/test_strategies_and_faults.py`` idiom), which
can never reach a forked volume's put path or a controller's notify.

This module provides named injection sites ("faultpoints") wired into the
store's hot paths:

    controller.notify     Controller.notify_put_batch entry
    controller.locate     Controller.locate_volumes entry
    volume.put            StorageVolume.put entry
    volume.get            StorageVolume.get entry
    volume.handshake      StorageVolume.handshake entry (all transports)
    shm.handshake         SHM server-side recv_handshake (volume process)
    shm.landing_stamp     TWO fire sites bracketing landing copies.
                          Volume-side (storage_volume._begin_landing):
                          fires after the per-entry seqlock goes odd,
                          before the landing is applied — delay/wedge
                          holds entries visibly write-in-flight so
                          one-sided readers observe the odd stamp and
                          fall back. Client-side (shared_memory.
                          stamped_read_batch): fires inside the warm
                          one-sided read's landing-copy window (between
                          stamp check and memcpy) — arm with
                          scope="client" to slow the get's landing stage
                          without touching any volume (the fleet-scale
                          stage-attribution legs)
    channel.publish_layer publisher-side entry of every streamed layer
                          batch (stream_sync.StreamedPut.put) — wedge/delay
                          freezes a publisher mid-stream; readers must keep
                          serving the previous sealed version, never a mix
    channel.watermark     controller-side watermark application inside
                          notify_put_batch — delay/wedge holds committed
                          bytes invisible to streaming readers (they keep
                          long-polling); raise fails the publisher's put
    volume.spill          spill-writer entry per demoted entry, fired after
                          the demotion decision and before the crash-safe
                          disk write (tiering/spill.py): die kills the
                          volume mid-spill — the committed version must
                          survive on replicas and the write-temp→rename
                          protocol must never leave a torn spill file
    volume.fault_in       volume-side entry of every spilled-entry
                          promotion (the first get of a cold key): raise
                          fails that get (clients fail over / retry),
                          delay/wedge holds the fault-in open so readers
                          observe the landing bracket, die kills the
                          volume mid-fault-in
    relay.forward         relay-node entry of every broadcast forwarding hop
                          (StorageVolume.pull_from with relay=True): arming
                          it inside one volume kills/wedges THAT relay node
                          mid-broadcast — the re-parenting chaos schedule
    actor.ping            ActorServer control-ping (per process: arming it
                          inside a volume wedges THAT volume's heartbeats)
    bulk.send_frame       bulk transport frame send (client and server)
    bulk.recv_frame       bulk server frame receive (supports drop-frame)
    rendezvous.dispatch   rendezvous server op dispatch
    control.reconcile     policy-engine reconcile entry (control/engine.py),
                          fired before the snapshot is taken: raise aborts
                          the whole round (interval loop logs and retries
                          next tick), wedge freezes the engine without
                          touching serving paths
    control.migrate       per-action entry of every engine-driven key
                          migration, fired before idx.migrate_key: die
                          inside the SOURCE volume (arm volume.get there
                          instead) or raise here mid-plan — the committed
                          generation must survive on the source replica and
                          the engine must abandon the action loudly (a
                          ``decision`` event with outcome=abandoned)
    autoscale.spawn       client-side entry of every scale-out volume spawn
                          (api._autoscale_spawn, before spawn_actors): raise
                          stops the spawn batch — already-attached volumes
                          stay attached, the round reports the shortfall
    autoscale.drain       autoscale-engine entry of every drain/retire
                          action (autoscale/engine.py, before the first
                          actuator touch): raise mid-drain must leave every
                          committed generation readable — the drain decision
                          lands errored and the next round resumes it
    blob.io               inside EVERY blob-store operation (put/get/list/
                          delete in tiering/blob.py, before bytes move):
                          raise makes a demotion abandon (entry stays on
                          disk, still served), a restore surface the error
                          to its get, a checkpoint report the volume errored

Cost when disarmed: ONE dict lookup (``_armed.get(name)`` on an empty dict)
— measured indistinguishable from noise on the many_keys bench. Sites fire
via :func:`fire` (sync paths) or :func:`afire` (async paths).

Arming:

- env: ``TORCHSTORE_TPU_FAULTPOINTS="volume.put=raise:count=2;actor.ping=wedge"``
  parsed at import and after fork, so faults ride into freshly spawned
  volume/controller processes (spawn_actors forwards TORCHSTORE_TPU_*).
- control RPC: ``ts.inject_fault(name, action, count=, prob=, delay_ms=,
  scope=)`` arms faults inside ALREADY-RUNNING actor processes through the
  ``inject_fault`` endpoints on the controller and every volume — the only
  way to schedule a fault mid-test without restarting the fleet.

Actions:

    raise       raise FaultInjectedError at the site
    delay       sleep delay_ms then proceed (asyncio.sleep at async sites)
    wedge       hang far past any configured deadline (cancellable at async
                sites; at sync sites this blocks the process's event loop —
                the whole process looks wedged, pings included)
    die         os._exit(17): the process vanishes mid-operation
    drop-frame  return the sentinel "drop-frame" for the site to interpret
                (bulk frame paths silently drop the frame; elsewhere no-op)

``count=N`` fires N times then self-disarms (deterministic schedules);
``prob=P`` fires with probability P per pass (chaos soaks). Unset count
with unset prob fires every pass until disarmed.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from torchstore_tpu.logging import get_logger
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import recorder as obs_recorder

logger = get_logger("torchstore_tpu.faults")

ENV_FAULTPOINTS = "TORCHSTORE_TPU_FAULTPOINTS"

# Every faultpoint name a call site may fire. The tslint ``retry-discipline``
# checker cross-references fire()/afire() string literals against this
# registry, so a typo'd site name fails pre-merge instead of silently never
# firing.
REGISTRY: frozenset[str] = frozenset(
    {
        "controller.notify",
        "controller.locate",
        "controller.shard_dispatch",
        "control.reconcile",
        "control.migrate",
        "autoscale.spawn",
        "autoscale.drain",
        "blob.io",
        "volume.put",
        "volume.get",
        "volume.handshake",
        "volume.spill",
        "volume.fault_in",
        "shm.handshake",
        "shm.landing_stamp",
        "channel.publish_layer",
        "channel.watermark",
        "channel.delta_baseline",
        "relay.forward",
        "actor.ping",
        "bulk.send_frame",
        "bulk.recv_frame",
        "rendezvous.dispatch",
    }
)

ACTIONS = ("raise", "delay", "wedge", "die", "drop-frame")

# How long a "wedge" hangs: far past any configured RPC deadline, short
# enough that an orphaned wedged task cannot outlive a test session by much.
WEDGE_S = 600.0

_FIRED = obs_metrics.counter(
    "ts_faults_fired_total", "Fault injections triggered, by point and action"
)


class FaultInjectedError(RuntimeError):
    """Raised at a faultpoint armed with action='raise'."""


@dataclass
class FaultSpec:
    """One armed fault. ``count`` is the REMAINING fire budget (None =
    unlimited); ``prob`` gates each pass; ``delay_ms`` parameterizes the
    ``delay`` action only (other actions execute immediately)."""

    name: str
    action: str
    count: Optional[int] = None
    prob: Optional[float] = None
    delay_ms: float = 100.0
    fired: int = field(default=0)

    def describe(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "action": self.action,
            "count": self.count,
            "prob": self.prob,
            "delay_ms": self.delay_ms,
            "fired": self.fired,
        }


# Armed faults for THIS process. Empty in production: every fire() is one
# failed dict lookup. Actor children re-arm from env in reinit_after_fork.
_armed: dict[str, FaultSpec] = {}  # tslint: disable=fork-safety


def arm(
    name: str,
    action: str,
    count: Optional[int] = None,
    prob: Optional[float] = None,
    delay_ms: Optional[float] = None,
) -> dict[str, Any]:
    """Arm one faultpoint in THIS process; returns the armed spec. Unknown
    names/actions fail loudly — a typo'd injection that never fires would
    make a chaos test silently vacuous."""
    if name not in REGISTRY:
        raise ValueError(
            f"unknown faultpoint {name!r}; registered: {sorted(REGISTRY)}"
        )
    if action not in ACTIONS:
        raise ValueError(f"unknown fault action {action!r}; have {ACTIONS}")
    if count is not None and count <= 0:
        raise ValueError("count must be positive (or None for unlimited)")
    if prob is not None and not (0.0 < prob <= 1.0):
        raise ValueError("prob must be in (0, 1]")
    spec = FaultSpec(
        name=name,
        action=action,
        count=count,
        prob=prob,
        delay_ms=100.0 if delay_ms is None else float(delay_ms),
    )
    _armed[name] = spec
    logger.warning(
        "faultpoint armed: %s=%s count=%s prob=%s delay_ms=%s [pid %d]",
        name,
        action,
        count,
        prob,
        spec.delay_ms,
        os.getpid(),
    )
    return spec.describe()


def disarm(name: Optional[str] = None) -> int:
    """Disarm one faultpoint (or ALL when name is None); returns how many
    were dropped. Unknown/unarmed names are a no-op (idempotent cleanup)."""
    if name is None:
        n = len(_armed)
        _armed.clear()
        return n
    return 1 if _armed.pop(name, None) is not None else 0


def armed() -> list[dict[str, Any]]:
    """Describe every armed fault in this process (test introspection)."""
    return [spec.describe() for spec in _armed.values()]


def _take(spec: FaultSpec) -> bool:
    """Decide whether this pass fires; consume count budget when it does."""
    if spec.prob is not None and random.random() >= spec.prob:
        return False
    if spec.count is not None:
        if spec.count <= 0:
            _armed.pop(spec.name, None)
            return False
        spec.count -= 1
        if spec.count == 0:
            _armed.pop(spec.name, None)
    spec.fired += 1
    _FIRED.inc(point=spec.name, action=spec.action)
    obs_recorder.record("fault", spec.name, action=spec.action)
    logger.warning(
        "faultpoint FIRING: %s action=%s (fire #%d) [pid %d]",
        spec.name,
        spec.action,
        spec.fired,
        os.getpid(),
    )
    return True


def _execute_sync(spec: FaultSpec) -> Optional[str]:
    if spec.action == "die":
        # The doomed process's last act: flush its flight ring to disk.
        # os._exit skips atexit, so this is the only post-mortem an
        # injected death ever leaves (the acceptance path for "volume
        # died — what were its last five seconds?").
        obs_recorder.dump_postmortem(f"fault_die:{spec.name}")
        os._exit(17)
    if spec.action == "raise":
        raise FaultInjectedError(f"injected fault at {spec.name!r}")
    if spec.action == "delay":
        time.sleep(spec.delay_ms / 1000.0)
        return None
    if spec.action == "wedge":
        time.sleep(WEDGE_S)
        return None
    return spec.action  # drop-frame: the site interprets the sentinel


async def _execute_async(spec: FaultSpec) -> Optional[str]:
    import asyncio

    if spec.action == "die":
        obs_recorder.dump_postmortem(f"fault_die:{spec.name}")
        os._exit(17)
    if spec.action == "raise":
        raise FaultInjectedError(f"injected fault at {spec.name!r}")
    if spec.action == "delay":
        await asyncio.sleep(spec.delay_ms / 1000.0)
        return None
    if spec.action == "wedge":
        await asyncio.sleep(WEDGE_S)
        return None
    return spec.action


def fire(name: str) -> Optional[str]:
    """Synchronous faultpoint. Disarmed cost: one dict lookup. Returns the
    action sentinel for pass-through actions (``drop-frame``), else None."""
    spec = _armed.get(name)
    if spec is None or not _take(spec):
        return None
    return _execute_sync(spec)


async def afire(name: str) -> Optional[str]:
    """Async faultpoint: like :func:`fire` but delay/wedge suspend only the
    firing task (the process's event loop — and its ping — stay live)."""
    spec = _armed.get(name)
    if spec is None or not _take(spec):
        return None
    return await _execute_async(spec)


# --------------------------------------------------------------------------
# env parsing (import-time + after fork)
# --------------------------------------------------------------------------


def parse_spec(text: str) -> list[dict[str, Any]]:
    """Parse ``name=action[:count=N][:prob=P][:delay_ms=D];...`` into arm()
    kwargs. Raises ValueError on malformed entries (a chaos schedule that
    silently half-parses would make tests vacuous)."""
    out: list[dict[str, Any]] = []
    for chunk in text.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        head, _, opts = chunk.partition(":")
        name, sep, action = head.partition("=")
        if not sep:
            raise ValueError(f"malformed faultpoint entry {chunk!r}")
        kwargs: dict[str, Any] = {"name": name.strip(), "action": action.strip()}
        for opt in filter(None, (o.strip() for o in opts.split(":"))):
            k, sep, v = opt.partition("=")
            if not sep or k not in ("count", "prob", "delay_ms"):
                raise ValueError(f"malformed faultpoint option {opt!r}")
            kwargs[k] = int(v) if k == "count" else float(v)
        out.append(kwargs)
    return out


def _arm_from_env() -> None:
    text = os.environ.get(ENV_FAULTPOINTS)
    if not text:
        return
    try:
        for kwargs in parse_spec(text):
            arm(**kwargs)
    except ValueError:
        # Malformed env must not kill a booting volume; it just disarms.
        logger.exception("ignoring malformed %s=%r", ENV_FAULTPOINTS, text)


def reinit_after_fork() -> None:
    """Re-arm from the (corrected) child env: forked actor children inherit
    the forkserver's module state, not its parent's env."""
    _armed.clear()
    _arm_from_env()


_arm_from_env()
