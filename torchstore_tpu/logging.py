"""Logging + lightweight latency/throughput tracking.

Equivalent of /root/reference/torchstore/logging.py:13-66: root-level config
from an env var, and a ``LatencyTracker`` that records named steps plus
end-to-end wall time and formats GB/s when a byte count is supplied.

Trace export lives in ``torchstore_tpu.observability.tracing`` (this module
once held a private ``_TraceCollector``; the public subsystem replaced it).
``LatencyTracker`` phases still land in the same Chrome-trace file as
``observability.span`` events when ``TORCHSTORE_TPU_TRACE`` is set.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

from torchstore_tpu.observability import tracing

_INITIALIZED = False

ENV_LOG_LEVEL = "TORCHSTORE_TPU_LOG_LEVEL"
ENV_TRACE = tracing.ENV_TRACE

# The process-global trace collector (compat alias — tests and older callers
# reach the collector through ``logging._trace``).
_trace = tracing.collector()


def init_logging() -> None:
    global _INITIALIZED
    if _INITIALIZED:
        return
    level_name = os.environ.get(ENV_LOG_LEVEL, "WARNING").upper()
    level = getattr(logging, level_name, logging.WARNING)
    logging.basicConfig(
        level=level,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    logging.getLogger("torchstore_tpu").setLevel(level)
    _INITIALIZED = True


def get_logger(name: str) -> logging.Logger:
    init_logging()
    return logging.getLogger(name)


def set_log_level(level_name: str) -> None:
    """Apply a config-driven log level (overrides the env-var default chosen
    at import). Called by ``initialize(config=...)`` so ``StoreConfig.log_level``
    is authoritative once a store exists."""
    level = getattr(logging, level_name.upper(), logging.WARNING)
    logging.getLogger("torchstore_tpu").setLevel(level)


def _format_throughput(nbytes: int, seconds: float) -> str:
    if seconds <= 0:
        return "inf GB/s"
    return f"{nbytes / seconds / 1e9:.3f} GB/s"


class LatencyTracker:
    """Per-step + end-to-end wall-clock tracking with optional GB/s.

    ``track_step`` records the time since the previous mark; ``log_summary``
    emits one line per step plus the total. INFO level is used for weight-sync
    phases so users see throughput without enabling debug (reference behavior,
    /root/reference/torchstore/logging.py:31-66).
    """

    def __init__(self, name: str, logger: Optional[logging.Logger] = None) -> None:
        self.name = name
        self.logger = logger or get_logger("torchstore_tpu.latency")
        self._start = time.perf_counter()
        self._last = self._start
        self.steps: list[tuple[str, float, Optional[int]]] = []

    def track_step(self, step: str, nbytes: Optional[int] = None) -> float:
        now = time.perf_counter()
        elapsed = now - self._last
        if _trace.enabled:
            _trace.add(self.name, step, self._last, elapsed, nbytes)
        self._last = now
        self.steps.append((step, elapsed, nbytes))
        return elapsed

    @property
    def elapsed(self) -> float:
        return time.perf_counter() - self._start

    def log_summary(self, level: int = logging.DEBUG) -> None:
        total = self.elapsed
        total_bytes = 0
        for step, elapsed, nbytes in self.steps:
            extra = ""
            if nbytes is not None:
                total_bytes += nbytes
                extra = f" ({_format_throughput(nbytes, elapsed)})"
            self.logger.log(level, "[%s] %s: %.4fs%s", self.name, step, elapsed, extra)
        extra = ""
        if total_bytes:
            extra = f" ({_format_throughput(total_bytes, total)})"
        self.logger.log(level, "[%s] e2e: %.4fs%s", self.name, total, extra)
