"""Logging + lightweight latency/throughput tracking + trace export.

Equivalent of /root/reference/torchstore/logging.py:13-66: root-level config
from an env var, and a ``LatencyTracker`` that records named steps plus
end-to-end wall time and formats GB/s when a byte count is supplied.

Beyond the reference (SURVEY §5 notes it has "no integration with torch
profiler/perfetto"): set ``TORCHSTORE_TPU_TRACE=/path/trace.json`` and every
LatencyTracker phase is ALSO recorded as a Chrome-trace complete event;
the file (written at process exit, one per process, pid-suffixed when
needed) loads directly in Perfetto / chrome://tracing, aligning store
phases (flatten, handshakes, data-plane copies, notify) on a timeline next
to jax profiler traces.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import threading
import time
from typing import Optional

_INITIALIZED = False

ENV_LOG_LEVEL = "TORCHSTORE_TPU_LOG_LEVEL"
ENV_TRACE = "TORCHSTORE_TPU_TRACE"


class _TraceCollector:
    """Process-global Chrome-trace event buffer (enabled by env var).
    Events stream to disk in the JSON *array* format, appending every
    FLUSH_EVERY events — the format's closing ``]`` is optional, so the
    file is loadable after a crash and memory stays bounded in
    long-running loops."""

    FLUSH_EVERY = 1000

    def __init__(self) -> None:
        self.path = os.environ.get(ENV_TRACE)
        self.events: list[dict] = []
        self._lock = threading.Lock()
        self._registered = False
        self._resolved_path: Optional[str] = None
        self._resolved_for: Optional[str] = None
        self._wrote_header = False

    @property
    def enabled(self) -> bool:
        return bool(self.path)

    def add(self, name: str, phase: str, start_s: float, dur_s: float,
            nbytes: Optional[int]) -> None:
        if not self.path:
            return
        event = {
            "name": f"{name}/{phase}",
            "cat": "torchstore",
            "ph": "X",
            "ts": start_s * 1e6,
            "dur": dur_s * 1e6,
            "pid": os.getpid(),
            "tid": threading.get_ident() & 0xFFFF,
        }
        if nbytes is not None:
            event["args"] = {
                "bytes": nbytes,
                "GBps": round(nbytes / dur_s / 1e9, 3) if dur_s > 0 else None,
            }
        with self._lock:
            self.events.append(event)
            if not self._registered:
                self._registered = True
                atexit.register(self.flush)
            if len(self.events) >= self.FLUSH_EVERY:
                self._flush_locked()

    def _resolve_path(self) -> str:
        # Re-resolve if the target changed (tests swap it) — and CLAIM the
        # file with O_EXCL: volume actors and the client all trace, and two
        # processes exists()-checking concurrently would interleave appends
        # into one corrupt file. The loser takes a pid-suffixed name.
        if self._resolved_path is None or self._resolved_for != self.path:
            base = self.path
            root, ext = os.path.splitext(base)
            pid_path = f"{root}.{os.getpid()}{ext or '.json'}"
            chosen = pid_path
            for cand in (base, pid_path):
                try:
                    os.close(
                        os.open(cand, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
                    )
                    chosen = cand
                    break
                except FileExistsError:
                    continue
                except OSError:
                    break
            self._resolved_path = chosen
            self._resolved_for = self.path
            self._wrote_header = False
        return self._resolved_path

    def _flush_locked(self) -> None:
        if not self.path or not self.events:
            return
        chunk = self.events
        self.events = []
        try:
            with open(self._resolve_path(), "a") as f:
                for event in chunk:
                    f.write("[\n" if not self._wrote_header else ",\n")
                    self._wrote_header = True
                    json.dump(event, f)
        except OSError:
            pass

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()


_trace = _TraceCollector()


def init_logging() -> None:
    global _INITIALIZED
    if _INITIALIZED:
        return
    level_name = os.environ.get(ENV_LOG_LEVEL, "WARNING").upper()
    level = getattr(logging, level_name, logging.WARNING)
    logging.basicConfig(
        level=level,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    logging.getLogger("torchstore_tpu").setLevel(level)
    _INITIALIZED = True


def get_logger(name: str) -> logging.Logger:
    init_logging()
    return logging.getLogger(name)


def set_log_level(level_name: str) -> None:
    """Apply a config-driven log level (overrides the env-var default chosen
    at import). Called by ``initialize(config=...)`` so ``StoreConfig.log_level``
    is authoritative once a store exists."""
    level = getattr(logging, level_name.upper(), logging.WARNING)
    logging.getLogger("torchstore_tpu").setLevel(level)


def _format_throughput(nbytes: int, seconds: float) -> str:
    if seconds <= 0:
        return "inf GB/s"
    return f"{nbytes / seconds / 1e9:.3f} GB/s"


class LatencyTracker:
    """Per-step + end-to-end wall-clock tracking with optional GB/s.

    ``track_step`` records the time since the previous mark; ``log_summary``
    emits one line per step plus the total. INFO level is used for weight-sync
    phases so users see throughput without enabling debug (reference behavior,
    /root/reference/torchstore/logging.py:31-66).
    """

    def __init__(self, name: str, logger: Optional[logging.Logger] = None) -> None:
        self.name = name
        self.logger = logger or get_logger("torchstore_tpu.latency")
        self._start = time.perf_counter()
        self._last = self._start
        self.steps: list[tuple[str, float, Optional[int]]] = []

    def track_step(self, step: str, nbytes: Optional[int] = None) -> float:
        now = time.perf_counter()
        elapsed = now - self._last
        if _trace.enabled:
            _trace.add(self.name, step, self._last, elapsed, nbytes)
        self._last = now
        self.steps.append((step, elapsed, nbytes))
        return elapsed

    @property
    def elapsed(self) -> float:
        return time.perf_counter() - self._start

    def log_summary(self, level: int = logging.DEBUG) -> None:
        total = self.elapsed
        total_bytes = 0
        for step, elapsed, nbytes in self.steps:
            extra = ""
            if nbytes is not None:
                total_bytes += nbytes
                extra = f" ({_format_throughput(nbytes, elapsed)})"
            self.logger.log(level, "[%s] %s: %.4fs%s", self.name, step, elapsed, extra)
        extra = ""
        if total_bytes:
            extra = f" ({_format_throughput(total_bytes, total)})"
        self.logger.log(level, "[%s] e2e: %.4fs%s", self.name, total, extra)
