"""Client orchestration: the resharding planner.

TPU-native equivalent of /root/reference/torchstore/client.py:52-496. One
logical get becomes: locate (controller RPC) -> expand the wanted region
against every stored shard (slice intersection, replica dedup) -> per-volume
sub-requests fetched in parallel -> bounding-box assembly, with an in-place
fast path that lands transport writes directly in destination memory.
"""

from __future__ import annotations

import asyncio
import os
import time
import zlib
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from torchstore_tpu import sharding as shd
from torchstore_tpu import torch_interop
from torchstore_tpu.config import StoreConfig, default_config
from torchstore_tpu.faults import FaultInjectedError
from torchstore_tpu.controller import ObjectType, StorageInfo
from torchstore_tpu.logging import LatencyTracker, get_logger
from torchstore_tpu.native import copy_into
from torchstore_tpu.observability import context as obs_context
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import profile as obs_profile
from torchstore_tpu.observability import recorder as obs_recorder
from torchstore_tpu.observability import timeline as obs_timeline
from torchstore_tpu.observability.tracing import span
from torchstore_tpu.runtime import ActorDiedError, ActorRef
from torchstore_tpu.strategy import StorageVolumeRef
from torchstore_tpu.transport.buffers import TransportContext
from torchstore_tpu.transport.factory import (
    TransportType,
    create_transport_buffer,
    demotion_ladder,
)
from torchstore_tpu.transport.types import (
    OpaqueBlob,
    Request,
    TensorMeta,
    TensorSlice,
)
from torchstore_tpu.utils import (
    Box,
    assemble_tensor,
    get_destination_view,
    intersect_boxes,
    tensors_overlap_in_memory,
)

logger = get_logger("torchstore_tpu.client")

# Client-side op instruments: logical store operations (one put_batch is one
# op however many volumes/replicas it fans out to; transport-level counters
# in transport/buffers.py count the physical transfers underneath).
_OP_COUNT = obs_metrics.counter(
    "ts_client_ops_total", "Logical client operations by op"
)
_OP_BYTES = obs_metrics.counter(
    "ts_client_bytes_total", "Logical payload bytes by op (pre-replication)"
)
_OP_ERRORS = obs_metrics.counter(
    "ts_client_errors_total", "Failed client operations by op"
)
_OP_SECONDS = obs_metrics.histogram(
    "ts_client_op_seconds", "End-to-end wall time of one client op"
)
_FETCH_RETRIES = obs_metrics.counter(
    "ts_client_fetch_retries_total",
    "Batch fetches retried after a stale-location/ref failure",
)
_PLAN_HITS = obs_metrics.counter(
    "ts_plan_cache_hits_total",
    "put/get_state_dict iterations served by a cached transfer plan, by op",
)
_PLAN_MISSES = obs_metrics.counter(
    "ts_plan_cache_misses_total",
    "put/get_state_dict iterations that (re)built their transfer plan, by op",
)
_PLAN_INVALIDATIONS = obs_metrics.counter(
    "ts_plan_cache_invalidations_total",
    "Cached transfer plans dropped, by reason (epoch/capacity)",
)
_PUT_RETRIES = obs_metrics.counter(
    "ts_client_put_retries_total",
    "Non-replicated put landings retried under the unified RetryPolicy, "
    "by the transport rung the retry used",
)
_FAILOVERS = obs_metrics.counter(
    "ts_client_failovers_total",
    "Operations that succeeded only after failing over (get replica "
    "re-route or put transport demotion), by op",
)

# The ONE transient-failure family every retry/failover decision keys on:
# dead/wedged actors (ActorTimeoutError subclasses ActorDiedError), broken
# transport sockets, and injected chaos faults. Anything else (missing key,
# shape mismatch, type error) is a real answer and surfaces immediately.
RETRYABLE_ERRORS = (ActorDiedError, ConnectionError, OSError, FaultInjectedError)


class SyncPlanCache:
    """Iteration-stable transfer plans for ``put_state_dict`` /
    ``get_state_dict`` (the steady-state sync pipeline's control-plane leg).

    An RL weight-sync loop repeats the SAME size signature every iteration,
    yet the naive path re-validates structure, re-fetches the commit
    marker, and rebuilds request metadata each time. Plans are keyed by
    (op, state-dict key, size signature) and validated against the
    controller's placement epoch — which moves only on STRUCTURAL metadata
    changes (new/changed/deleted keys, detaches, repairs), never on
    same-shape overwrites — so iteration N+1 goes straight to the data
    plane; any placement change drops every plan (and the caller clears
    its location cache with them)."""

    MAX_ENTRIES = 64

    def __init__(self) -> None:
        self.entries: dict[tuple, dict] = {}
        # Last adopted controller placement epoch (None until first seen).
        self.epoch: Optional[int] = None
        # signature -> plan hint seeded by ts.prewarm (provision handoff):
        # the first put of a prewarmed working set adopts the arena layout
        # the provisioner already computed instead of re-deriving it.
        self.seeds: dict[tuple, dict] = {}
        # key -> signature of this client's last put_state_dict push: a
        # CHANGED signature under the same key means the structure was
        # republished — the index alone cannot always see that (dropping
        # keys from a push deletes nothing), so the publisher bumps the
        # placement epoch explicitly.
        self.last_put_sig: dict[str, tuple] = {}

    def observe_epoch(self, epoch: Optional[int]) -> bool:
        """Adopt a controller placement epoch; returns True when the bump
        invalidated cached plans (caller should clear location caches)."""
        if epoch is None or epoch == self.epoch:
            return False
        moved = self.epoch is not None
        self.epoch = epoch
        if moved and self.entries:
            _PLAN_INVALIDATIONS.inc(len(self.entries), reason="epoch")
            self.entries.clear()
        return moved

    def lookup(self, op: str, key: str, signature: tuple) -> Optional[dict]:
        entry = self.entries.get((op, key, signature))
        if entry is not None and entry.get("epoch") == self.epoch:
            _PLAN_HITS.inc(op=op)
            return entry
        _PLAN_MISSES.inc(op=op)
        return None

    def peek(self, op: str, key: str, signature: tuple) -> Optional[dict]:
        """Like lookup but without counting a hit/miss — used to decide
        whether an epoch-validation RPC is even worth issuing."""
        return self.entries.get((op, key, signature))

    def store(
        self,
        op: str,
        key: str,
        signature: tuple,
        plan: dict,
        epoch: Optional[int] = None,
    ) -> None:
        """``epoch`` pins the plan to the placement epoch it was BUILT
        under (callers capture it before fetching the data the plan
        describes) — stamping a later-observed epoch onto an earlier-built
        plan would let a mid-build structural change validate forever."""
        if len(self.entries) >= self.MAX_ENTRIES:
            # Wholesale clear, like the location cache: cheap, and a warm
            # working set re-fills in one iteration.
            _PLAN_INVALIDATIONS.inc(len(self.entries), reason="capacity")
            self.entries.clear()
        plan["epoch"] = self.epoch if epoch is None else epoch
        self.entries[(op, key, signature)] = plan

    def seed(self, signature: tuple, hint: dict) -> None:
        if len(self.seeds) >= self.MAX_ENTRIES:
            self.seeds.clear()
        self.seeds[signature] = hint


@dataclass
class Shard:
    """Explicit sharded value for put/get without a jax.Array: the raw shard
    data plus its TensorSlice placement (used by SPMD ranks and tests)."""

    data: Optional[np.ndarray]
    tensor_slice: TensorSlice


class LocalClient:
    # Bound on the per-client location cache; overflow clears wholesale
    # (cheap, and a warm working set re-fills in one locate round).
    LOC_CACHE_MAX = 65536

    def __init__(
        self,
        controller: ActorRef,
        config: Optional[StoreConfig] = None,
    ) -> None:
        from torchstore_tpu.metadata.router import MetadataRouter

        # Every controller RPC routes through the metadata router: it fans
        # index ops out per controller shard (when the store is sharded),
        # counts every metadata RPC into the traffic ledger, and serves
        # the warm-path reads (locate / plan validation / stream polling)
        # from same-host stamped segments with zero RPCs. Coordinator-
        # scoped ops — including the health diagnosis fan-out — pass
        # through to the one coordinator actor unchanged.
        if isinstance(controller, MetadataRouter):
            controller = controller.coordinator
        self._controller = MetadataRouter(controller)
        self._config = config or default_config()
        self._strategy = None
        self._volume_refs: Optional[dict[str, StorageVolumeRef]] = None
        self._ctx = TransportContext()
        # key -> {volume_id: StorageInfo}: saves the locate RPC on repeat
        # gets (the small-op fast path — reference clients locate on every
        # get, /root/reference/torchstore/client.py:204-237). Invalidated
        # on local deletes; cross-client relocations/deletes are discovered
        # by the fetch failing and retried once with a fresh locate.
        self._loc_cache: dict[str, dict[str, StorageInfo]] = {}
        # Negative memo for nearest-copy routing: (key, prefer_volume)
        # pairs a FRESH locate showed lacking the preferred replica.
        # Without it, every fetch of a key that will never land on the
        # relay volume (sharded keys stay point-to-point) would bypass
        # the location cache and pay a locate RPC forever. Cleared with
        # the location cache on every placement-epoch bump — relay
        # landings are structural, so a later local copy is re-seen.
        self._prefer_misses: set[tuple[str, str]] = set()
        # Volumes observed dead/wedged by THIS client: get ordering prefers
        # healthy replicas, so a replicated key survives a volume death
        # transparently (cleared when a later health check reports ok).
        self._dead_volumes: set[str] = set()
        # Last full-fleet diagnosis (monotonic timestamp + statuses): the
        # retry loops can fail many attempts per second during a correlated
        # outage, and each _raise_with_diagnosis would otherwise trigger a
        # controller-side ping fan-out across EVERY volume — one diagnosis
        # per window serves the whole loop.
        self._diag_at: float = 0.0
        self._diag_statuses: dict[str, str] = {}
        # Volumes the CONTROLLER's health supervisor has quarantined: puts
        # route around them and get ordering deprioritizes them. Refreshed
        # lazily after any placement-epoch bump (quarantine/reinstatement
        # transitions always bump the epoch).
        self._avoid_volumes: set[str] = set()
        self._volumes_stale = False
        # Epoch tracking when the plan cache is disabled (the cache tracks
        # it itself otherwise).
        self._seen_epoch: Optional[int] = None
        # Bumped whenever the volume map is dropped as stale (repair
        # replaced actors); _fetch retries once after any bump.
        self._refresh_epoch = 0
        # Iteration-stable transfer-plan cache (state_dict sync hot path);
        # None when disabled by config.
        self.plan_cache: Optional[SyncPlanCache] = (
            SyncPlanCache() if self._config.plan_cache else None
        )
        # Per-tenant admission gate (control plane, client-side half):
        # None unless armed — the unthrottled hot path pays one attribute
        # check per batch. The local overload probe is the router's
        # per-shard inflight view; slo_report overload feeds refresh()
        # when a harness ships it in.
        self._admission = None
        if self._config.control_admission:
            from torchstore_tpu.control.admission import AdmissionController

            self._admission = AdmissionController(
                self._config.admit_rate_hz,
                burst=self._config.admit_burst,
                tenant=self._config.tenant,
                overload_inflight=self._config.overload_inflight,
            )
            self._admission.bind_local_signal(
                self._controller.inflight_snapshot
            )
        # Hot-key read spreading (replica_spread): a stable per-client salt
        # rotates which equally-eligible replica sorts first, per key —
        # otherwise every client drains the same deterministic first choice
        # and the policy engine's hot-key splits never share load.
        self._spread_salt: Optional[str] = (
            f"{os.getpid()}-{id(self):x}"
            if self._config.replica_spread
            else None
        )

    @property
    def controller(self) -> ActorRef:
        return self._controller

    async def _ensure_setup(self) -> None:
        if self._volume_refs is not None:
            return
        await self._load_volumes()

    async def _load_volumes(self) -> None:
        """(Re)fetch strategy + volume map. The swap at the end is a single
        atomic assignment: concurrent operations keep using the previous
        (possibly stale but structurally valid) map mid-await — they fail
        and retry rather than crash on a half-built state."""
        self._controller.rpc_timeout = self._config.rpc_timeout
        # Metadata-plane topology first: shard refs make every index op
        # below routable, and same-host stamped segments arm the zero-RPC
        # warm paths (advisory — a topology-less controller still serves).
        await self._controller.load_topology(
            meta_stamped=self._config.meta_stamped
        )
        # Arm push-on-publish validation: a push-staged arena serves only
        # once the (possibly mirrored) stamped index confirms its pack-time
        # write generations, so a warm push serve stays zero-RPC end to end.
        from torchstore_tpu.transport.bulk import BulkClientCache

        self._ctx.get_cache(BulkClientCache).push_validate = (
            self._controller.stamped_write_gens
        )
        strategy = await self._controller.get_strategy.call_one()
        vmap = await self._controller.get_volume_map.call_one()
        forced = strategy.default_transport_type if strategy else None
        for info in vmap.values():
            # Every endpoint call on these refs inherits the configured RPC
            # deadline (a wedged-but-alive volume must never hang a client
            # forever — the supervision Monarch provides the reference).
            info["ref"].rpc_timeout = self._config.rpc_timeout
        self._strategy = strategy
        self._volume_refs = {
            vid: StorageVolumeRef(
                actor=info["ref"],
                volume_id=vid,
                transport_context=self._ctx,
                hostname=info["hostname"],
                transport_type=forced,
            )
            for vid, info in vmap.items()
        }

    def _observe_epoch(self, epoch: Optional[int]) -> None:
        """Adopt a controller placement epoch from any RPC reply; a bump
        drops cached plans AND cached locations together (both describe the
        placement that just changed) and marks the health view stale —
        quarantine/reinstatement transitions always bump the epoch, so the
        next put re-reads volume health before selecting targets."""
        if epoch is None:
            return
        bumped = False
        if self.plan_cache is not None:
            bumped = self.plan_cache.observe_epoch(epoch)
        elif self._seen_epoch is not None and epoch != self._seen_epoch:
            bumped = True
        self._seen_epoch = epoch
        if bumped:
            self._loc_cache.clear()
            self._prefer_misses.clear()
            self._volumes_stale = True
            self._drop_one_sided()

    def _drop_one_sided(self) -> None:
        """Epoch/stamp coupling: a placement-epoch bump (structural change,
        quarantine, repair) drops every cached one-sided plan — SHM stamped
        reads AND bulk doorbells — together with the location cache they
        were derived from. The seqlock stamps already make stale plans fall
        back on their own; this keeps the fallback storm to one miss per
        plan and re-routes warm gets with the fresh placement."""
        from torchstore_tpu.transport.bulk import BulkClientCache
        from torchstore_tpu.transport.shared_memory import ShmClientCache

        dropped = 0
        for cache_cls in (ShmClientCache, BulkClientCache):
            cache = self._ctx.peek(cache_cls)
            if cache is not None:
                dropped += cache.drop_one_sided()
        if dropped:
            _PLAN_INVALIDATIONS.inc(dropped, reason="one_sided_epoch")

    @staticmethod
    def _one_sided_miss(cache, miss, pairs) -> None:
        """Count a one-sided miss LOUDLY and, for the plan-invalidating
        family (stale/torn/gone), drop the batch's plans so the fallback
        RPC serve re-records fresh ones."""
        from torchstore_tpu.transport import shared_memory as shm_mod

        shm_mod.ONE_SIDED_FALLBACKS.inc(reason=miss.reason)
        if miss.reason in shm_mod.PLAN_DROPPING_MISSES:
            for pair in pairs:
                cache.one_sided.pop(pair, None)

    async def _refresh_health(self) -> None:
        """Re-read the controller's per-volume health (one cheap RPC, only
        after an epoch bump): quarantined AND draining volumes go into the
        avoid set so puts route around them — a draining volume (autoscale
        scale-in) keeps serving reads but must take no new placements or
        the drain never converges. Volumes the autoscaler attached or
        retired since the last refresh are adopted here too (the attach/
        retire epoch bump is what triggered this refresh)."""
        self._volumes_stale = False
        try:
            vmap = await self._controller.get_volume_map.call_one()
        except RETRYABLE_ERRORS:  # controller hiccup: keep the stale view
            return
        self._avoid_volumes = {
            vid
            for vid, info in vmap.items()
            if info.get("health") in ("quarantined", "draining")
        }
        if set(vmap) != set(self._volume_refs or {}):
            # Fleet membership changed (autoscale attach/retire): rebuild
            # the wrapped volume refs so puts can target new volumes and
            # stop holding refs to retired ones.
            await self._load_volumes()

    async def placement_epoch(self) -> int:
        """Fetch + adopt the controller's current placement epoch — the
        warm plan-validation read. Served from the coordinator's stamped
        header with ZERO RPCs whenever it CONFIRMS the epoch this client
        already holds (the steady-state case: nothing changed, plans stay
        valid). Any other stamped value — older (publish lag) or newer —
        falls back to the RPC for the authoritative answer: adopting a
        lagging epoch would spuriously invalidate every cached plan
        (observe_epoch keys on inequality), costing a rebuild storm for
        nothing."""
        from torchstore_tpu.metadata import router as meta_router

        known = (
            self.plan_cache.epoch
            if self.plan_cache is not None
            else self._seen_epoch
        )
        if known is not None:
            stamped = self._controller.stamped_epoch()
            if stamped is not None and stamped == known:
                meta_router.count_stamped("placement_epoch")
                return stamped
        epoch = await self._controller.placement_epoch.call_one()
        self._observe_epoch(epoch)
        return epoch

    async def bump_placement_epoch(self) -> int:
        """Force-invalidate cached transfer plans fleet-wide (publisher-side
        escape hatch for restructures the index cannot see)."""
        epoch = await self._controller.bump_placement_epoch.call_one()
        self._observe_epoch(epoch)
        return epoch

    async def _land_requests(
        self,
        volume: StorageVolumeRef,
        requests: list[Request],
        plan_hint: Optional[dict] = None,
        transport: Optional[TransportType] = None,
    ) -> dict[str, int]:
        """Data-plane landing of ``requests`` on one volume (batched where
        the transport supports it) — shared by put_batch and replicate_to.
        ``transport`` forces a specific rung (the put retry's demotion
        ladder). Returns the volume-assigned per-key write generations,
        forwarded to the controller so stale-replica reclaims can delete
        conditionally."""
        buffer = create_transport_buffer(volume, self._config, force=transport)
        buffer.plan_hint = plan_hint
        if buffer.supports_batch_puts:
            await buffer.put_to_storage_volume(volume, requests)
            return buffer.write_gens or {}
        await buffer.put_to_storage_volume(volume, requests[:1])
        gens = dict(buffer.write_gens or {})
        for req in requests[1:]:
            b = create_transport_buffer(volume, self._config, force=transport)
            await b.put_to_storage_volume(volume, [req])
            gens.update(b.write_gens or {})
        return gens

    def _put_volumes(self) -> list[StorageVolumeRef]:
        """Every volume a put writes to (primary + replicas). The strategy
        selects against the FULL volume list (strategies like
        LocalRankStrategy key on the client's own id being present); any
        selected volume that is quarantined or client-observed-dead is then
        substituted with a healthy unselected volume. With no healthy spare
        the avoided volume stays (degraded put: land on whoever answers,
        detach the rest) rather than starving the write."""
        client_id = self._strategy.get_client_id()
        selected = list(
            self._strategy.select_put_volume_ids(
                client_id, list(self._volume_refs)
            )
        )
        avoid = self._avoid_volumes | self._dead_volumes
        if avoid and any(vid in avoid for vid in selected):
            spares = sorted(
                vid
                for vid in self._volume_refs
                if vid not in avoid and vid not in selected
            )
            selected = [
                spares.pop(0) if vid in avoid and spares else vid
                for vid in selected
            ]
        return [self._volume_refs[vid] for vid in selected]

    # ------------------------------------------------------------------
    # put
    # ------------------------------------------------------------------

    @staticmethod
    def _value_to_requests(key: str, value: Any) -> list[Request]:
        if isinstance(value, Shard):
            data = value.data
            if torch_interop.is_torch_tensor(data):
                data = torch_interop.to_numpy_view(data)
            return [Request.from_tensor_slice(key, value.tensor_slice, data)]
        if shd.is_jax_array(value):
            return shd.put_requests(key, value)
        if isinstance(value, np.ndarray):
            return [Request.from_tensor(key, value)]
        if torch_interop.is_torch_tensor(value):
            # Zero-copy view: the transport reads straight out of the torch
            # storage (migration parity — reference callers hold torch
            # tensors everywhere).
            return [Request.from_tensor(key, torch_interop.to_numpy_view(value))]
        if isinstance(value, (int, float, complex)) or np.isscalar(value):
            return [Request.from_objects(key, OpaqueBlob.wrap(value))]
        if hasattr(value, "__array_interface__"):
            return [Request.from_tensor(key, np.asarray(value))]
        # Arbitrary objects are pickled HERE, in the client: volumes and
        # transports carry opaque bytes and never materialize user types
        # (materializing a jax-bearing leaf inside a volume process would
        # initialize an accelerator backend there).
        return [Request.from_objects(key, OpaqueBlob.wrap(value))]

    async def put(self, key: str, value: Any) -> None:
        await self.put_batch({key: value})

    async def put_batch(
        self,
        items: dict[str, Any],
        plan_hint: Optional[dict] = None,
        watermark: Optional[tuple] = None,
        unchanged: Optional[dict] = None,
    ) -> None:
        t0 = time.perf_counter()
        try:
            # ensure_root: every logical op roots (or joins) a distributed
            # trace — the id rides the notify/volume RPC frames so remote
            # spans stitch to this one in a merged timeline.
            with obs_context.ensure_root(), span(
                "put_batch",
                keys=len(items),
                key=next(iter(items), None),
            ) as sp:
                nbytes = await self._put_batch(
                    items, sp, plan_hint, watermark, unchanged
                )
                dur = time.perf_counter() - t0
                obs_profile.record_op(
                    "put",
                    next(iter(items), None),
                    nbytes,
                    t0,
                    dur,
                    tally=False,  # per-key tallies happen in _put_batch
                    keys=len(items),
                )
        except BaseException as exc:
            _OP_ERRORS.inc(op="put")
            obs_recorder.record(
                "error", "put", error=f"{type(exc).__name__}: {exc}"[:200]
            )
            raise
        _OP_COUNT.inc(op="put")
        _OP_BYTES.inc(nbytes, op="put")
        _OP_SECONDS.observe(dur, op="put")
        # Decision telemetry: rolling p50/p99 digests (+ their SLO checks)
        # and a flight-recorder breadcrumb — one each per BATCH.
        obs_timeline.observe_op("put", dur)
        obs_recorder.record(
            "op", "put", keys=len(items), nbytes=nbytes,
            ms=round(dur * 1e3, 3),
        )

    async def _put_batch(
        self,
        items: dict[str, Any],
        sp,
        plan_hint: Optional[dict] = None,
        watermark: Optional[tuple] = None,
        unchanged: Optional[dict] = None,
    ) -> int:
        if self._admission is not None:
            # Backpressure BEFORE any volume sees bytes: a bursting tenant
            # queues at its own bucket, not inside the landing pool.
            delay = self._admission.admit(len(items))
            if delay > 0.0:
                await asyncio.sleep(delay)
        await self._ensure_setup()
        if self._volumes_stale:
            await self._refresh_health()
        tracker = LatencyTracker("put_batch")
        # Issue every device->host copy for the WHOLE batch up front so
        # transfers overlap across arrays too, not just across one array's
        # shards (shd.put_requests overlaps within an array).
        for value in items.values():
            if shd.is_jax_array(value):
                for shard in value.addressable_shards:
                    shd._start_d2h(shard.data)
        requests: list[Request] = []
        for key, value in items.items():
            requests.extend(self._value_to_requests(key, value))
        volumes = self._put_volumes()
        # Stage attribution: everything before the first byte moves is the
        # planning leg (setup, D2H kicks, request building, placement).
        obs_timeline.observe_stage("put", "plan", tracker.elapsed)
        nbytes = sum(r.nbytes for r in requests)
        sp.set(nbytes=nbytes, replicas=len(volumes))
        hot = obs_profile.hot_key_tracker()
        for req in requests:
            hot.record(req.key, req.nbytes)

        async def put_to(volume: StorageVolumeRef) -> dict[str, int]:
            try:
                return await self._land_requests(volume, requests, plan_hint)
            except (ActorDiedError, ConnectionError, OSError) as exc:
                # Bulk/peer transports surface volume death as
                # ConnectionError — normalize so callers and the failover
                # machinery see one exception family.
                await self._raise_with_diagnosis(volume.volume_id, exc)

        async def land_all() -> tuple[list, list]:
            # Replicated puts hit every target volume concurrently.
            # return_exceptions: every write FINISHES before we decide (no
            # detached sibling tasks racing a caller's retry, no
            # unretrieved exceptions).
            results = await asyncio.gather(
                *(put_to(v) for v in volumes), return_exceptions=True
            )
            return (
                [
                    (v, r)
                    for v, r in zip(volumes, results)
                    if not isinstance(r, BaseException)
                ],
                [
                    (v, r)
                    for v, r in zip(volumes, results)
                    if isinstance(r, BaseException)
                ],
            )

        landed, failed = await land_all()
        if (
            not landed
            and len(volumes) > 1
            and all(isinstance(r, RETRYABLE_ERRORS) for _, r in failed)
        ):
            # EVERY replica failed transiently (correlated chaos, a fleet-
            # wide hiccup): a partial failure would detach-and-continue,
            # but with zero landed copies there is nothing to commit —
            # retry the whole replicated landing under the unified policy.
            policy = self._config.retry
            deadline = policy.start()
            attempt = 0
            while not landed and policy.should_retry(attempt, deadline):
                await asyncio.sleep(policy.backoff(attempt))
                attempt += 1
                # Re-resolve placement each attempt: the supervisor may
                # have quarantined the failed replicas meanwhile, or the
                # diagnosis marked them dead — _put_volumes substitutes
                # healthy spares for both, and land_all reads the rebound
                # list (the supersede notify detaches whatever the old
                # replicas still hold under these keys).
                if self._volumes_stale:
                    await self._refresh_health()
                fresh = self._put_volumes()
                if {v.volume_id for v in fresh} != {
                    v.volume_id for v in volumes
                }:
                    logger.warning(
                        "replicated put re-routed: %s -> %s",
                        sorted(v.volume_id for v in volumes),
                        sorted(v.volume_id for v in fresh),
                    )
                    volumes = fresh
                landed, retry_failed = await land_all()
                if landed:
                    failed = retry_failed
                    _FAILOVERS.inc(op="put")
                    logger.warning(
                        "replicated put recovered on retry %d (first "
                        "failure: %s)",
                        attempt,
                        failed[0][1] if failed else "all replicas",
                    )
                elif not all(
                    isinstance(r, RETRYABLE_ERRORS) for _, r in retry_failed
                ):
                    failed = retry_failed
                    break  # a real (non-transient) answer surfaced
        if not landed and len(volumes) == 1:
            # Non-replicated put: no sibling replica absorbs the failure,
            # so retry transient transport failures under the unified
            # RetryPolicy, demoting one transport rung per attempt
            # (shm -> bulk -> rpc). Volumes the controller diagnosed
            # dead/wedged/quarantined are NOT retried here — no transport
            # reaches a dead process (put_to's diagnosis populated
            # _dead_volumes before we got here).
            gens = await self._retry_put_demoted(
                volumes[0], requests, failed[0][1]
            )
            if gens is not None:
                landed, failed = [(volumes[0], gens)], []
            elif isinstance(failed[0][1], RETRYABLE_ERRORS):
                # The target itself is gone (diagnosed dead/wedged): re-
                # resolve placement — _put_volumes now filters it out — and
                # land on the next healthy volume. The supersede notify
                # below detaches whatever the dead volume still holds under
                # these keys, so its stale bytes can never resurface if it
                # is later reinstated.
                if self._volumes_stale:
                    await self._refresh_health()
                retry = self._put_volumes()
                if retry and retry[0].volume_id != volumes[0].volume_id:
                    try:
                        gens = await self._land_requests(retry[0], requests)
                    except RETRYABLE_ERRORS as exc:
                        logger.warning(
                            "put failover to %s failed too: %s",
                            retry[0].volume_id,
                            exc,
                        )
                    else:
                        landed, failed = [(retry[0], gens)], []
                        _FAILOVERS.inc(op="put")
                        logger.warning(
                            "put failed over from %s to %s",
                            volumes[0].volume_id,
                            retry[0].volume_id,
                        )
        if not landed:
            raise failed[0][1]
        # The wire legs themselves record the "transport" stage per volume
        # (transport/buffers.py) — the tracker only logs the wall span here.
        tracker.track_step("data_plane", nbytes)
        for volume, exc in failed:
            # Partial replication failure on an OVERWRITE would leave the
            # failed replica serving the previous value under still-
            # committed metadata — the notify below atomically detaches
            # its copies of exactly these metas, so readers only ever see
            # volumes holding the new bytes. The put succeeds at degraded
            # redundancy; the next successful put re-replicates.
            logger.warning(
                "replicated put degraded: volume %s failed (%s); detaching "
                "its stale copies",
                volume.volume_id,
                exc,
            )
        # Two-plane invariant: metadata notify happens only after the data
        # landed (/root/reference/torchstore/client.py:86-90). ONE RPC
        # indexes every landed replica and detaches every failed one — no
        # window where new metadata coexists with a stale replica location.
        epoch = await self._controller.notify_put_batch.call_one(
            [r.meta_only() for r in requests],
            [v.volume_id for v, _ in landed],
            detach_volume_ids=[v.volume_id for v, _ in failed] or None,
            write_gens={v.volume_id: gens for v, gens in landed},
            # Full overwrite: any volume OUTSIDE this put's replica set
            # still indexed for these metas (an auto-repair extra copy, or
            # a previous placement before failover re-routed) holds
            # superseded bytes — detach + reclaim them in the same step.
            supersede=True,
            # Streamed publishes stamp every key of this batch with the
            # stream version in the same indexing step — the watermark is
            # only ever visible once its bytes are committed.
            watermark=watermark,
            # Unchanged-key aliases (delta tier) ride the same step.
            unchanged=unchanged,
        )
        # The notify reply carries the placement epoch for free: a bump
        # (structural change anywhere in the fleet) drops cached plans.
        self._observe_epoch(epoch)
        obs_timeline.observe_stage("put", "notify", tracker.track_step("notify"))
        tracker.log_summary()
        return nbytes

    async def _retry_put_demoted(
        self,
        volume: StorageVolumeRef,
        requests: list[Request],
        first_exc: BaseException,
    ) -> Optional[dict[str, int]]:
        """Retry a failed single-volume landing under ``config.retry``,
        walking down the transport ladder one rung per attempt. Returns the
        write generations on success, None when the policy is exhausted or
        the volume is diagnosed dead (caller surfaces ``first_exc``)."""
        if not isinstance(first_exc, RETRYABLE_ERRORS):
            return None
        if volume.volume_id in self._dead_volumes:
            return None
        policy = self._config.retry
        deadline = policy.start()
        ladder = demotion_ladder(volume, self._config)
        attempt = 0
        while policy.should_retry(attempt, deadline):
            await asyncio.sleep(policy.backoff(attempt))
            rung = ladder[min(attempt + 1, len(ladder) - 1)]
            try:
                # plan_hint deliberately dropped: it describes the rung
                # that just failed (e.g. an shm arena layout).
                gens = await self._land_requests(
                    volume, requests, transport=rung
                )
            except RETRYABLE_ERRORS as exc:
                attempt += 1
                logger.warning(
                    "put retry %d on %s over %s failed: %s",
                    attempt,
                    volume.volume_id,
                    rung.value,
                    exc,
                )
                if volume.volume_id in self._dead_volumes:
                    return None
                continue
            _PUT_RETRIES.inc(transport=rung.value)
            _FAILOVERS.inc(op="put")
            logger.warning(
                "non-replicated put to %s recovered on transport %s after "
                "%d retr%s (first failure: %s)",
                volume.volume_id,
                rung.value,
                attempt + 1,
                "y" if attempt == 0 else "ies",
                first_exc,
            )
            return gens
        return None

    # ------------------------------------------------------------------
    # get
    # ------------------------------------------------------------------

    async def get(self, key: str, like: Any = None) -> Any:
        results = await self.get_batch({key: like})
        return results[key]

    async def get_batch(
        self,
        items,
        _seed_plan: bool = True,
        prefer_volume: Optional[str] = None,
    ) -> dict[str, Any]:
        """All-or-nothing batched get (invariant 8): any missing key fails the
        whole batch before data moves (locate happens up front). ``items``
        is either a list of keys or {key: fetch_target_or_None} (reference
        signature parity, /root/reference/torchstore/api.py:242-279).

        ``prefer_volume``: replica-selection preference — when a key has a
        copy on this volume (e.g. the caller's RELAY volume, holding the
        broadcast-distributed local copy), fetch from it; other replicas
        stay as fallback. Never a hard pin: a key absent there serves from
        wherever it lives.

        ``_seed_plan=False`` (internal): state-dict ops manage their own
        SyncPlanCache entries and epoch validation — they skip the
        batch-level seeding below to avoid double bookkeeping."""
        t0 = time.perf_counter()
        try:
            with obs_context.ensure_root(), span(
                "get_batch", keys=len(items)
            ) as sp:
                out = await self._get_batch(
                    items, _seed_plan=_seed_plan, prefer_volume=prefer_volume
                )
                # Stored OBJECTS come back as arbitrary user types; only
                # count an nbytes attribute that is actually a number.
                sizes = [
                    (
                        key,
                        n if isinstance((n := getattr(v, "nbytes", 0)), int) else 0,
                    )
                    for key, v in out.items()
                ]
                nbytes = sum(n for _, n in sizes)
                sp.set(nbytes=nbytes)
                dur = time.perf_counter() - t0
                obs_profile.record_keys("get", sizes, t0, dur)
        except BaseException as exc:
            _OP_ERRORS.inc(op="get")
            obs_recorder.record(
                "error", "get", error=f"{type(exc).__name__}: {exc}"[:200]
            )
            raise
        _OP_COUNT.inc(op="get")
        _OP_BYTES.inc(nbytes, op="get")
        _OP_SECONDS.observe(dur, op="get")
        obs_timeline.observe_op("get", dur)
        obs_recorder.record(
            "op", "get", keys=len(items), nbytes=nbytes,
            ms=round(dur * 1e3, 3),
        )
        return out

    async def _get_batch(
        self,
        items,
        _seed_plan: bool = True,
        prefer_volume: Optional[str] = None,
    ) -> dict[str, Any]:
        if isinstance(items, str):
            raise TypeError(
                "get_batch takes a list of keys or a {key: target} dict, "
                f"not a bare string ({items!r}); use get() for one key"
            )
        if not isinstance(items, dict):
            items = {key: None for key in items}
        if self._admission is not None:
            delay = self._admission.admit(len(items))
            if delay > 0.0:
                await asyncio.sleep(delay)
        await self._ensure_setup()
        if self._config.one_sided:
            # Covered warm batch: every member served straight from stamped
            # SHM segments BEFORE any Request/signature machinery runs —
            # the many-keys warm get leg is this line plus one native
            # scatter memcpy (zero RPCs; ISSUE 7 acceptance).
            served = await self._get_batch_one_sided(items)
            if served is not None:
                return served
        plan: list[tuple[str, Request, Any]] = []  # (key, request, like)
        # plan index -> device array served one-sided before any request was
        # built (plain-spec warm path: device_put straight from the stamped
        # segment view — no host copy, no RPC).
        pre_served: dict[int, Any] = {}
        jax_targets: dict[int, list] = {}
        # plan index -> (original torch tensor, its numpy view): the original
        # is handed back only when the fetch actually landed in the view.
        torch_returns: dict[int, tuple[Any, np.ndarray]] = {}
        requests: list[Request] = []
        for key, like in items.items():
            if torch_interop.is_torch_tensor(like):
                view = torch_interop.to_numpy_view(like, allow_copy=False)
                torch_returns[len(plan)] = (like, view)
                like = view
            if like is None:
                requests.append(Request.meta_request(key))
                plan.append((key, requests[-1], None))
            elif isinstance(like, Shard):
                data = like.data
                if torch_interop.is_torch_tensor(data):
                    view = torch_interop.to_numpy_view(data, allow_copy=False)
                    torch_returns[len(plan)] = (data, view)
                    like = Shard(data=view, tensor_slice=like.tensor_slice)
                req = Request.from_tensor_slice(key, like.tensor_slice)
                req.tensor_val = like.data
                requests.append(req)
                plan.append((key, req, like))
            elif isinstance(like, TensorSlice):
                requests.append(Request.from_tensor_slice(key, like))
                plan.append((key, requests[-1], like))
            elif shd.is_jax_array(like) or shd.is_sharded_spec(like):
                # target_slices/build_array only need .shape/.sharding, so a
                # ShapeDtypeStruct works as a no-allocation restore target.
                targets = shd.target_slices(like)
                jax_targets[len(plan)] = targets
                sub_reqs = [Request.from_tensor_slice(key, ts) for _, ts in targets]
                requests.extend(sub_reqs)
                plan.append((key, sub_reqs, like))
            elif shd.is_plain_spec(like):
                # Sharding-less ShapeDtypeStruct: fetch the whole tensor and
                # return a default-placed device array of the spec's dtype.
                # Warm path first: upload straight from the stamped segment.
                served = self._try_one_sided_device(key, like)
                if served is not None:
                    pre_served[len(plan)] = served
                    plan.append((key, None, like))
                else:
                    requests.append(Request.meta_request(key))
                    plan.append((key, requests[-1], like))
            elif isinstance(like, np.ndarray):
                req = Request(key=key, tensor_val=like)
                requests.append(req)
                plan.append((key, req, like))
            else:
                raise TypeError(f"unsupported get target {type(like)} for {key!r}")

        # Batch-level plan seeding (the get_batch leg of the iteration-
        # stable plan cache — previously only state-dict ops populated it):
        # a repeated identical batch validates with ONE epoch check instead
        # of per-key locates, and skips even that when every member has a
        # one-sided plan (the stamped reads self-validate).
        pc = self.plan_cache
        batch_sig = self._batch_signature(items) if _seed_plan and pc else None
        batch_plan = None
        if batch_sig is not None and pc.peek("get_batch", "", batch_sig):
            if not self._one_sided_covers(requests):
                await self.placement_epoch()
            batch_plan = pc.lookup("get_batch", "", batch_sig)
            if batch_plan is not None:
                if len(self._loc_cache) + len(batch_plan["located"]) > (
                    self.LOC_CACHE_MAX
                ):
                    self._loc_cache.clear()
                for k, infos in batch_plan["located"].items():
                    self._loc_cache.setdefault(k, infos)
        flat_results = await self._fetch(requests, prefer_volume=prefer_volume)
        if batch_sig is not None and batch_plan is None:
            pc.store(
                "get_batch",
                "",
                batch_sig,
                {
                    "located": {
                        r.key: self._loc_cache[r.key]
                        for r in requests
                        if r.key in self._loc_cache
                    }
                },
            )
        by_request = dict(zip((id(r) for r in requests), flat_results))

        out: dict[str, Any] = {}
        for idx, (key, req_or_list, like) in enumerate(plan):
            if idx in pre_served:
                out[key] = pre_served[idx]
                continue
            if isinstance(req_or_list, list):  # jax target
                targets = jax_targets[idx]
                # Honor the target's dtype (the orbax restore idiom: a
                # bf16 spec over fp32-stored weights converts on fetch).
                want_dtype = (
                    TensorMeta(shape=(), dtype=str(like.dtype)).np_dtype
                    if hasattr(like, "dtype")
                    else None
                )
                parts = []
                for (dev, _), r in zip(targets, req_or_list):
                    arr = np.asarray(by_request[id(r)])
                    if want_dtype is not None and arr.dtype != want_dtype:
                        arr = arr.astype(want_dtype)
                    parts.append((dev, arr))
                out[key] = shd.build_array(like, parts)
            elif shd.is_plain_spec(like):
                import jax.numpy as jnp

                arr = np.asarray(by_request[id(req_or_list)])
                if tuple(arr.shape) != tuple(like.shape):
                    raise ValueError(
                        f"stored shape {tuple(arr.shape)} != spec shape "
                        f"{tuple(like.shape)} for key {key!r}"
                    )
                out[key] = jnp.asarray(arr, dtype=like.dtype)
            else:
                out[key] = by_request[id(req_or_list)]
            if idx in torch_returns:
                tensor, view = torch_returns[idx]
                # Hand the caller their tensor object back ONLY if the fetch
                # landed in its storage (assemble returns the dest view). A
                # key stored as a plain object comes back as that object —
                # never a silently unfilled tensor.
                if out[key] is view:
                    out[key] = tensor
        return out

    async def _get_batch_one_sided(self, items: dict) -> Optional[dict]:
        """Whole-batch one-sided serve for the simple warm shape: every
        target is None or a plain numpy destination and every key has a
        cached stamped plan. Runs before the per-item Request-building
        loop — at many-keys scale that loop (type dispatch, Request
        construction, signature/seeding bookkeeping) costs more than the
        copies. Returns None (untouched batch) when any member doesn't
        qualify; misses drop stale plans and fall back to the full path,
        exactly like ``_fetch_all_one_sided``."""
        from torchstore_tpu.transport import shared_memory as shm_mod

        cache = self._ctx.peek(shm_mod.ShmClientCache)
        if cache is None or not cache.one_sided:
            return None
        one_sided = cache.one_sided
        plans: list[dict] = []
        dests: list[Optional[np.ndarray]] = []
        for key, like in items.items():
            if like is not None and type(like) is not np.ndarray:
                return None
            plan = shm_mod.covered_plan(
                one_sided, key, None, has_dest=like is not None
            )
            if plan is None:
                return None
            plans.append(plan)
            dests.append(like)
        try:
            results = await shm_mod.stamped_read_batch(
                cache, plans, dests, config=self._config
            )
        except shm_mod.OneSidedMiss as miss:
            self._one_sided_miss(cache, miss, [(key, None) for key in items])
            return None
        return dict(zip(items, results))

    def _batch_signature(self, items: dict) -> Optional[tuple]:
        """Hashable identity of a get_batch request set (keys + target
        layouts) — the plan-cache key for batch-level seeding. None when a
        target has no stable signature (that batch is not plan-cached)."""
        from torchstore_tpu.state_dict_utils import _leaf_signature

        try:
            return tuple(
                (key, None if like is None else _leaf_signature(like))
                for key, like in items.items()
            )
        except Exception:  # noqa: BLE001 - unsignable target: skip caching
            return None

    # ------------------------------------------------------------------
    # fetch pipeline
    # ------------------------------------------------------------------

    async def _fetch(
        self,
        requests: list[Request],
        prefer_volume: Optional[str] = None,
    ) -> list[Any]:
        """Fetch with two retry families layered on ``_fetch_once``:

        - *Stale state* (KeyError/ValueError: another client deleted or
          re-published a key, layout mismatch): ONE fresh retry — a missing
          key is an answer, not a transient, so no backoff loop.
        - *Transient* (dead/wedged actors, broken sockets, injected
          faults): retries under the unified RetryPolicy. Each failure's
          diagnosis marks unhealthy volumes, so the re-located retry fails
          over to the next healthy replica; retries continue only while a
          volume this client has NOT seen fail remains (when every volume
          is known-dead, waiting out the deadline helps nobody — surface)."""
        policy = self._config.retry
        deadline = policy.start()
        attempt = 0
        stale_retried = False
        while True:
            epoch = self._refresh_epoch
            try:
                out = await self._fetch_once(
                    requests,
                    use_cache=attempt == 0 and not stale_retried,
                    prefer_volume=prefer_volume,
                )
                if attempt > 0:
                    _FAILOVERS.inc(op="get")
                return out
            except RETRYABLE_ERRORS as exc:
                for req in requests:
                    self._loc_cache.pop(req.key, None)
                alive = [
                    v
                    for v in (self._volume_refs or {})
                    if v not in self._dead_volumes
                ]
                if not alive and attempt > 0:
                    raise  # whole fleet diagnosed down: nothing to fail over to
                if not policy.should_retry(attempt, deadline):
                    raise
                _FETCH_RETRIES.inc()
                logger.warning(
                    "fetch attempt %d failed (%s); failing over "
                    "(%d healthy volume(s) remain)",
                    attempt + 1,
                    exc,
                    len(alive),
                )
                await asyncio.sleep(policy.backoff(attempt))
                attempt += 1
            except (KeyError, ValueError) as exc:
                stale = [r.key for r in requests if r.key in self._loc_cache]
                if stale_retried or (
                    not stale and self._refresh_epoch == epoch
                ):
                    raise
                stale_retried = True
                for key in stale:
                    self._loc_cache.pop(key, None)
                _FETCH_RETRIES.inc()
                logger.info(
                    "stale location/refs for %d key(s) (%s); re-locating",
                    len(stale),
                    exc,
                )

    async def _fetch_once(
        self,
        requests: list[Request],
        use_cache: bool,
        prefer_volume: Optional[str] = None,
    ) -> list[Any]:
        # Refs may have been dropped by a stale-ref diagnosis between the
        # first attempt and this retry; rebuild them from the controller.
        await self._ensure_setup()
        if use_cache and self._config.one_sided:
            served = await self._fetch_all_one_sided(requests)
            if served is not None:
                return served
        t_plan = time.perf_counter()
        keys = list({r.key for r in requests})
        located: dict[str, dict[str, StorageInfo]] = {}
        missing = []
        for key in keys:
            cached = self._loc_cache.get(key) if use_cache else None
            if (
                cached is not None
                and prefer_volume is not None
                and prefer_volume not in cached
                and (key, prefer_volume) not in self._prefer_misses
            ):
                # Nearest-copy routing: the cached locations predate the
                # relay landing this caller's local replica (another
                # subscriber of the same client located the key earlier) —
                # a stale entry here would silently re-route every read
                # back to the origin volumes. Re-locate ONCE per placement
                # epoch; if the fresh view still lacks the preferred
                # replica the miss is memoized and the key serves from
                # wherever it lives.
                cached = None
            if cached is not None:
                located[key] = cached
            else:
                missing.append(key)
        if missing and use_cache and prefer_volume is None:
            # One-sided warm locate: committed locations from the stamped
            # metadata segments (zero RPCs), filling the location cache so
            # the staleness ladder below them is EXACTLY the warm-cache
            # one — a lingering deleted key fails at the volume and the
            # fetch retries with use_cache=False, which skips this path
            # and pays the authoritative RPC locate.
            hits = self._controller.stamped_locate(missing)
            if hits:
                if len(self._loc_cache) + len(hits) > self.LOC_CACHE_MAX:
                    self._loc_cache.clear()
                self._loc_cache.update(hits)
                located.update(hits)
                missing = [k for k in missing if k not in hits]
        if missing:
            fresh = await self._controller.locate_volumes.call_one(missing)
            if len(self._loc_cache) + len(fresh) > self.LOC_CACHE_MAX:
                self._loc_cache.clear()
            self._loc_cache.update(fresh)
            located.update(fresh)
            if prefer_volume is not None:
                if len(self._prefer_misses) > self.LOC_CACHE_MAX:
                    self._prefer_misses.clear()
                self._prefer_misses.update(
                    (key, prefer_volume)
                    for key, infos in fresh.items()
                    if prefer_volume not in infos
                )
        # Stage attribution: location resolve (cache / stamped segments /
        # RPC locate) + request partitioning is the get's planning leg.
        obs_timeline.observe_stage(
            "get", "plan", time.perf_counter() - t_plan
        )
        # volume_id -> list of (request_index, sub_request)
        by_volume: dict[str, list[tuple[int, Request]]] = {}
        inplace_ok = self._transports_support_inplace(located)
        for idx, req in enumerate(requests):
            subs = self._build_volume_requests(
                req, located[req.key], inplace_ok, prefer_volume=prefer_volume
            )
            for vid, sub in subs:
                by_volume.setdefault(vid, []).append((idx, sub))

        # Results are collected by SIDE EFFECT (tasks return None): a finished
        # asyncio Task retains its result until garbage collection, so
        # returning fetched arrays through gather() would keep zero-copy
        # views alive indefinitely — the volume would never see their
        # releases and every put would retire-and-reallocate segments.
        parts_by_request: dict[int, list[tuple[Request, Any]]] = {}

        # One-sided warm path: volumes whose every sub-request has a cached
        # stamped plan are served straight out of their pre-attached SHM
        # segments — zero RPCs — and leave the fan-out below entirely.
        if use_cache and self._config.one_sided:
            await self._serve_one_sided(by_volume, parts_by_request)

        async def fetch_volume(vid: str, entries: list[tuple[int, Request]]) -> None:
            volume = self._volume_refs[vid]
            buffer = create_transport_buffer(volume, self._config)
            subs = [sub for _, sub in entries]
            # Shard coordinates ride the span so a trace shows exactly which
            # mesh coords each volume served (straggler attribution).
            coords = [
                sub.tensor_slice.coordinates
                for sub in subs
                if sub.tensor_slice is not None
            ]
            with span(
                "fetch_volume",
                volume=vid,
                transport=buffer.transport_name,
                keys=len(subs),
                coords=coords if coords else None,
            ):
                try:
                    if buffer.supports_batch_gets or len(subs) == 1:
                        results = await buffer.get_from_storage_volume(
                            volume, subs
                        )
                    else:
                        results = []
                        for sub in subs:
                            b = create_transport_buffer(volume, self._config)
                            results.extend(
                                await b.get_from_storage_volume(volume, [sub])
                            )
                except (ActorDiedError, ConnectionError, OSError) as exc:
                    # Bulk/peer transports report volume death as
                    # ConnectionError; normalizing through the diagnosis path
                    # marks the volume dead so the retry prefers replicas.
                    await self._raise_with_diagnosis(vid, exc)
            for (idx, sub), res in zip(entries, results):
                parts_by_request.setdefault(idx, []).append((sub, res))

        await asyncio.gather(
            *(fetch_volume(vid, entries) for vid, entries in by_volume.items())
        )
        out = [
            self._assemble_result(req, parts_by_request.pop(idx, []))
            for idx, req in enumerate(requests)
        ]
        return out

    async def _fetch_all_one_sided(
        self, requests: list[Request]
    ) -> Optional[list[Any]]:
        """Whole-batch one-sided fast path: when EVERY request is a plain
        full-tensor fetch with a cached stamped plan, serve the lot as one
        stamped memcpy loop and skip the locate / per-key sub-request
        building / transport-buffer machinery entirely (measured ~40% of
        warm many-keys get wall time on a 2-vCPU host — per-key Python,
        not data movement). Returns None when any member is uncovered or
        the batch misses; stale/torn misses drop the affected plans so the
        normal path's RPC serve re-records fresh ones. Deleted keys miss
        too (tombstoned stamp), so the normal path still owns the loud
        KeyError."""
        from torchstore_tpu.transport import shared_memory as shm_mod

        cache = self._ctx.peek(shm_mod.ShmClientCache)
        if cache is None or not cache.one_sided:
            return None
        plans: list[dict] = []
        dests: list[Optional[np.ndarray]] = []
        for req in requests:
            if req.is_object or req.tensor_slice is not None:
                return None
            plan = shm_mod.covered_plan(
                cache.one_sided,
                req.key,
                None,
                has_dest=req.tensor_val is not None,
            )
            if plan is None:
                # Uncovered, or a destination-less big get where the RPC
                # path's zero-copy snapshot view beats a one-sided copy.
                return None
            plans.append(plan)
            dests.append(req.tensor_val)
        try:
            return await shm_mod.stamped_read_batch(
                cache, plans, dests, config=self._config
            )
        except shm_mod.OneSidedMiss as miss:
            self._one_sided_miss(
                cache, miss, [(req.key, None) for req in requests]
            )
            return None

    async def _serve_one_sided(
        self,
        by_volume: dict[str, list[tuple[int, Request]]],
        parts_by_request: dict[int, list[tuple[Request, Any]]],
    ) -> None:
        """Serve every fully plan-covered volume's sub-requests as one
        stamped memcpy loop (``shared_memory.stamped_read_batch``) and drop
        those volumes from the RPC fan-out. All-or-nothing per volume: a
        partially covered batch stays on the RPC path (it pays the RPC
        anyway, and the RPC serve refreshes every member's plan). Misses
        fall back LOUDLY (``ts_one_sided_fallbacks_total``); stale/torn/
        gone plans are dropped so the fallback RPC re-records fresh ones."""
        from torchstore_tpu.transport import shared_memory as shm_mod

        cache = self._ctx.peek(shm_mod.ShmClientCache)
        if cache is None or not cache.one_sided:
            return
        for vid in list(by_volume):
            entries = by_volume[vid]
            plans: Optional[list[dict]] = []
            for _, sub in entries:
                if sub.is_object:
                    plans = None
                    break
                plan = shm_mod.covered_plan(
                    cache.one_sided,
                    sub.key,
                    shm_mod.slice_sig(sub.tensor_slice),
                    has_dest=sub.destination_view is not None,
                )
                if plan is None:
                    plans = None
                    break
                plans.append(plan)
            if plans is None:
                continue
            dests = [sub.destination_view for _, sub in entries]
            try:
                results = await shm_mod.stamped_read_batch(
                    cache, plans, dests, config=self._config
                )
            except shm_mod.OneSidedMiss as miss:
                self._one_sided_miss(
                    cache,
                    miss,
                    [
                        (sub.key, shm_mod.slice_sig(sub.tensor_slice))
                        for _, sub in entries
                    ],
                )
                continue
            for (idx, sub), res in zip(entries, results):
                parts_by_request.setdefault(idx, []).append((sub, res))
            del by_volume[vid]

    def _one_sided_covers(self, requests: list[Request]) -> bool:
        """True when every request has a cached one-sided plan for its exact
        (key, slice): the warm batch can go ZERO-RPC, so even the epoch-
        validation RPC is skipped — the per-entry stamps self-validate (any
        placement change lands through the volume and moves them, and a
        deleted entry's tombstone forces the fallback that re-locates)."""
        if not self._config.one_sided or not requests:
            return False
        from torchstore_tpu.transport.shared_memory import (
            ShmClientCache,
            covered_plan,
            slice_sig,
        )

        cache = self._ctx.peek(ShmClientCache)
        if cache is None or not cache.one_sided:
            return False
        return all(
            not req.is_object
            and covered_plan(
                cache.one_sided,
                req.key,
                slice_sig(req.tensor_slice),
                has_dest=req.tensor_val is not None,
            )
            is not None
            for req in requests
        )

    def one_sided_covers_items(
        self, items: "list[tuple[str, bool]]"
    ) -> bool:
        """True when every (store key, has_destination) pair would be served
        by the whole-batch one-sided fast path — same coverage test as
        ``_fetch_all_one_sided``, callable before requests are built (the
        warm ``get_state_dict`` plan path uses it to skip even the
        epoch-validation RPC; the per-entry stamps self-validate)."""
        if not self._config.one_sided:
            return False
        from torchstore_tpu.transport.shared_memory import (
            ShmClientCache,
            covered_plan,
        )

        cache = self._ctx.peek(ShmClientCache)
        if cache is None or not cache.one_sided:
            return False
        return all(
            covered_plan(cache.one_sided, key, None, has_dest) is not None
            for key, has_dest in items
        )

    def _try_one_sided_device(self, key: str, spec) -> Optional[Any]:
        """Warm plain-spec (ShapeDtypeStruct) get: upload to device STRAIGHT
        from the borrowed stamped SHM view — jax reads the mapped segment
        bytes itself, so there is no intermediate host copy and no RPC.
        Returns the device array, or None (no plan / shape drift / torn
        upload) and the caller takes the normal fetch path."""
        if not self._config.one_sided:
            return None
        from torchstore_tpu.transport import device_transfer
        from torchstore_tpu.transport import shared_memory as shm_mod

        cache = self._ctx.peek(shm_mod.ShmClientCache)
        if cache is None:
            return None
        plan = cache.one_sided.get((key, None))
        if plan is None:
            return None
        if plan["nbytes"] > shm_mod.ONE_SIDED_COPY_MAX:
            # The upload runs synchronously on the event loop (device_put +
            # block_until_ready); past this size the stall starves every
            # concurrent op — stand down to the normal fetch path.
            return None
        if tuple(plan["meta"].shape) != tuple(spec.shape):
            return None
        try:
            view, recheck = shm_mod.stamped_read(cache, plan, borrow=True)
        except shm_mod.OneSidedMiss as miss:
            shm_mod.ONE_SIDED_FALLBACKS.inc(reason=miss.reason)
            cache.one_sided.pop((key, None), None)
            return None
        arr = device_transfer.upload_stamped(view, recheck, dtype=spec.dtype)
        if arr is None:
            shm_mod.ONE_SIDED_FALLBACKS.inc(reason="torn")
            return None
        return arr

    async def _raise_with_diagnosis(self, vid: str, exc: Exception) -> None:
        """A volume RPC failed or timed out: ask the controller to
        health-check the fleet and re-raise with the diagnosis attached
        (dead vs wedged vs healthy-but-slow is actionable for operators).
        The failed volume is remembered so retried gets prefer healthy
        replicas; volumes the health check clears are forgiven. The fleet
        fan-out runs at most once per 2 s window: retry loops under a
        correlated outage reuse the cached verdict instead of pinging
        every volume on every failed attempt."""
        import time as _time

        self._dead_volumes.add(vid)
        now = _time.monotonic()
        if now - self._diag_at < 2.0:
            cached = self._diag_statuses.get(vid)
            if cached is None or cached == "ok":
                # _dead_volumes means CONTROLLER-confirmed dead (it gates
                # the put demotion retry and replicated re-routing): a
                # failure the last fan-out didn't confirm stays retryable.
                self._dead_volumes.discard(vid)
            raise ActorDiedError(
                f"storage volume {vid!r} RPC failed: {exc} "
                f"[controller diagnosis (cached): "
                f"{cached or 'not in last health check'}]"
            ) from exc
        self._diag_at = now
        diagnosis = "controller unreachable"
        try:
            statuses = await self._controller.check_volumes.with_timeout(
                15.0
            ).call_one(timeout=5.0)
            diagnosis = statuses.get(vid, "unknown volume")
            self._diag_statuses = statuses
            self._dead_volumes = {
                v for v, status in statuses.items() if status != "ok"
            }
            if statuses.get(vid) == "ok":
                # Our RPC to vid failed but the controller reaches it: OUR
                # ref is stale (repair swapped in a replacement actor).
                # Drop cached refs/locations so the retry reconnects to
                # the fresh fleet instead of re-selecting a dead ref.
                diagnosis += " (ref was stale; volume map refreshed)"
                self._loc_cache.clear()
                self._refresh_epoch += 1
                try:
                    await self._load_volumes()
                except Exception:  # noqa: BLE001 - retry will re-attempt
                    pass
        except Exception:  # noqa: BLE001 - diagnosis is best-effort
            pass
        raise ActorDiedError(
            f"storage volume {vid!r} RPC failed: {exc} "
            f"[controller diagnosis: {diagnosis}]"
        ) from exc

    def _transports_support_inplace(self, located) -> tuple[bool, bool]:
        """(supports_inplace, requires_contiguous) across every transport that
        may participate — in-place views are attached only when all do
        (/root/reference/torchstore/client.py:255-314)."""
        supports = True
        contiguous = False
        for infos in located.values():
            for vid in infos:
                volume = self._volume_refs[vid]
                buffer = create_transport_buffer(volume, self._config)
                supports = supports and buffer.supports_inplace
                contiguous = contiguous or buffer.requires_contiguous_inplace
        return supports, contiguous

    def _build_volume_requests(
        self,
        req: Request,
        infos: dict[str, StorageInfo],
        inplace_ok: tuple[bool, bool],
        prefer_volume: Optional[str] = None,
    ) -> list[tuple[str, Request]]:
        supports_inplace, need_contig = inplace_ok
        any_info = next(iter(infos.values()))
        own_id = None
        try:
            own_id = self._strategy.get_client_id()
        except Exception:
            pass
        # Prefer healthy volumes first (replica failover), then the
        # caller's preferred replica (a relay-distributed local copy),
        # then this client's own volume, then stable order (locality) —
        # or, with replica_spread on, a per-(client, key) salted rotation
        # so split replicas of a hot key share the read load across
        # clients instead of all draining the same first choice.
        # Known-dead and supervisor-quarantined volumes stay as a last
        # resort: if they hold the only copy the fetch still tries them
        # and surfaces the real error.
        salt = self._spread_salt
        ordered = sorted(
            infos,
            key=lambda v: (
                v in self._dead_volumes or v in self._avoid_volumes,
                v != prefer_volume,
                v != own_id,
                zlib.crc32(f"{salt}|{req.key}|{v}".encode())
                if salt is not None
                else 0,
                v,
            ),
        )

        if any_info.object_type == ObjectType.OBJECT:
            sub = Request(key=req.key, is_object=True)
            return [(ordered[0], sub)]

        if any_info.object_type == ObjectType.TENSOR:
            wanted: Optional[TensorSlice] = req.tensor_slice
            sub = Request(
                key=req.key,
                tensor_slice=wanted,
                tensor_meta=any_info.tensor_meta,
            )
            if supports_inplace and req.tensor_val is not None:
                dest_box = Box(
                    (0,) * req.tensor_val.ndim, tuple(req.tensor_val.shape)
                )
                region = wanted.box if wanted is not None else dest_box
                sub.destination_view = get_destination_view(
                    req.tensor_val, dest_box, region, require_contiguous=need_contig
                )
            return [(ordered[0], sub)]

        # TENSOR_SLICE: intersect wanted region with every stored shard.
        stored_slices: list[tuple[str, TensorSlice]] = []
        for vid in ordered:
            for ts in infos[vid].tensor_slices.values():
                stored_slices.append((vid, ts))
        if req.tensor_slice is not None:
            wanted_box = req.tensor_slice.box
        else:
            wanted_box = shd.full_box(stored_slices[0][1].global_shape)
        dest = req.tensor_val
        dest_box = (
            req.tensor_slice.box
            if (dest is not None and req.tensor_slice is not None)
            else (
                Box((0,) * dest.ndim, tuple(dest.shape)) if dest is not None else None
            )
        )
        seen_boxes: set[Box] = set()
        subs: list[tuple[str, Request]] = []
        for vid, stored in stored_slices:
            inter = intersect_boxes(stored.box, wanted_box)
            if inter is None or inter in seen_boxes:
                # Replica dedup: identical regions from replicated shards are
                # fetched once (improves on the reference's noted-inefficient
                # redundant replicate fetch, /root/reference/torchstore/client.py:295-297).
                continue
            seen_boxes.add(inter)
            sub = Request(
                key=req.key,
                tensor_slice=stored.with_box(inter),
                tensor_meta=infos[vid].tensor_meta,
            )
            if supports_inplace and dest is not None and dest_box is not None:
                sub.destination_view = get_destination_view(
                    dest, dest_box, inter, require_contiguous=need_contig
                )
            subs.append((vid, sub))
        if not subs:
            raise KeyError(
                f"no stored shard of {req.key!r} overlaps requested region "
                f"{wanted_box}"
            )
        return subs

    def _assemble_result(
        self, req: Request, parts: list[tuple[Request, Any]]
    ) -> Any:
        if not parts:
            raise KeyError(f"fetch produced no data for key {req.key!r}")
        first_sub, first_res = parts[0]
        if first_sub.is_object:
            if isinstance(first_res, OpaqueBlob):
                return first_res.unwrap()
            return first_res  # pre-envelope durable entries read as-is
        dest = req.tensor_val
        arrays = [
            (np.asarray(res), sub.tensor_slice.offsets if sub.tensor_slice else None)
            for sub, res in parts
        ]
        if arrays[0][1] is None:
            # Whole-tensor fetch.
            out = arrays[0][0]
            if dest is not None:
                if out is not dest and not tensors_overlap_in_memory(dest, [out]):
                    # Native landing path; raises on shape mismatch instead
                    # of broadcasting (a stale-plan fetch must fail loudly).
                    copy_into(dest, out)
                return dest
            return out
        if dest is not None and tensors_overlap_in_memory(
            dest, [a for a, _ in arrays]
        ):
            return dest  # in-place fast path: everything already landed
        with span(
            "reshard",
            key=req.key,
            parts=len(arrays),
            nbytes=sum(a.nbytes for a, _ in arrays),
        ):
            out, offsets = assemble_tensor([(a, off) for a, off in arrays])
        if dest is not None:
            dest_box = (
                req.tensor_slice.box
                if req.tensor_slice is not None
                else Box((0,) * dest.ndim, tuple(dest.shape))
            )
            region = Box(offsets, tuple(out.shape))
            view = get_destination_view(
                dest, dest_box, region, require_contiguous=False
            )
            if view is None:
                raise ValueError(
                    f"fetched region {region} does not fit destination "
                    f"{dest_box} for key {req.key!r}"
                )
            copy_into(view, out)
            return dest
        return out

    # ------------------------------------------------------------------
    # delete / keys / exists
    # ------------------------------------------------------------------

    async def delete(self, key: str) -> None:
        await self.delete_batch([key])

    async def delete_batch(self, keys: list[str]) -> None:
        await self._ensure_setup()
        # Notify-before-delete ordering (invariant 1 delete path).
        by_volume = await self._controller.notify_delete_batch.call_one(keys)
        ordered = sorted(by_volume.items())
        results = await asyncio.gather(
            *(
                self._volume_refs[vid].actor.delete_batch.call_one(vkeys)
                for vid, vkeys in ordered
            ),
            return_exceptions=True,
        )
        for (vid, vkeys), result in zip(ordered, results):
            if isinstance(result, RETRYABLE_ERRORS):
                # The keys are already de-indexed (notify above), so a
                # dead/wedged volume only strands unreachable bytes — a
                # GC-during-failure must not kill the caller over them
                # (process exit reclaims memory-backed volumes; durable
                # backends reconcile on rebuild).
                logger.warning(
                    "delete of %d key(s) on unreachable volume %s skipped "
                    "(%s); bytes reclaimed when the volume exits/rebuilds",
                    len(vkeys),
                    vid,
                    result,
                )
            elif isinstance(result, BaseException):
                raise result
        for key in keys:
            self._ctx.delete_key(key)
            self._loc_cache.pop(key, None)

    async def delete_prefix(self, prefix: str) -> int:
        """Delete every key under a prefix (e.g. an old checkpoint version:
        ``delete_prefix("policy/v41")``). Returns the number of keys
        removed. Idempotent like delete_batch."""
        keys = await self._controller.keys.call_one(prefix)
        if keys:
            await self.delete_batch(keys)
        return len(keys)

    async def keys(self, prefix: Optional[str] = None) -> list[str]:
        return await self._controller.keys.call_one(prefix)

    async def exists(self, key: str) -> bool:
        return await self._controller.contains.call_one(key) != "missing"

    # ------------------------------------------------------------------
    # repair support
    # ------------------------------------------------------------------

    async def refresh_volumes(self) -> None:
        """Re-fetch the volume map (repair swapped in replacement actors);
        drops cached locations and dead-volume marks so retries see the
        fresh fleet."""
        self._loc_cache.clear()
        self._prefer_misses.clear()
        self._dead_volumes.clear()
        self._refresh_epoch += 1
        await self._load_volumes()

    async def replicate_to(self, volume_id: str, requests: list[Request]) -> None:
        """Targeted put: land ``requests`` on ONE specific volume and index
        them there (bypasses strategy placement — the re-replication path
        of ``ts.repair``)."""
        await self._ensure_setup()
        gens = await self._land_requests(self._volume_refs[volume_id], requests)
        await self._controller.notify_put_batch.call_one(
            [r.meta_only() for r in requests],
            volume_id,
            write_gens={volume_id: gens},
        )

    # ------------------------------------------------------------------
    # blocking waits
    # ------------------------------------------------------------------

    def _wait_rpc_timeout(self, timeout: Optional[float]) -> float:
        # The RPC deadline must outlive the server-side wait so the server's
        # precise TimeoutError (naming the missing keys) beats the generic
        # client-side one; 0 disables the client deadline for timeout=None.
        return 0 if timeout is None else timeout + 10.0

    async def wait_for(
        self, keys, timeout: Optional[float] = None
    ) -> None:
        """Block until every key exists and is fully committed. Replaces the
        reference pattern of polling get/get_state_dict in a try/except
        loop; raises TimeoutError on expiry."""
        if isinstance(keys, str):
            keys = [keys]
        await self._ensure_setup()
        await self._controller.wait_for_committed.with_timeout(
            self._wait_rpc_timeout(timeout)
        ).call_one(list(keys), timeout)

    async def wait_for_change(
        self, key: str, last_gen: int = 0, timeout: Optional[float] = None
    ) -> dict:
        """Block until ``key``'s update generation exceeds ``last_gen``;
        returns {"gen", "state"} (state: missing|partial|committed). The
        substrate for version subscriptions (see weight_channel)."""
        await self._ensure_setup()
        return await self._controller.wait_for_change.with_timeout(
            self._wait_rpc_timeout(timeout)
        ).call_one(key, last_gen, timeout)

    # ------------------------------------------------------------------
    # layer-streamed sync (see torchstore_tpu/stream_sync.py)
    # ------------------------------------------------------------------

    async def stream_begin(self, key: str, quant: Optional[dict] = None) -> int:
        """Open the next streamed publish of ``key``; returns the assigned
        stream version. ``quant`` registers static quantization meta on the
        record so readers can decode layer blobs before the seal."""
        await self._ensure_setup()
        return await self._controller.stream_begin.call_one(key, quant)

    async def stream_seal(self, key: str, version: int) -> None:
        await self._ensure_setup()
        await self._controller.stream_seal.call_one(key, version)

    async def stream_mark_unchanged(
        self, key: str, version: int, aliases: dict
    ) -> None:
        """Watermark unchanged keys of a streamed delta publish whose
        fragment landed no bytes (every key aliased to the previous
        version's committed bytes)."""
        await self._ensure_setup()
        await self._controller.stream_mark_unchanged.call_one(
            key, version, aliases
        )

    async def stream_state(self, key: str) -> Optional[dict]:
        """Snapshot of ``key``'s stream record, or None when never
        streamed. Always validate served keys through the blessed helpers
        in :mod:`torchstore_tpu.stream_sync` (tslint ``stream-discipline``)."""
        await self._ensure_setup()
        return await self._controller.stream_state.call_one(key)

    async def wait_for_stream(
        self,
        key: str,
        version: int,
        known: int = 0,
        timeout: Optional[float] = None,
        volume_id: Optional[str] = None,
    ) -> dict:
        """Long-poll streamed-publish progress (see
        Controller.wait_for_stream); the substrate for layer-by-layer
        acquires — woken by the notify that commits each layer, no spin.
        ``volume_id`` gates readiness on this subscriber's RELAY copy: keys
        report ready only once the broadcast tree landed them on that
        volume (ignored when the volume is not a live relay member).

        Both gate-less AND relay-gated polls serve from the stamped stream
        snapshot (same-host segment or this host's metadata mirror) with
        ZERO controller RPCs when attached: the controller publishes the
        relay-gate picture into the snapshot, so a gated poll applies the
        exact wait_for_stream formula against the local replica. The RPC
        long-poll stays the loud fallback (unattached, torn, stale, or
        mirror past its lag bound)."""
        await self._ensure_setup()
        served = await self._controller.stamped_wait_stream(
            key, version, known, timeout, volume_id=volume_id
        )
        if served is not None:
            return served
        return await self._controller.wait_for_stream.with_timeout(
            self._wait_rpc_timeout(timeout)
        ).call_one(key, version, known, timeout, volume_id)

    # ------------------------------------------------------------------
    # broadcast relay distribution (torchstore_tpu/relay.py)
    # ------------------------------------------------------------------

    async def relay_subscribe(
        self, channel: str, volume_id: Optional[str] = None
    ) -> dict:
        """Join ``channel``'s broadcast tree: the controller assigns (or
        adopts, via ``volume_id``) this host's relay volume — published
        versions flow to it volume-to-volume and local acquires read the
        one host-local copy. Returns ``{"volume_id", "epoch", "fanout"}``;
        ``{"volume_id": None, "disabled": True}`` when
        TORCHSTORE_TPU_RELAY_ENABLED is off."""
        await self._ensure_setup()
        if not self._config.relay_enabled:
            return {"volume_id": None, "disabled": True}
        from torchstore_tpu.observability.ledger import local_host

        return await self._controller.relay_subscribe.call_one(
            channel, local_host(), volume_id
        )

    async def relay_unsubscribe(self, channel: str, volume_id: str) -> dict:
        """Leave ``channel``'s broadcast tree (elastic membership: the last
        subscriber on a host removes its member and live runs re-parent
        around it). Idempotent."""
        await self._ensure_setup()
        return await self._controller.relay_unsubscribe.call_one(
            channel, volume_id
        )

    async def stream_ack(
        self, key: str, version: int, subscriber: str
    ) -> None:
        """Record this subscriber's acquire completion on the stream's
        generation timeline (telemetry for ``ts.sync_timeline``; advisory,
        bounded controller-side)."""
        await self._ensure_setup()
        await self._controller.stream_ack.call_one(key, version, subscriber)

    # ------------------------------------------------------------------
    # tiered capacity & multi-version serving (torchstore_tpu/tiering/)
    # ------------------------------------------------------------------

    async def lease_acquire(
        self,
        cohort: str,
        channel: str,
        version: int,
        ttl_s: Optional[float] = None,
    ) -> dict:
        """Pin (channel, version) for ``cohort`` against GC and spill
        (TTL'd; renew to keep it past the TTL). Returns the lease
        description — carry ``lease_id`` to renew/release."""
        await self._ensure_setup()
        return await self._controller.lease_acquire.call_one(
            cohort, channel, version, ttl_s
        )

    async def lease_renew(
        self, lease_id: str, ttl_s: Optional[float] = None
    ) -> dict:
        await self._ensure_setup()
        return await self._controller.lease_renew.call_one(lease_id, ttl_s)

    async def lease_release(self, lease_id: str) -> bool:
        await self._ensure_setup()
        return await self._controller.lease_release.call_one(lease_id)

    async def lease_list(
        self, channel: Optional[str] = None
    ) -> dict[str, dict[int, list[str]]]:
        """{channel: {version: [cohort, ...]}} over live leases."""
        await self._ensure_setup()
        return await self._controller.lease_list.call_one(channel)

    async def version_catalog(
        self, channel: Optional[str] = None
    ) -> dict[str, dict[int, dict]]:
        """Per-channel versions × tier × leases × bytes (see
        Controller.version_catalog)."""
        await self._ensure_setup()
        return await self._controller.version_catalog.call_one(channel)

    async def tier_sweep(self) -> dict[str, dict]:
        """Run one fleet spill pass now; returns per-volume summaries."""
        await self._ensure_setup()
        return await self._controller.tier_sweep.call_one()
