"""ctypes bindings for the native data-path library (native/libtsnative.so).

Fail-open: when the library is absent we attempt one `make` build (the
toolchain is part of the deployment image); if that fails, every helper
falls back to numpy — the store stays fully functional, just slower. Gated
by ``StoreConfig.use_native`` / TORCHSTORE_TPU_USE_NATIVE.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

from torchstore_tpu.config import default_config
from torchstore_tpu.logging import get_logger

logger = get_logger("torchstore_tpu.native")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libtsnative.so")

# Below this size the ctypes call overhead beats the threading win.
PARALLEL_THRESHOLD = 8 * 1024 * 1024

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False
# True once a v2+ library bound the threaded-prefault entry (v1 binaries
# carry an incompatible 2-arg ts_prefault that must never be called).
_has_prefault = False
# True once a v3+ library bound the batched scatter memcpy.
_has_copy_batch = False


def _try_build() -> bool:
    """Build the library once, under a cross-process file lock so N actor
    processes starting together don't race `make` (a loser could otherwise
    dlopen a half-written .so). Called from initialize()/volume startup, not
    from the transfer hot path."""
    makefile = os.path.join(_NATIVE_DIR, "Makefile")
    if not os.path.exists(makefile) or not os.access(_NATIVE_DIR, os.W_OK):
        return False
    import fcntl

    lock_path = os.path.join(_NATIVE_DIR, ".build.lock")
    try:
        with open(lock_path, "w") as lock_file:
            fcntl.flock(lock_file, fcntl.LOCK_EX)
            if os.path.exists(_LIB_PATH):  # another process built it
                return True
            subprocess.run(
                ["make", "-C", _NATIVE_DIR],
                check=True,
                capture_output=True,
                timeout=60,
            )
            return os.path.exists(_LIB_PATH)
    except Exception as exc:
        logger.warning("native build failed (falling back to numpy): %s", exc)
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    if not default_config().use_native:
        return None
    if not os.path.exists(_LIB_PATH) and not _try_build():
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        lib.ts_parallel_memcpy.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
        ]
        lib.ts_parallel_memcpy.restype = None
        lib.ts_copy_2d.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int,
        ]
        lib.ts_copy_2d.restype = None
        lib.ts_read_fd.argtypes = [ctypes.c_int, ctypes.c_void_p, ctypes.c_uint64]
        lib.ts_read_fd.restype = ctypes.c_int64
        lib.ts_write_fd.argtypes = [ctypes.c_int, ctypes.c_void_p, ctypes.c_uint64]
        lib.ts_write_fd.restype = ctypes.c_int64
        lib.ts_version.restype = ctypes.c_uint32
        version = lib.ts_version()
        assert version in (1, 2, 3), version
        if version >= 2:
            # v2: multi-threaded page prefault (the provisioning subsystem's
            # prewarm entry). v1 binaries carry an incompatible 2-arg
            # ts_prefault — never bind it there.
            lib.ts_prefault.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
            ]
            lib.ts_prefault.restype = ctypes.c_int
            global _has_prefault
            _has_prefault = True
        else:
            logger.info("native library is v1 (no threaded prefault)")
        if version >= 3:
            # v3: batched scatter memcpy (the one-sided warm get's landing
            # loop). v2 binaries fall back to the per-pair Python loop.
            lib.ts_copy_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_uint64, ctypes.c_int,
            ]
            lib.ts_copy_batch.restype = None
            global _has_copy_batch
            _has_copy_batch = True
        _lib = lib
        logger.info("native data path loaded (%s)", _LIB_PATH)
    except Exception as exc:
        logger.warning("native library unusable, using numpy fallback: %s", exc)
        _lib = None
    return _lib


def available() -> bool:
    return get_lib() is not None


def copy_batch_available() -> bool:
    """True when the v3 batched scatter memcpy is bound (callers build the
    pointer arrays only when the call can actually happen)."""
    return get_lib() is not None and _has_copy_batch


def _addr(arr: np.ndarray) -> int:
    return arr.__array_interface__["data"][0]


def fast_copy(dst: np.ndarray, src: np.ndarray) -> None:
    """np.copyto with a multi-threaded native path for large contiguous
    same-dtype copies (the store's hot memcpy). Shapes must match exactly:
    landing copies never broadcast — a silent broadcast would paper over a
    stale-metadata fetch (e.g. a location cache that missed a same-key
    shape change) with wrong data."""
    if dst.shape != src.shape:
        raise ValueError(
            f"landing-copy shape mismatch: dst {dst.shape} vs src {src.shape}"
        )
    lib = get_lib()
    if (
        lib is not None
        and dst.dtype == src.dtype
        and dst.shape == src.shape
        and dst.nbytes >= PARALLEL_THRESHOLD
        and dst.flags["C_CONTIGUOUS"]
        and src.flags["C_CONTIGUOUS"]
    ):
        lib.ts_parallel_memcpy(_addr(dst), _addr(src), dst.nbytes, 0)
        return
    np.copyto(dst, src)


def copy_into(dst: np.ndarray, src: np.ndarray) -> None:
    """Best copy path for a landing: contiguous native memcpy, then the
    native strided row-block path, then numpy. Never broadcasts (see
    fast_copy)."""
    if dst.shape != src.shape:
        raise ValueError(
            f"landing-copy shape mismatch: dst {dst.shape} vs src {src.shape}"
        )
    if (
        dst.flags["C_CONTIGUOUS"]
        and src.flags["C_CONTIGUOUS"]
        and dst.dtype == src.dtype
        and dst.shape == src.shape
    ):
        fast_copy(dst, src)
        return
    if fast_copy_2d(dst, src):
        return
    np.copyto(dst, src)


def copy_batch(
    dst_addrs: np.ndarray,
    src_addrs: np.ndarray,
    lens: np.ndarray,
    nthreads: int = 0,
) -> bool:
    """Batched scatter memcpy: one GIL-free native call lands ``len(lens)``
    independent (dst, src, len) copies, byte-balanced across threads. The
    caller OWNS eligibility: every pair must be same-size, both sides
    C-contiguous, and non-overlapping (the landing layer checks this).
    Arrays must be uint64 and C-contiguous. Returns False when the library
    is absent or pre-v3 — the caller runs its per-pair Python loop."""
    lib = get_lib()
    if lib is None or not _has_copy_batch:
        return False
    n = len(lens)
    if n == 0:
        return True
    lib.ts_copy_batch(
        dst_addrs.ctypes.data, src_addrs.ctypes.data, lens.ctypes.data,
        n, nthreads,
    )
    return True


def prefault(addr: int, length: int, nthreads: int = 0) -> bool:
    """Multi-threaded prefault of ``length`` bytes at ``addr`` (one write per
    page, spread over ``nthreads``; 0 = auto). Returns True when the native
    path ran; False means the caller must fall back to touching pages itself
    (v1 library or numpy-only build). Used by the provisioning subsystem to
    pre-allocate tmpfs segment pages off the first-sync critical path."""
    lib = get_lib()
    if lib is None or not _has_prefault:
        return False
    if length <= 0:
        return True
    lib.ts_prefault(addr, length, nthreads)
    return True


def fast_copy_2d(dst: np.ndarray, src: np.ndarray) -> bool:
    """Row-block strided copy (2D, same row length, contiguous rows).
    Returns False when the pattern doesn't apply (caller uses numpy)."""
    lib = get_lib()
    if (
        lib is None
        or dst.ndim != 2
        or src.shape != dst.shape
        or dst.dtype != src.dtype
        or dst.strides[1] != dst.itemsize
        or src.strides[1] != src.itemsize
        or dst.nbytes < PARALLEL_THRESHOLD
    ):
        return False
    lib.ts_copy_2d(
        _addr(dst), dst.strides[0], _addr(src), src.strides[0],
        dst.shape[1] * dst.itemsize, dst.shape[0], 0,
    )
    return True
