"""jax.Array / NamedSharding <-> TensorSlice bridge.

This replaces the reference's DTensor integration
(/root/reference/torchstore/transport/types.py:58-196, which leans on
``_compute_local_shape_and_global_offset``): here shard placement comes from
``jax.sharding.NamedSharding`` — each addressable shard's ``.index`` gives its
(offsets, local_shape) and the mesh position of its device gives the commit
coordinates. jax is imported lazily so storage volumes / host-only processes
never pay for it.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from torchstore_tpu.transport.types import Request, TensorSlice
from torchstore_tpu.utils import Box


def is_jax_array(value: Any) -> bool:
    try:
        import jax
    except ImportError:
        return False
    return isinstance(value, jax.Array)


def is_sharded_spec(value: Any) -> bool:
    """A jax.ShapeDtypeStruct carrying a sharding: a fetch target that needs
    no prefilled array (orbax-style restore targets)."""
    try:
        import jax
    except ImportError:
        return False
    return (
        isinstance(value, jax.ShapeDtypeStruct)
        and getattr(value, "sharding", None) is not None
    )


def is_plain_spec(value: Any) -> bool:
    """A jax.ShapeDtypeStruct WITHOUT a sharding: fetch target producing a
    default-placed device array of the spec's shape/dtype."""
    try:
        import jax
    except ImportError:
        return False
    return (
        isinstance(value, jax.ShapeDtypeStruct)
        and getattr(value, "sharding", None) is None
    )


def _mesh_coords_map(mesh) -> dict:
    """device -> coordinates in the mesh array."""
    coords = {}
    for idx, dev in np.ndenumerate(mesh.devices):
        coords[dev] = tuple(int(i) for i in idx)
    return coords


def _is_demotable(sharding) -> bool:
    """Fully-replicated / single-device arrays are stored as plain tensors —
    the reference's fully-local DTensor demotion (MoE/EP use case, invariant
    7; /root/reference/torchstore/transport/types.py:58-85)."""
    import jax

    if not isinstance(sharding, jax.sharding.NamedSharding):
        return True
    if sharding.mesh.devices.size == 1:
        return True
    return sharding.is_fully_replicated


def put_requests(key: str, x) -> list[Request]:
    """Expand a jax.Array into per-addressable-shard put requests.

    One process may own several devices (a TPU host owns 4-8 chips), so a
    single put covers all addressable shards — the multi-controller analog of
    the reference's one-shard-per-rank DTensor put. Device->host staging is
    OVERLAPPED: every shard's async D2H copy is issued before the first is
    awaited, so transfers from different chips ride their DMA engines
    concurrently (the reference overlaps CUDA side-stream copies the same
    way, /root/reference/torchstore/transport/shared_memory.py:362-420)."""
    import jax  # noqa: F401

    sharding = x.sharding
    if _is_demotable(sharding):
        _start_d2h(x)
        return [Request.from_tensor(key, np.asarray(x))]
    mesh = sharding.mesh
    mesh_shape = tuple(int(s) for s in mesh.devices.shape)
    coords_map = _mesh_coords_map(mesh)
    global_shape = tuple(int(s) for s in x.shape)
    shards = list(x.addressable_shards)
    for shard in shards:
        _start_d2h(shard.data)
    requests = []
    for shard in shards:
        data = np.asarray(shard.data)
        offsets = tuple(int(sl.start or 0) for sl in shard.index)
        ts = TensorSlice(
            offsets=offsets,
            local_shape=tuple(int(s) for s in data.shape),
            global_shape=global_shape,
            coordinates=coords_map[shard.device],
            mesh_shape=mesh_shape,
        )
        requests.append(Request.from_tensor_slice(key, ts, data))
    return requests


def _start_d2h(arr) -> None:
    """Kick off the async device->host copy for ``arr`` (no-op when the
    runtime lacks it); a later np.asarray then finds the bytes already in
    flight or landed."""
    start = getattr(arr, "copy_to_host_async", None)
    if start is not None:
        try:
            start()
        except Exception:  # pragma: no cover - backend without async D2H
            pass


def target_slices(like) -> list[tuple[Any, TensorSlice]]:
    """(device, TensorSlice) for every addressable shard a resharding get
    must produce to rebuild ``like``'s sharding locally."""
    import jax

    sharding = like.sharding
    global_shape = tuple(int(s) for s in like.shape)
    if _is_demotable(sharding):
        dev = next(iter(sharding.device_set))
        full = TensorSlice(
            offsets=(0,) * len(global_shape),
            local_shape=global_shape,
            global_shape=global_shape,
            coordinates=(),
            mesh_shape=(),
        )
        return [(dev, full)]
    mesh = sharding.mesh
    mesh_shape = tuple(int(s) for s in mesh.devices.shape)
    coords_map = _mesh_coords_map(mesh)
    out = []
    index_map = sharding.addressable_devices_indices_map(global_shape)
    for dev, index in index_map.items():
        offsets = tuple(int(sl.start or 0) for sl in index)
        local_shape = tuple(
            int((sl.stop if sl.stop is not None else dim) - (sl.start or 0))
            for sl, dim in zip(index, global_shape)
        )
        ts = TensorSlice(
            offsets=offsets,
            local_shape=local_shape,
            global_shape=global_shape,
            coordinates=coords_map[dev],
            mesh_shape=mesh_shape,
        )
        out.append((dev, ts))
    return out


def build_array(like, parts: list[tuple[Any, np.ndarray]]):
    """Assemble a jax.Array with ``like``'s sharding from fetched host parts
    [(device, local_array)] — the functional analog of the reference's
    in-place DTensor update (jax arrays are immutable, so a reshard-get
    returns a new array; TPU-first semantics)."""
    import jax

    sharding = like.sharding
    if _is_demotable(sharding):
        # target_slices produced a single full-array part; replicate it onto
        # every addressable device of the target sharding.
        ((_, arr),) = parts
        arrays = [jax.device_put(arr, d) for d in sharding.addressable_devices]
    else:
        arrays = [jax.device_put(arr, dev) for dev, arr in parts]
    return jax.make_array_from_single_device_arrays(
        tuple(int(s) for s in like.shape), sharding, arrays
    )


def full_box(global_shape: tuple[int, ...]) -> Box:
    return Box((0,) * len(global_shape), tuple(global_shape))


def plan_signature(value: Any):
    """Hashable transfer-plan signature component for a jax leaf, or None
    for non-jax values. Includes the SHARDING, not just shape/dtype: two
    pushes of the same global shape under different meshes decompose into
    different request sets, so a cached plan keyed without the sharding
    would replay the wrong fan-out (the iteration-stable plan cache keys on
    this, client.SyncPlanCache)."""
    if is_jax_array(value) or is_sharded_spec(value):
        return (
            "jax",
            tuple(int(s) for s in value.shape),
            str(value.dtype),
            value.sharding,  # NamedSharding et al. are hashable
        )
    if is_plain_spec(value):
        return ("spec", tuple(int(s) for s in value.shape), str(value.dtype))
    return None
