"""Mesh + sharding helpers: logical-axis rules, param sharding, train step.

This is where the framework's multi-chip story lives (SURVEY §2.4: any
sharding expressible as per-device slices over an N-D mesh can be stored and
re-fetched under any other). Models annotate params with logical axes
(``vocab``/``embed``/``heads``/``mlp``/``expert``); these rules map them onto
mesh axes (dp/fsdp/tp/ep) and XLA inserts the collectives — the jax-native
replacement for the reference's NCCL/process-group machinery.
"""

from __future__ import annotations

import numpy as np


def make_mesh(shape: dict[str, int], devices=None):
    """Mesh from {axis: size}, e.g. {"dp": 2, "tp": 4}."""
    import jax
    from jax.sharding import Mesh

    sizes = tuple(shape.values())
    if devices is None:
        devices = jax.devices()[: int(np.prod(sizes))]
    return Mesh(np.array(devices).reshape(sizes), tuple(shape.keys()))


# Logical-axis -> mesh-axis rules (MaxText-style). First matching mesh axis
# present in the mesh wins; unmatched axes replicate.
DEFAULT_RULES = (
    ("vocab", ("tp",)),
    ("embed", ("fsdp",)),
    ("heads", ("tp",)),
    ("kv_heads", ("tp",)),
    ("mlp", ("tp",)),
    ("expert", ("ep", "tp")),
    ("batch", ("dp", "fsdp")),
    ("seq", ("sp",)),
)


def logical_to_mesh_axes(logical_axes, mesh, rules=DEFAULT_RULES):
    from jax.sharding import PartitionSpec

    if logical_axes is None:
        return PartitionSpec()
    out = []
    used = set()
    for axis in logical_axes:
        resolved = None
        for name, candidates in rules:
            if axis == name:
                for cand in candidates:
                    if cand in mesh.axis_names and cand not in used:
                        resolved = cand
                        break
                break
        if resolved is not None:
            used.add(resolved)
        out.append(resolved)
    return PartitionSpec(*out)


def shard_params(params, mesh, rules=DEFAULT_RULES):
    """Apply logical-axis metadata (flax ``nn.with_logical_partitioning``) to
    place a param pytree on the mesh; params without metadata replicate."""
    import jax
    from flax.core import meta
    from jax.sharding import NamedSharding, PartitionSpec

    def place(leaf):
        if isinstance(leaf, meta.Partitioned):
            spec = logical_to_mesh_axes(leaf.names, mesh, rules)
            value = leaf.value
        else:
            spec = PartitionSpec()
            value = leaf
        return jax.device_put(value, NamedSharding(mesh, spec))

    return jax.tree.map(
        place, params, is_leaf=lambda x: isinstance(x, meta.Partitioned)
    )


def unbox(params):
    """Strip flax Partitioned metadata boxes, keeping raw arrays."""
    from flax.core import meta

    return meta.unbox(params)


def activation_rules(mesh, rules=DEFAULT_RULES):
    """flax ``logical_axis_rules`` context manager resolving our logical
    axes against ``mesh`` — activates the model's activation sharding
    constraints (batch->dp/fsdp, seq->sp for sequence parallelism)."""
    import flax.linen as nn

    # Different logical axes may share one mesh axis (they live on different
    # tensors); per-tensor axis-uniqueness is handled in logical_to_mesh_axes.
    resolved = [
        (name, next((c for c in candidates if c in mesh.axis_names), None))
        for name, candidates in rules
    ]
    return nn.logical_axis_rules(resolved)


def reshard(x, sharding):
    """In-process resharding over ICI: when source and destination live in
    the same jax runtime (one process, or SPMD multi-controller where every
    participant calls this), ``device_put`` compiles to direct device-to-
    device transfers / XLA collectives over ICI — no host round trip.

    This is the TPU answer to the reference's device-side RDMA rung
    (SURVEY §2.3 monarch.rdma): between *separate* actor groups with
    separate runtimes the store's SHM/bulk transports carry the bytes, but
    whenever the caller's own mesh holds both layouts this path wins by an
    order of magnitude."""
    import jax

    return jax.device_put(x, sharding)


def make_train_step(model, optimizer):
    """A jittable causal-LM train step (loss = next-token cross-entropy).
    Sharding propagates from the input shardings (params/opt_state/tokens
    placed via ``shard_params`` / device_put); params and optimizer state are
    donated so updates happen in place on device."""
    import jax
    import optax

    def loss_fn(params, tokens):
        logits = model.apply(params, tokens[:, :-1])
        targets = tokens[:, 1:]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets
        ).mean()

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(train_step, donate_argnums=(0, 1))
