"""Shared landing-copy pool: overlapped segment copies + arena layout math.

The steady-state put/get hot path used to run one ``fast_copy`` per request,
serially, on the event loop thread — every copy blocked the loop, so a batch
of landings could overlap neither each other nor the RPC/D2H work the loop
still had in flight. This module provides the shared, bounded executor all
landing sites fan out to:

- **put side**: ``SharedMemoryTransportBuffer._post_handshake`` lands every
  request's client->segment copy through ``land_async``;
- **get side**: in-place destination copies in the SHM response handler;
- **volume side**: arena member indexing / inline landings.

The pool is budgeted against cores (``TORCHSTORE_TPU_LANDING_THREADS``,
0 = one per core capped at 4): ``fast_copy`` is already internally threaded
for large contiguous arrays, so stacking a wide pool on top of it would
oversubscribe the host. Very large tensors are additionally CHUNKED into
row blocks, so a single tensor's landing pipelines across pool threads and
yields the event loop between chunks instead of occupying one thread (and,
pre-pool, the loop) for the whole copy.

Arena layout (``compute_arena_layout``) lives here too so the SHM
transport, the bulk packed frame, and the provisioning manifest all pack
small keys identically — a prewarm-provisioned arena segment is exactly the
size the first put's handshake asks for.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

import numpy as np

from torchstore_tpu.config import StoreConfig, default_config
from torchstore_tpu.native import copy_into
from torchstore_tpu.observability import metrics as obs_metrics

# Chunk size for pipelining one very large tensor's landing: big enough that
# per-chunk submission overhead is invisible, small enough that a 1 GB
# tensor becomes ~32 overlappable units.
CHUNK_BYTES = 32 << 20

# Arena members are aligned so every packed tensor starts on a cache-line
# boundary (also satisfies any dtype's alignment).
ARENA_ALIGN = 64

_LANDING_SECONDS = obs_metrics.histogram(
    "ts_landing_copy_seconds",
    "Wall time of one overlapped landing-copy batch, by pipeline stage",
)
_PIPELINE_COPIES = obs_metrics.counter(
    "ts_sync_pipeline_copies_total",
    "Landing copies routed through the overlap pool, by stage",
)
_PIPELINE_BYTES = obs_metrics.counter(
    "ts_sync_pipeline_bytes_total",
    "Bytes landed through the overlap pool, by stage",
)
_PIPELINE_CHUNKS = obs_metrics.counter(
    "ts_sync_pipeline_chunks_total",
    "Row-block chunks large tensors were split into for pipelined landing",
)
ARENA_KEYS = obs_metrics.counter(
    "ts_arena_packed_keys_total",
    "Small tensors packed into a shared arena, by transport",
)
ARENA_BYTES = obs_metrics.counter(
    "ts_arena_bytes_total",
    "Payload bytes carried inside packed arenas, by transport",
)

_exec: Optional[ThreadPoolExecutor] = None
_exec_threads = 0
_exec_lock = threading.Lock()


def configured_threads(config: Optional[StoreConfig] = None) -> int:
    n = (config or default_config()).landing_threads
    if n > 0:
        return n
    return max(1, min(4, os.cpu_count() or 1))


def get_executor(config: Optional[StoreConfig] = None) -> ThreadPoolExecutor:
    """The process-wide landing pool (created lazily; resized only if a
    config asks for MORE threads than the pool was built with)."""
    global _exec, _exec_threads
    want = configured_threads(config)
    with _exec_lock:
        if _exec is None or want > _exec_threads:
            old = _exec
            _exec = ThreadPoolExecutor(
                max_workers=want, thread_name_prefix="ts-landing"
            )
            _exec_threads = want
            if old is not None:
                old.shutdown(wait=False)
        return _exec


def reinit_after_fork() -> None:
    """Forked children inherit a dead pool object (executor threads do not
    survive fork); drop it so the first landing re-creates a live one."""
    global _exec, _exec_threads
    _exec = None
    _exec_threads = 0


def _chunk_pairs(dst: np.ndarray, src: np.ndarray) -> list[tuple]:
    """Split one large contiguous same-dtype copy into row-block chunks so
    it pipelines across pool threads. Non-chunkable shapes return the pair
    unsplit."""
    if (
        dst.nbytes <= CHUNK_BYTES
        or dst.dtype != src.dtype
        or not dst.flags["C_CONTIGUOUS"]
        or not src.flags["C_CONTIGUOUS"]
    ):
        return [(dst, src)]
    flat_d = dst.reshape(-1)
    flat_s = src.reshape(-1)
    step = max(1, CHUNK_BYTES // max(1, dst.itemsize))
    chunks = [
        (flat_d[off : off + step], flat_s[off : off + step])
        for off in range(0, flat_d.shape[0], step)
    ]
    _PIPELINE_CHUNKS.inc(len(chunks))
    return chunks


def _copy_group(group: list[tuple], copy: Callable) -> None:
    for dst, src in group:
        copy(dst, src)


def _plan_tasks(
    pairs: list[tuple[np.ndarray, np.ndarray]],
    threads: int,
    copy: Callable,
) -> list[tuple[Callable, list[tuple]]]:
    """Partition a landing batch into at most ~2x``threads`` executor tasks:
    very large pairs are chunked into row blocks (one task each — a single
    huge tensor pipelines across threads), everything else is grouped into
    byte-balanced runs so a 2048-small-key batch costs a handful of
    submissions, not 2048 (per-future overhead on a 2-core host exceeds a
    64 KB memcpy by an order of magnitude)."""
    tasks: list[tuple[Callable, list[tuple]]] = []
    small: list[tuple] = []
    small_bytes = 0
    for dst, src in pairs:
        if dst.nbytes > CHUNK_BYTES:
            for cd, cs in _chunk_pairs(dst, src):
                tasks.append((copy, [(cd, cs)]))
        else:
            small.append((dst, src))
            small_bytes += dst.nbytes
    if small:
        target = max(1, -(-small_bytes // max(1, threads)))
        group: list[tuple] = []
        acc = 0
        for pair in small:
            group.append(pair)
            acc += pair[0].nbytes
            if acc >= target:
                tasks.append((copy, group))
                group, acc = [], 0
        if group:
            tasks.append((copy, group))
    return tasks


async def land_async(
    pairs: list[tuple[np.ndarray, np.ndarray]],
    stage: str,
    copy: Callable[[np.ndarray, np.ndarray], None] = copy_into,
    config: Optional[StoreConfig] = None,
) -> None:
    """Land every (dst, src) pair through the shared pool, concurrently,
    without blocking the event loop. Pairs above CHUNK_BYTES are split so a
    single huge tensor pipelines too; small pairs are grouped so per-future
    overhead stays amortized. Exceptions (shape mismatches — the fast_copy
    no-broadcast rule) propagate to the caller."""
    import asyncio

    pairs = [(d, s) for d, s in pairs if d.nbytes]
    if not pairs:
        return
    t0 = time.perf_counter()
    nbytes = sum(d.nbytes for d, _ in pairs)
    _PIPELINE_COPIES.inc(len(pairs), stage=stage)
    _PIPELINE_BYTES.inc(nbytes, stage=stage)
    threads = configured_threads(config)
    tasks = _plan_tasks(pairs, threads, copy)
    if len(tasks) == 1 and nbytes <= (256 << 10):
        # One small batch: the submission round trip costs more than it
        # could overlap; run it inline.
        _copy_group(tasks[0][1], copy)
        _LANDING_SECONDS.observe(time.perf_counter() - t0, stage=stage)
        return
    loop = asyncio.get_running_loop()
    pool = get_executor(config)
    await asyncio.gather(
        *(
            loop.run_in_executor(pool, _copy_group, group, fn)
            for fn, group in tasks
        )
    )
    _LANDING_SECONDS.observe(time.perf_counter() - t0, stage=stage)


async def land_batch_async(
    dst_addrs: list[int],
    src_addrs: list[int],
    lens: list[int],
    stage: str,
    config: Optional[StoreConfig] = None,
) -> bool:
    """Single-submission scatter landing: ONE executor hop runs the native
    v3 ``ts_copy_batch`` (GIL-free, internally threaded) over every
    (dst, src, len) triple. This is the one-sided warm get's copy stage —
    the grouped ``land_async`` path pays a pool submission per group plus
    per-pair interpreter/GIL hand-off, which measured ~2x the raw copy
    time for many-small-key batches on a 2-vCPU host. The CALLER owns
    eligibility (same-size, both sides C-contiguous, non-overlapping
    pairs). Returns False (nothing copied) when the native entry is
    unavailable — the caller falls back to :func:`land_async`."""
    import asyncio

    from torchstore_tpu import native

    if not native.copy_batch_available():
        return False
    if not lens:
        return True
    t0 = time.perf_counter()
    da = np.array(dst_addrs, dtype=np.uint64)
    sa = np.array(src_addrs, dtype=np.uint64)
    ln = np.array(lens, dtype=np.uint64)
    total = int(ln.sum())
    _PIPELINE_COPIES.inc(len(lens), stage=stage)
    _PIPELINE_BYTES.inc(total, stage=stage)
    threads = configured_threads(config)
    if total <= (256 << 10):
        # Small batch: the executor round trip costs more than the copy.
        ok = native.copy_batch(da, sa, ln, threads)
    else:
        loop = asyncio.get_running_loop()
        ok = await loop.run_in_executor(
            get_executor(config), native.copy_batch, da, sa, ln, threads
        )
    if ok:
        _LANDING_SECONDS.observe(time.perf_counter() - t0, stage=stage)
    return ok


def land_sync(
    pairs: list[tuple[np.ndarray, np.ndarray]],
    stage: str,
    copy: Callable[[np.ndarray, np.ndarray], None] = copy_into,
    config: Optional[StoreConfig] = None,
) -> None:
    """Blocking variant for sync contexts (no running loop): still spreads
    the pairs across the pool so copies overlap each other."""
    pairs = [(d, s) for d, s in pairs if d.nbytes]
    if not pairs:
        return
    t0 = time.perf_counter()
    _PIPELINE_COPIES.inc(len(pairs), stage=stage)
    _PIPELINE_BYTES.inc(sum(d.nbytes for d, _ in pairs), stage=stage)
    threads = configured_threads(config)
    tasks = _plan_tasks(pairs, threads, copy)
    if len(tasks) == 1:
        _copy_group(tasks[0][1], copy)
    else:
        pool = get_executor(config)
        list(pool.map(lambda t: _copy_group(t[1], t[0]), tasks))
    _LANDING_SECONDS.observe(time.perf_counter() - t0, stage=stage)


async def run_in_pool(fn: Callable, *args, config: Optional[StoreConfig] = None):
    """Run one CPU-bound callable on the landing pool with the caller's
    contextvars (so spans/trace ids opened inside still stitch to the
    active trace)."""
    import asyncio

    ctx = contextvars.copy_context()
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(
        get_executor(config), lambda: ctx.run(fn, *args)
    )


def align_up(n: int, align: int = ARENA_ALIGN) -> int:
    return (n + align - 1) // align * align


# Scale tables are f32: the slot fused after each payload only needs 4-byte
# alignment (64B between MEMBERS stays — the payload start dominates cache
# behavior; padding a 4-byte-aligned scale run to 64B would waste more than
# the whole table for small tensors).
SCALE_ALIGN = 4


def compute_arena_layout(
    sizes: list[int], scale_sizes: Optional[list[int]] = None
):
    """Offsets + total for packing ``sizes`` byte payloads back-to-back at
    ARENA_ALIGN boundaries. THE arena layout function: the SHM transport,
    the bulk packed frame, and the provisioning manifest all call this, so
    a prewarmed pool segment is exactly the size the first put asks for.

    ``scale_sizes`` (quantized wire tier) fuses a per-member SCALE SLOT
    into the SAME layout: member ``i``'s slot holds its payload at
    ``offsets[i]`` and its f32 scale table at ``scale_offsets[i]``
    (4-byte-aligned immediately after the payload) — one segment, one
    handshake, and the scales can never ride a separate RPC from the
    bytes they decode. Returns ``(offsets, scale_offsets, total)`` in
    that mode, ``(offsets, total)`` classically."""
    offsets: list[int] = []
    scale_offsets: list[int] = []
    off = 0
    for i, nbytes in enumerate(sizes):
        offsets.append(off)
        end = off + int(nbytes)
        if scale_sizes is not None:
            s_off = align_up(end, SCALE_ALIGN)
            scale_offsets.append(s_off)
            end = s_off + int(scale_sizes[i])
        off = align_up(end)
    total = max(off, 1)
    if scale_sizes is not None:
        return offsets, scale_offsets, total
    return offsets, total


# ---------------------------------------------------------------------------
# fused quant-blob layout (blockwise int8/int4 wire tier)
# ---------------------------------------------------------------------------
#
# A blockwise-quantized tensor crosses the wire as ONE self-describing
# uint8 blob: [header+shape | changed-block bitmap | packed codes | f32
# scale table]. The scale slot rides compute_arena_layout's scale_sizes
# mode, so payload and scales share a segment by construction — the
# transport, the bulk packed frame, and the provisioning manifest all see
# a single ordinary byte payload. Layout math lives HERE (the arena
# layout module); encode/decode live in state_dict_utils (the only other
# module allowed to touch scale tables, per the tslint quant-discipline
# rule).

QUANT_HEADER_BYTES = 64


def quant_payload_nbytes(fmt: str, block: int, changed: int) -> int:
    """Packed-code bytes for ``changed`` blocks of ``block`` elements:
    int8_block stores one byte per element; int4_block packs two 4-bit
    codes per byte (blocks are whole slots — the tail block zero-pads)."""
    if fmt == "int4_block":
        return changed * ((block + 1) // 2)
    return changed * block


def quant_blob_layout(
    rank: int, nblocks: int, changed: int, fmt: str, block: int
) -> dict:
    """Section offsets + total size of one fused quant blob. The payload/
    scale pair goes through compute_arena_layout's scale-slot mode, so the
    scale table provably occupies the same segment as the codes it
    decodes."""
    head = QUANT_HEADER_BYTES + 8 * rank
    bitmap = (nblocks + 7) // 8
    offsets, scale_offsets, total = compute_arena_layout(
        [head, bitmap, quant_payload_nbytes(fmt, block, changed)],
        scale_sizes=[0, 0, 4 * changed],
    )
    return {
        "header": offsets[0],
        "bitmap": offsets[1],
        "payload": offsets[2],
        "scales": scale_offsets[2],
        "total": total,
    }


def quant_wire_nbytes(fmt: str, block: int, nelems: int, rank: int) -> int:
    """Full-keyframe wire size of an ``nelems``-element tensor under
    blockwise quantization — what the provisioning manifest sizes pools
    with, so a prewarmed pool holds the scale-bearing arena segment the
    first quantized publish asks for."""
    nblocks = max(1, -(-int(nelems) // max(1, block)))
    return quant_blob_layout(rank, nblocks, nblocks, fmt, block)["total"]
