"""Transport buffer contract + client-side transport caches.

TPU-native equivalent of /root/reference/torchstore/transport/buffers.py:20-361.
The same five-phase lifecycle makes transports pluggable and independently
testable (SURVEY §5 "distributed communication backend"):

    client                                server (storage volume)
    ------                                -----------------------
    perform_handshake ──RPC──────────────▶ recv_handshake
    _pre_put_hook / _pre_get_hook
    volume.put/get(buffer, metas) ──RPC──▶ handle_put_request /
                                           handle_get_request
    _handle_storage_volume_response ◀─────(buffer rides the response)
    _post_request_success; drop() in finally

The buffer object itself is serialized into the RPC both ways; client-only
references (live arrays, caches) are stripped in ``__getstate__`` by each
implementation.
"""

from __future__ import annotations

import time
from abc import ABC, abstractmethod
from typing import TYPE_CHECKING, Any, Optional

import numpy as np

from torchstore_tpu.logging import get_logger
from torchstore_tpu.observability import ledger as obs_ledger
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import recorder as obs_recorder
from torchstore_tpu.observability import timeline as obs_timeline
from torchstore_tpu.observability import tracing
from torchstore_tpu.transport.types import Request
from torchstore_tpu.utils import maybe_await

if TYPE_CHECKING:
    from torchstore_tpu.strategy import StorageVolumeRef

logger = get_logger("torchstore_tpu.transport")

# Per-transport data-plane instruments (client side — where the bytes are
# handed to / received from the wire). Labeled by transport rung + op so one
# snapshot answers "where did the bytes go".
_OPS = obs_metrics.counter(
    "ts_transport_ops_total", "Data-plane transfers by transport and op"
)
_BYTES = obs_metrics.counter(
    "ts_transport_bytes_total",
    "Logical payload bytes handed to / received from each transport",
)
_ERRORS = obs_metrics.counter(
    "ts_transport_errors_total", "Failed transfers by transport and op"
)
_OP_SECONDS = obs_metrics.histogram(
    "ts_transport_op_seconds", "Wall time of one transfer by transport and op"
)

# Data-plane RPCs carry (or wait on) tensor bytes: their deadline must scale
# with payload size or a transfer slower than config.rpc_timeout spuriously
# fails. 50 MB/s is a conservative DCN floor.
MIN_TRANSFER_RATE_BPS = 50e6


def transfer_timeout(base: Optional[float], nbytes: int) -> Optional[float]:
    if base is None or base <= 0:
        return base  # timeouts disabled
    return base + nbytes / MIN_TRANSFER_RATE_BPS


class TransportCache:
    """Base class for per-volume client-side caches (connections, segments,
    registrations). Reference: /root/reference/torchstore/transport/buffers.py:20-38."""

    def delete_key(self, key: str) -> None:  # noqa: B027 - optional hook
        pass

    def clear(self) -> None:  # noqa: B027 - optional hook
        pass


class TransportContext:
    """Type-keyed lazy registry of ``TransportCache`` instances, one per
    client (and one per storage volume server side). Reference:
    /root/reference/torchstore/transport/buffers.py:39-69."""

    def __init__(self) -> None:
        self._caches: dict[type, TransportCache] = {}

    def get_cache(self, cache_cls: type, *args, **kwargs) -> Any:
        cache = self._caches.get(cache_cls)
        if cache is None:
            cache = cache_cls(*args, **kwargs)
            self._caches[cache_cls] = cache
        return cache

    def peek(self, cache_cls: type) -> Any:
        """The cache of this type if one was ever created, else None (stats
        paths must not instantiate caches as a side effect)."""
        return self._caches.get(cache_cls)

    def delete_key(self, key: str) -> None:
        for cache in self._caches.values():
            cache.delete_key(key)

    def clear(self) -> None:
        for cache in self._caches.values():
            cache.clear()
        self._caches.clear()


class TransportBuffer(ABC):
    """One instance per request batch; orchestrates the transfer lifecycle.

    Subclasses implement the hooks; this base drives ordering, error
    propagation and guaranteed resource release (``drop()`` runs in a
    ``finally`` for both success and failure — reference invariant,
    /root/reference/torchstore/transport/buffers.py:196-257).
    """

    requires_handshake: bool = False
    # Rung label for metrics/spans ("shm" | "bulk" | "rpc" | ...).
    transport_name: str = "unknown"
    # Which ops actually need the handshake RPC; transports whose gets are
    # self-describing (SHM descriptors ride the get response) skip the extra
    # round trip by narrowing this to ("put",).
    handshake_ops: tuple = ("put", "get")
    supports_inplace: bool = True
    requires_contiguous_inplace: bool = False
    supports_batch_puts: bool = True
    supports_batch_gets: bool = True
    # Per-key write generations the volume assigned to the last put this
    # buffer carried (set by put_to_storage_volume; forwarded by the client
    # to the controller so stale-replica reclaims can delete conditionally).
    write_gens: "Optional[dict[str, int]]" = None
    # Optional transfer-plan hint from the iteration-stable plan cache
    # (client.put_batch plumbs it): e.g. a precomputed arena layout the
    # transport may adopt instead of recomputing. Transports MUST validate
    # the hint against the actual requests before trusting it.
    plan_hint: "Optional[dict]" = None

    # ---- client-side lifecycle ------------------------------------------

    async def put_to_storage_volume(
        self, volume: "StorageVolumeRef", requests: list[Request]
    ) -> None:
        for req in requests:
            if not req.is_object and req.tensor_val is None:
                raise ValueError(
                    f"put of key {req.key!r} carries no tensor data "
                    "(Shard.data must not be None on puts)"
                )
        nbytes = sum(r.nbytes for r in requests)
        t0 = time.perf_counter()
        try:
            with tracing.span(
                "transport.put",
                transport=self.transport_name,
                volume=volume.volume_id,
                keys=len(requests),
                nbytes=nbytes,
            ):
                if self.requires_handshake and "put" in self.handshake_ops:
                    await self._perform_handshake(volume, requests, op="put")
                await self._pre_put_hook(volume, requests)
                metas = [r.meta_only() for r in requests]
                put = volume.actor.put
                reply = await put.with_timeout(
                    transfer_timeout(put._effective_timeout(), nbytes)
                ).call_one(self, metas)
                if isinstance(reply, dict) and "write_gens" in reply:
                    self.write_gens = reply["write_gens"]
                    reply = reply["reply"]
                self._handle_put_reply(volume, reply, requests)
                self._post_request_success(volume)
            _OPS.inc(transport=self.transport_name, op="put")
            _BYTES.inc(nbytes, transport=self.transport_name, op="put")
            dur = time.perf_counter() - t0
            _OP_SECONDS.observe(
                dur, transport=self.transport_name, op="put"
            )
            # Stage attribution: this lifecycle (handshake -> frames/RPC ->
            # reply) IS the wire leg of a put; replicated puts record one
            # segment per replica, so the stage total carries the real
            # aggregate wire time.
            obs_timeline.observe_stage("put", "transport", dur)
            # Traffic ledger + flight recorder (decision telemetry): the
            # client side of every put knows BOTH endpoints, so this is the
            # count-once choke point the traffic matrix is built from.
            # The enabled check lives HERE (not just inside record) so a
            # disabled ledger skips even the per-key items build.
            if obs_ledger.ledger().enabled:
                obs_ledger.record(
                    self.transport_name,
                    obs_ledger.EGRESS,
                    nbytes,
                    peer_host=volume.hostname or "",
                    volume=volume.volume_id,
                    items=[(r.key, r.nbytes) for r in requests],
                )
            obs_recorder.record(
                "transfer",
                f"put/{self.transport_name}",
                volume=volume.volume_id,
                keys=len(requests),
                nbytes=nbytes,
            )
        except BaseException as exc:
            _ERRORS.inc(transport=self.transport_name, op="put")
            obs_recorder.record(
                "error",
                f"put/{self.transport_name}",
                volume=volume.volume_id,
                error=f"{type(exc).__name__}: {exc}"[:200],
            )
            raise
        finally:
            self.drop()

    async def get_from_storage_volume(
        self, volume: "StorageVolumeRef", requests: list[Request]
    ) -> list[np.ndarray]:
        t0 = time.perf_counter()
        try:
            with tracing.span(
                "transport.get",
                transport=self.transport_name,
                volume=volume.volume_id,
                keys=len(requests),
            ) as sp:
                if self.requires_handshake and "get" in self.handshake_ops:
                    await self._perform_handshake(volume, requests, op="get")
                await self._pre_get_hook(volume, requests)
                metas = [r.meta_only() for r in requests]
                nbytes = sum(
                    m.tensor_meta.nbytes for m in metas if m.tensor_meta is not None
                )
                sp.set(nbytes=nbytes)
                get = volume.actor.get
                remote = await get.with_timeout(
                    transfer_timeout(get._effective_timeout(), nbytes)
                ).call_one(self, metas)
                results = await maybe_await(
                    self._handle_storage_volume_response(volume, remote, requests)
                )
                self._post_request_success(volume)
            _OPS.inc(transport=self.transport_name, op="get")
            _BYTES.inc(nbytes, transport=self.transport_name, op="get")
            dur = time.perf_counter() - t0
            _OP_SECONDS.observe(
                dur, transport=self.transport_name, op="get"
            )
            obs_timeline.observe_stage("get", "transport", dur)
            if obs_ledger.ledger().enabled:
                obs_ledger.record(
                    self.transport_name,
                    obs_ledger.INGRESS,
                    nbytes,
                    peer_host=volume.hostname or "",
                    volume=volume.volume_id,
                    items=[
                        (
                            m.key,
                            m.tensor_meta.nbytes
                            if m.tensor_meta is not None
                            else 0,
                        )
                        for m in metas
                    ],
                )
            obs_recorder.record(
                "transfer",
                f"get/{self.transport_name}",
                volume=volume.volume_id,
                keys=len(requests),
                nbytes=nbytes,
            )
            return results
        except BaseException as exc:
            _ERRORS.inc(transport=self.transport_name, op="get")
            obs_recorder.record(
                "error",
                f"get/{self.transport_name}",
                volume=volume.volume_id,
                error=f"{type(exc).__name__}: {exc}"[:200],
            )
            raise
        finally:
            self.drop()

    async def _perform_handshake(
        self, volume: "StorageVolumeRef", requests: list[Request], op: str
    ) -> None:
        self._pre_handshake(volume, requests, op)
        metas = [r.meta_only() for r in requests]
        reply = await volume.actor.handshake.call_one(self, metas, op)
        # May be a coroutine: the SHM buffer lands its post-handshake
        # segment copies through the overlap pool instead of serially on
        # the event loop thread.
        await maybe_await(self._post_handshake(volume, requests, reply, op))

    # ---- hooks (client) --------------------------------------------------

    def _pre_handshake(self, volume, requests, op) -> None:  # noqa: B027
        pass

    def _post_handshake(self, volume, requests, reply, op) -> None:  # noqa: B027
        pass

    async def _pre_put_hook(self, volume, requests) -> None:  # noqa: B027
        pass

    async def _pre_get_hook(self, volume, requests) -> None:  # noqa: B027
        pass

    @abstractmethod
    def _handle_storage_volume_response(
        self, volume, remote: "TransportBuffer", requests: list[Request]
    ) -> list[np.ndarray]:
        """Land fetched data: into destination views when attached, else
        return fresh arrays, in request order."""

    def _handle_put_reply(self, volume, reply, requests) -> None:  # noqa: B027
        """Process the server's (small, picklable) put reply — e.g. segment
        renames a client cache must adopt. ``reply`` is ``put_reply()``'s
        return value from the server-side buffer instance."""

    def _post_request_success(self, volume) -> None:  # noqa: B027
        """Promote any handshake-scoped resources to the reusable cache —
        only reached on success, so failed requests cannot poison caches
        (reference invariant 5, SURVEY §2.2)."""

    def drop(self) -> None:  # noqa: B027
        """Release pinned/staged resources; safe to call multiple times."""

    # ---- hooks (server side, run inside the storage-volume process) ------

    def recv_handshake(
        self, ctx: TransportContext, metas: list[Request], existing: dict, op: str
    ) -> Any:
        """Server-side handshake step; returns a (picklable) reply. May be a
        coroutine (socket-backed transports await IO inside the volume's
        event loop)."""
        return None

    @abstractmethod
    def handle_put_request(
        self, ctx: TransportContext, metas: list[Request], existing: dict[str, Any]
    ) -> dict[int, np.ndarray]:
        """Materialize incoming data server-side: returns {request_index:
        host array} for the store to keep (may be a coroutine). ``existing``
        maps request index -> previously stored array for in-place reuse
        (invariant 6)."""

    def put_reply(self):
        """Small picklable reply returned to the client after a put lands
        (rides the put RPC response; must never carry tensor bytes)."""
        return None

    @abstractmethod
    def handle_get_request(
        self, ctx: TransportContext, metas: list[Request], entries: list[Any]
    ) -> None:
        """Load outgoing data into this buffer server-side (may be a
        coroutine). ``entries`` are the store's arrays/objects in request
        order."""
