"""Device-path weight sync: the ICI rung.

TPU-native answer to the reference's one-sided RDMA device reads
(/root/reference/torchstore/transport/monarch_rdma.py:158-219, ibverbs reads
of source GPU memory). TPUs expose no raw one-sided read primitive to user
code, but the XLA runtime does: ``jax.experimental.transfer`` starts a
per-process *transfer server* attached to the local backend, and a remote
process can pull staged device arrays directly — device-to-device over the
accelerator fabric (ICI within a pod, DCN across), never touching host
staging buffers. This module wraps that engine as the store's device
transport rung, gated by ``StoreConfig.ici_enabled``.

Protocol (one-shot staging is the engine's contract — each ``await_pull``
uuid serves exactly ONE ``pull``):

    source: engine.ensure_server() -> address; publish handles via the store
    dest:   asks the source to stage a fresh generation (tiny TCP control
            op, see direct_weight_sync) -> uuid
    dest:   conn.pull(uuid, specs_with_source_sharding) -> device arrays
    dest:   reshards locally (jax.device_put) — XLA moves shards over ICI

Because staging happens per pull request, a dest always receives the
source's CURRENT weights with zero host copies on either side.

Scope: single-controller sources stage whole (mesh-sharded) arrays;
multi-rank SPMD sources each run their own transfer server and publish
per-shard entries the dest merges (direct_weight_sync._device_parts).
Sharding descriptors reconstruct by GLOBAL device id, so source and dest
must share a jax world (jax.distributed) or have coinciding device ids
(same-topology slices). When they don't, the dest falls back to the
source-side host-staging control op (_STAGE_HOST) and reads over TCP.

Shardings cannot be pickled across processes (they hold live Device
objects); ``ShardingDescriptor`` round-trips NamedSharding /
SingleDeviceSharding by mesh shape + axis names + device ids, reconstructed
over the destination process's view of the same global device set.
"""

from __future__ import annotations

import os
import uuid as uuid_mod
from dataclasses import dataclass
from typing import Any, Optional

from torchstore_tpu.logging import get_logger
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import tracing

logger = get_logger("torchstore_tpu.transport.ici")

_STAGED = obs_metrics.counter(
    "ts_device_staged_total", "Device arrays staged for one-shot remote pulls"
)
_PULL_OPS = obs_metrics.counter(
    "ts_device_pull_ops_total", "Device-to-device pulls through the ICI rung"
)
# Same instruments the host transports feed (transport/buffers.py) — the
# ICI rung reports under transport="ici" so one query covers every rung.
_OPS = obs_metrics.counter(
    "ts_transport_ops_total", "Data-plane transfers by transport and op"
)
_PULL_BYTES = obs_metrics.counter(
    "ts_transport_bytes_total",
    "Logical payload bytes handed to / received from each transport",
)
_OP_SECONDS = obs_metrics.histogram(
    "ts_transport_op_seconds", "Wall time of one transfer by transport and op"
)


def is_available() -> bool:
    """True when this jax build ships the transfer engine."""
    try:
        from jax.experimental import transfer  # noqa: F401

        return hasattr(transfer, "start_transfer_server")
    except Exception:  # pragma: no cover - jax without the extension
        return False


# --------------------------------------------------------------------------
# sharding descriptors (picklable)
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class ShardingDescriptor:
    """Picklable description of a NamedSharding/SingleDeviceSharding."""

    kind: str  # "named" | "single"
    mesh_shape: tuple[int, ...] = ()
    axis_names: tuple[str, ...] = ()
    device_ids: tuple[int, ...] = ()  # mesh devices flattened, or [device]
    spec: tuple = ()  # PartitionSpec entries (None | str | tuple[str, ...])
    memory_kind: Optional[str] = None

    @classmethod
    def of(cls, sharding) -> "ShardingDescriptor":
        import jax

        if isinstance(sharding, jax.sharding.SingleDeviceSharding):
            (dev,) = sharding.device_set
            return cls(kind="single", device_ids=(dev.id,))
        if isinstance(sharding, jax.sharding.NamedSharding):
            mesh = sharding.mesh
            spec = tuple(
                tuple(p) if isinstance(p, (list, tuple)) else p
                for p in sharding.spec
            )
            return cls(
                kind="named",
                mesh_shape=tuple(mesh.devices.shape),
                axis_names=tuple(mesh.axis_names),
                device_ids=tuple(d.id for d in mesh.devices.flat),
                spec=spec,
                memory_kind=sharding.memory_kind,
            )
        raise TypeError(f"unsupported sharding type {type(sharding).__name__}")

    def build(self):
        """Reconstruct the sharding over THIS process's devices."""
        import numpy as np

        import jax

        by_id = {d.id: d for d in jax.devices()}
        try:
            devices = [by_id[i] for i in self.device_ids]
        except KeyError as exc:
            raise ValueError(
                f"device id {exc} in sharding descriptor is not visible in "
                "this process (device-path sync requires a shared jax world)"
            ) from None
        if self.kind == "single":
            return jax.sharding.SingleDeviceSharding(devices[0])
        mesh = jax.sharding.Mesh(
            np.array(devices, dtype=object).reshape(self.mesh_shape),
            self.axis_names,
        )
        spec = jax.sharding.PartitionSpec(*self.spec)
        if self.memory_kind is not None:
            return jax.sharding.NamedSharding(
                mesh, spec, memory_kind=self.memory_kind
            )
        return jax.sharding.NamedSharding(mesh, spec)


@dataclass(frozen=True)
class DeviceSpec:
    """Shape/dtype/placement of one staged array (pull-spec ingredients)."""

    shape: tuple[int, ...]
    dtype: str
    sharding: ShardingDescriptor

    @classmethod
    def of(cls, arr) -> "DeviceSpec":
        return cls(
            shape=tuple(arr.shape),
            dtype=str(arr.dtype),
            sharding=ShardingDescriptor.of(arr.sharding),
        )

    def to_jax(self):
        import jax
        import jax.numpy as jnp

        return jax.ShapeDtypeStruct(
            self.shape, jnp.dtype(self.dtype), sharding=self.sharding.build()
        )


# --------------------------------------------------------------------------
# the engine (per-process singleton)
# --------------------------------------------------------------------------


class DeviceTransferEngine:
    """Owns this process's transfer server + cached peer connections."""

    _instance: Optional["DeviceTransferEngine"] = None

    def __init__(self) -> None:
        self._server = None
        self._conns: dict[str, Any] = {}
        # uuids must be unique per (source process, staging); random base +
        # counter keeps restarted sources from colliding with stale pulls.
        self._next_uuid = uuid_mod.uuid4().int & ((1 << 62) - 1)

    @classmethod
    def get(cls) -> "DeviceTransferEngine":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def ensure_server(self, client=None) -> str:
        """Start (once) the transfer server on the local backend; returns its
        reachable address."""
        if self._server is None:
            import jax
            from jax.experimental import transfer

            if client is None:
                client = jax.devices()[0].client
            bind = os.environ.get("TORCHSTORE_TPU_BIND_HOST", "127.0.0.1")
            if bind in ("0.0.0.0", "::"):
                bind = "[::]" if bind == "::" else "0.0.0.0"
            self._server = transfer.start_transfer_server(
                client, f"{bind}:0", [f"{bind}:0"]
            )
            logger.info("device transfer server at %s", self._server.address())
        return self._server.address()

    def stage(self, arrays: list) -> int:
        """Schedule ``arrays`` (device jax.Arrays) for ONE remote pull;
        returns the uuid the peer must pull with."""
        self.ensure_server()
        self._next_uuid += 1
        uid = self._next_uuid
        self._server.await_pull(uid, list(arrays))
        _STAGED.inc(len(arrays))
        return uid

    def pull(self, address: str, uid: int, specs: list[DeviceSpec]) -> list:
        """Pull staged arrays from a peer server, landing them with the
        source's sharding (reshard afterwards with jax.device_put)."""
        return self.pull_built(address, uid, [s.to_jax() for s in specs])

    def pull_built(self, address: str, uid: int, jax_specs: list) -> list:
        """Pull with pre-built jax ShapeDtypeStructs (callers that validate
        sharding reconstruction up front reuse the same objects here)."""
        self.ensure_server()
        conn = self._conns.get(address)
        if conn is None:
            conn = self._server.connect(address)
            self._conns[address] = conn
        import time

        import numpy as np

        nbytes = sum(
            int(np.prod(s.shape)) * s.dtype.itemsize for s in jax_specs
        )
        t0 = time.perf_counter()
        with tracing.span(
            "transport.pull_device",
            transport="ici",
            peer=address,
            arrays=len(jax_specs),
            nbytes=nbytes,
        ):
            out = conn.pull(uid, jax_specs)
        _PULL_OPS.inc()
        _OPS.inc(transport="ici", op="get")
        _PULL_BYTES.inc(nbytes, transport="ici", op="get")
        _OP_SECONDS.observe(time.perf_counter() - t0, transport="ici", op="get")
        return out

    def reset(self) -> None:
        """Drop connections (tests); the server itself is process-lifetime."""
        self._conns.clear()


def finalize_stamped(uploaded, recheck) -> bool:
    """Settle a device upload that consumed a BORROWED stamped SHM view
    (``shared_memory.stamped_read(..., borrow=True)``): block until the
    device has fully read the mapped bytes, then re-check the seqlock.
    True -> the upload holds one consistent generation; False -> a landing
    raced the read and the arrays may mix generations — the caller MUST
    discard them and fall back to the RPC path."""
    import jax

    jax.block_until_ready(uploaded)
    return bool(recheck())


def upload_stamped(view, recheck, dtype=None, sharding=None):
    """One-sided host->device upload: hand the borrowed stamped segment
    view straight to the device runtime (``jax.device_put`` reads the
    mmapped bytes itself — no intermediate host staging copy, the staging
    buffer IS the stamped segment), then :func:`finalize_stamped`. With an
    ICI-capable backend the very same call pulls over the accelerator
    fabric; on host-only backends it is still the zero-extra-copy path.
    Returns the device array, or None when the upload tore (the caller
    falls back to the RPC path, which serves a consistent snapshot)."""
    import jax

    import numpy as np

    devices = (
        list(sharding.device_set) if sharding is not None else jax.devices()
    )
    if all(d.platform == "cpu" for d in devices):
        # Host-only backend: device_put of an aligned C-contiguous host
        # array may SHARE the buffer instead of copying — the "device"
        # array would alias recyclable segment memory and mutate under
        # the caller after a later landing. Materialize a private copy
        # first (the cost real accelerators pay in the H2D DMA anyway);
        # the recheck below still validates it was not torn.
        view = np.asarray(view).copy()
    out = (
        jax.device_put(view, sharding)
        if sharding is not None
        else jax.device_put(view)
    )
    if dtype is not None and str(out.dtype) != str(dtype):
        out = out.astype(dtype)  # on-device; depends on the H2D transfer
    if not finalize_stamped(out, recheck):
        from torchstore_tpu.transport.shared_memory import ONE_SIDED_TORN

        ONE_SIDED_TORN.inc(transport="device")
        return None
    return out


def prewarm_engine() -> Optional[str]:
    """Cold-start provisioning for the ICI rung: start this process's
    transfer server BEFORE the first publish/pull needs it (server startup
    binds a listener and initializes the backend's transfer machinery — paid
    once, and without prewarm it lands on iteration 0's critical path).
    Returns the server address, or None when this jax build has no transfer
    engine. Staging itself stays per-pull (the engine's one-shot contract);
    dest-side staging buffers are the pull targets the caller provides."""
    if not is_available():
        return None
    with tracing.span("provision.device_server"):
        return DeviceTransferEngine.get().ensure_server()
