"""RPC transport: payloads ride the actor-RPC serialization itself.

Universal fallback, equivalent of the reference's MonarchRPC transport
(/root/reference/torchstore/transport/monarch_rpc.py:26-87). Unlike the
reference (whose codec copies tensors into the pickle stream), our runtime
frames numpy arrays out-of-band (pickle protocol 5), so even this fallback
moves tensor bytes with a single copy into the socket.

Client-held in-place destination views are stripped on pickle and re-attached
from the original requests when the response lands.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from torchstore_tpu.transport.buffers import TransportBuffer, TransportContext
from torchstore_tpu.native import copy_into, fast_copy
from torchstore_tpu.transport.types import Request


class RPCTransportBuffer(TransportBuffer):
    transport_name = "rpc"
    requires_handshake = False
    supports_inplace = True
    requires_contiguous_inplace = False
    supports_batch_puts = True
    supports_batch_gets = True

    def __init__(self, inproc_copy: bool = False) -> None:
        # index -> payload. On put: filled client-side (pre_put) and read
        # server-side. On get: filled server-side and read client-side.
        self.tensors: dict[int, np.ndarray] = {}
        self.objects: dict[int, Any] = {}
        # Colocated volumes dispatch endpoints WITHOUT serialization, so the
        # "remote" side would receive the caller's arrays by reference;
        # explicit copies restore the value semantics pickling provides.
        self.inproc_copy = inproc_copy

    # ---- client ----------------------------------------------------------

    async def _pre_put_hook(self, volume, requests: list[Request]) -> None:
        for idx, req in enumerate(requests):
            if req.is_object:
                self.objects[idx] = req.objects
            else:
                arr = np.ascontiguousarray(req.tensor_val)
                self.tensors[idx] = arr

    def _handle_storage_volume_response(
        self, volume, remote: "RPCTransportBuffer", requests: list[Request]
    ) -> list[Any]:
        results: list[Any] = []
        for idx, req in enumerate(requests):
            if req.is_object or idx in remote.objects:
                results.append(remote.objects[idx])
                continue
            arr = remote.tensors[idx]
            if req.destination_view is not None:
                # Native landing path (multi-threaded contiguous + strided
                # row-block); raises on shape mismatch instead of
                # broadcasting stale-metadata fetches into place.
                copy_into(req.destination_view, arr)
                results.append(req.destination_view)
            else:
                results.append(arr)
        return results

    def drop(self) -> None:
        self.tensors = {}
        self.objects = {}

    # ---- server ----------------------------------------------------------

    def handle_put_request(
        self, ctx: TransportContext, metas: list[Request], existing: dict[int, Any]
    ) -> dict[int, np.ndarray]:
        out: dict[int, Any] = {}
        for idx, obj in self.objects.items():
            if self.inproc_copy:
                import copy

                obj = copy.deepcopy(obj)
            out[idx] = obj
        for idx in self.tensors:
            arr = self.tensors[idx]
            prev: Optional[np.ndarray] = existing.get(idx)
            if (
                prev is not None
                and prev.shape == arr.shape
                and prev.dtype == arr.dtype
            ):
                # In-place overwrite reuses storage so SHM/bulk clients that
                # alias the stored buffer observe the update (invariant 6).
                fast_copy(prev, arr)
                out[idx] = prev
            else:
                out[idx] = arr.copy() if self.inproc_copy else arr
        return out

    def handle_get_request(
        self, ctx: TransportContext, metas: list[Request], entries: list[Any]
    ) -> None:
        for idx, (meta, entry) in enumerate(zip(metas, entries)):
            if meta.is_object:
                if self.inproc_copy:
                    import copy

                    entry = copy.deepcopy(entry)
                self.objects[idx] = entry
            elif self.inproc_copy:
                self.tensors[idx] = np.array(entry)  # never hand out storage
            else:
                self.tensors[idx] = np.ascontiguousarray(entry)
