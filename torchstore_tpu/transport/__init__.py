from torchstore_tpu.transport.buffers import (
    TransportBuffer,
    TransportCache,
    TransportContext,
)
from torchstore_tpu.transport.factory import TransportType, create_transport_buffer
from torchstore_tpu.transport.types import Request, TensorMeta, TensorSlice

__all__ = [
    "Request",
    "TensorMeta",
    "TensorSlice",
    "TransportBuffer",
    "TransportCache",
    "TransportContext",
    "TransportType",
    "create_transport_buffer",
]
